"""apex_tpu.observability — metrics, tracing, and run reports.

The third leg of the production triangle next to ``resilience``
(survive) and ``analysis`` (lint): *observe*. TorchTitan (PAPERS.md,
arXiv:2410.06511) treats metrics/logging/profiling as a first-class
subsystem of a pre-training stack; this package is that subsystem here.

- :class:`MetricsRegistry` — thread-safe counters, gauges, and
  bounded-memory histograms with pluggable sinks
  (:class:`JsonlSink`, :class:`PrometheusTextfileSink`,
  :class:`InMemorySink`).
- :class:`StepMetrics` / :class:`StepTimer` — per-step wall time,
  tokens/s, and MFU (FLOP math shared with the benchmark harness via
  :mod:`apex_tpu.utils.flops`), plus device ``memory_stats`` gauges.
  ``ResilienceConfig(metrics=registry)`` wires the whole layer into
  :func:`apex_tpu.resilience.run_training`.
- :func:`span` / :class:`ProfilerCapture` — named scopes that also
  record host durations, and windowed ``jax.profiler`` captures
  (every-N-steps or on watchdog incident).
- :func:`build_report` / :func:`render_report` — fold a run's JSONL log
  into the report ``python -m apex_tpu.monitor`` prints.
- :class:`SLOSpec` / :func:`evaluate_slos`
  (:mod:`~apex_tpu.observability.slo`) — declared service-level
  objectives (TTFT/TPOT/latency percentiles, goodput, error budget,
  recovery time) scored from the run log; the monitor renders the
  verdict and ``python -m apex_tpu.loadtest --check`` gates on it.
- :mod:`~apex_tpu.observability.trace` — request-level span timelines:
  every serving request carries a ``trace_id``; the engine/supervisor/
  fleet stamp typed ``kind="span"`` rows whose phase durations sum to
  the request's measured latency (:func:`check_span_conservation`).
- :class:`FleetMetrics` / :class:`ReplicaRegistry`
  (:mod:`~apex_tpu.observability.fleet_metrics`) — per-replica metric
  views merged into one fleet snapshot plus the polled ``signals()``
  dict (goodput window, queue depth, p99 TTFT/TPOT, occupancy,
  per-adapter share) that feeds the autoscaler and the drift sentinel.
- :class:`FlightRecorder` (:mod:`~apex_tpu.observability.recorder`) —
  bounded ring buffers of recent telemetry attached as a registry sink;
  any incident-class event (:data:`TRIGGER_EVENTS`) dumps a
  self-contained JSON postmortem bundle rendered by
  ``python -m apex_tpu.monitor bundle``.
- :class:`DriftSentinel` / :class:`SentinelConfig`
  (:mod:`~apex_tpu.observability.sentinel`) — online EWMA + robust
  z-score drift detection over ``FleetMetrics.signals()``, emitting
  typed ``kind="anomaly"`` records with paired ``anomalies_*``
  counters (and the periodic ``kind="gauge_snapshot"`` trajectory
  feed) from the fleet tick.
"""

from apex_tpu.observability.registry import (
    HistogramSnapshot,
    MetricsRegistry,
    percentile,
)
from apex_tpu.observability.sinks import (
    InMemorySink,
    JsonlSink,
    PrometheusTextfileSink,
)
from apex_tpu.observability.step_metrics import StepMetrics, StepTimer
from apex_tpu.observability.tracing import ProfilerCapture, span
from apex_tpu.observability.report import (
    build_report,
    read_records,
    render_report,
)
from apex_tpu.observability.slo import (
    SLO_METRICS,
    SLOObjective,
    SLOReport,
    SLOSpec,
    evaluate_slos,
    measure_slo_metrics,
)
from apex_tpu.observability.trace import (
    MARK_SPANS,
    PHASE_SPANS,
    build_timelines,
    check_span_conservation,
    emit_request_spans,
    emit_span,
    format_timeline,
    new_trace_id,
)
from apex_tpu.observability.fleet_metrics import (
    FleetMetrics,
    ReplicaRegistry,
    merge_histograms,
)
from apex_tpu.observability.recorder import (
    TRIGGER_EVENTS,
    FlightRecorder,
)
from apex_tpu.observability.sentinel import (
    DriftSentinel,
    SentinelConfig,
)

__all__ = [
    "MetricsRegistry",
    "HistogramSnapshot",
    "percentile",
    "InMemorySink",
    "JsonlSink",
    "PrometheusTextfileSink",
    "StepMetrics",
    "StepTimer",
    "ProfilerCapture",
    "span",
    "build_report",
    "read_records",
    "render_report",
    "SLO_METRICS",
    "SLOSpec",
    "SLOObjective",
    "SLOReport",
    "evaluate_slos",
    "measure_slo_metrics",
    "PHASE_SPANS",
    "MARK_SPANS",
    "new_trace_id",
    "emit_request_spans",
    "emit_span",
    "build_timelines",
    "format_timeline",
    "check_span_conservation",
    "FleetMetrics",
    "ReplicaRegistry",
    "merge_histograms",
    "FlightRecorder",
    "TRIGGER_EVENTS",
    "DriftSentinel",
    "SentinelConfig",
]
