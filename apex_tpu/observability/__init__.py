"""apex_tpu.observability — metrics, tracing, and run reports.

The third leg of the production triangle next to ``resilience``
(survive) and ``analysis`` (lint): *observe*. TorchTitan (PAPERS.md,
arXiv:2410.06511) treats metrics/logging/profiling as a first-class
subsystem of a pre-training stack; this package is that subsystem here.

- :class:`MetricsRegistry` — thread-safe counters, gauges, and
  bounded-memory histograms with pluggable sinks
  (:class:`JsonlSink`, :class:`PrometheusTextfileSink`,
  :class:`InMemorySink`).
- :class:`StepMetrics` / :class:`StepTimer` — per-step wall time,
  tokens/s, and MFU (FLOP math shared with the benchmark harness via
  :mod:`apex_tpu.utils.flops`), plus device ``memory_stats`` gauges.
  ``ResilienceConfig(metrics=registry)`` wires the whole layer into
  :func:`apex_tpu.resilience.run_training`.
- :func:`span` / :class:`ProfilerCapture` — named scopes that also
  record host durations, and windowed ``jax.profiler`` captures
  (every-N-steps or on watchdog incident).
- :func:`build_report` / :func:`render_report` — fold a run's JSONL log
  into the report ``python -m apex_tpu.monitor`` prints.
- :class:`SLOSpec` / :func:`evaluate_slos`
  (:mod:`~apex_tpu.observability.slo`) — declared service-level
  objectives (TTFT/TPOT/latency percentiles, goodput, error budget,
  recovery time) scored from the run log; the monitor renders the
  verdict and ``python -m apex_tpu.loadtest --check`` gates on it.
"""

from apex_tpu.observability.registry import (
    HistogramSnapshot,
    MetricsRegistry,
    percentile,
)
from apex_tpu.observability.sinks import (
    InMemorySink,
    JsonlSink,
    PrometheusTextfileSink,
)
from apex_tpu.observability.step_metrics import StepMetrics, StepTimer
from apex_tpu.observability.tracing import ProfilerCapture, span
from apex_tpu.observability.report import (
    build_report,
    read_records,
    render_report,
)
from apex_tpu.observability.slo import (
    SLO_METRICS,
    SLOObjective,
    SLOReport,
    SLOSpec,
    evaluate_slos,
    measure_slo_metrics,
)

__all__ = [
    "MetricsRegistry",
    "HistogramSnapshot",
    "percentile",
    "InMemorySink",
    "JsonlSink",
    "PrometheusTextfileSink",
    "StepMetrics",
    "StepTimer",
    "ProfilerCapture",
    "span",
    "build_report",
    "read_records",
    "render_report",
    "SLO_METRICS",
    "SLOSpec",
    "SLOObjective",
    "SLOReport",
    "evaluate_slos",
    "measure_slo_metrics",
]
