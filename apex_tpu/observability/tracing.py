"""Span tracing + on-demand profiler capture.

Two pieces on top of :mod:`apex_tpu.utils.profiling`:

- :func:`span` — a named scope that *also* records its host-side wall
  duration into a registry histogram (``span/<name>_s``). The scope name
  still lands in XLA HLO metadata (it is ``jax.named_scope`` underneath),
  so one annotation shows up both in the profiler timeline and in the
  run's own metrics.
- :class:`ProfilerCapture` — windowed ``jax.profiler`` trace capture the
  resilience driver can drive: start every N steps and stop
  ``capture_steps`` later, and/or start on a watchdog incident — so when
  a run goes sideways there is a trace of the bad window without having
  profiled the whole run.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from apex_tpu.utils.logging import get_logger, log_event
from apex_tpu.utils.profiling import nvtx_range, profiler_start, profiler_stop

__all__ = ["span", "ProfilerCapture"]


def span(name: str, registry):
    """``with span("fwd", reg):`` — :func:`~apex_tpu.utils.profiling.
    nvtx_range` with the registry wired in: the enclosed host wall time
    is observed into the ``span/<name>_s`` histogram."""
    return nvtx_range(name, registry=registry)


class ProfilerCapture:
    """Start/stop ``jax.profiler`` traces on a schedule or on demand.

    The driver calls :meth:`on_step` after every completed step and
    :meth:`on_incident` when the watchdog fires; each capture lands in
    its own subdirectory ``<log_dir>/step<N>_<reason>`` (TensorBoard-
    readable).

    Args:
      log_dir: root directory for capture subdirectories.
      every_n_steps: start a capture when ``step % N == 0`` (None: only
        on demand/incident).
      capture_steps: steps per capture window before auto-stop.
      capture_on_incident: start a capture from :meth:`on_incident`.
      max_captures: total capture budget for the run (trace files are
        big; an unhealthy run must not fill the disk).
      registry: optional — capture start/stop emit registry events and a
        ``profiler_captures`` counter.
      start_fn / stop_fn: injectable trace hooks (default
        ``jax.profiler`` via :mod:`apex_tpu.utils.profiling`); tests
        substitute stubs.
    """

    def __init__(self, log_dir: str, *, every_n_steps: Optional[int] = None,
                 capture_steps: int = 2, capture_on_incident: bool = True,
                 max_captures: int = 4, registry=None,
                 start_fn: Callable[[str], None] = profiler_start,
                 stop_fn: Callable[[], None] = profiler_stop,
                 logger=None):
        self.log_dir = os.fspath(log_dir)
        self.every_n_steps = every_n_steps
        self.capture_steps = int(capture_steps)
        self.capture_on_incident = capture_on_incident
        self.max_captures = int(max_captures)
        self.registry = registry
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self._log = logger or get_logger(__name__)
        self.captures = 0
        self.active = False
        self._stop_at: Optional[int] = None

    def on_step(self, step: int) -> None:
        """Advance the schedule at completed step ``step`` (1-based)."""
        if self.active:
            if self._stop_at is not None and step >= self._stop_at:
                self.stop(step)
        elif (self.every_n_steps
                and step % self.every_n_steps == 0):
            self.start(step, reason="interval")

    def on_incident(self, reason: str, step: int) -> None:
        """Watchdog hook: capture the aftermath of an incident."""
        if self.capture_on_incident and not self.active:
            self.start(step, reason=reason)

    def start(self, step: int, reason: str = "manual") -> bool:
        """Begin a capture window; returns False when already active or
        the capture budget is spent."""
        if self.active or self.captures >= self.max_captures:
            return False
        target = os.path.join(self.log_dir, f"step{step}_{reason}")
        self._start_fn(target)
        self.active = True
        self.captures += 1
        self._stop_at = step + self.capture_steps
        log_event(self._log, "profiler_capture_start", step=step,
                  reason=reason, dir=target, level="info")
        if self.registry is not None:
            self.registry.inc("profiler_captures")
            self.registry.event("profiler_capture_start", step=step,
                                reason=reason, dir=target)
        return True

    def stop(self, step: Optional[int] = None) -> None:
        if not self.active:
            return
        self._stop_fn()
        self.active = False
        self._stop_at = None
        log_event(self._log, "profiler_capture_stop",
                  step=("?" if step is None else step), level="info")
        if self.registry is not None:
            self.registry.event("profiler_capture_stop",
                                step=(-1 if step is None else int(step)))
