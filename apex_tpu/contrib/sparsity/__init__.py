from apex_tpu.contrib.sparsity.asp import ASP, compute_sparse_mask_2to4
from apex_tpu.contrib.sparsity.permutation import (
    invert_permutation,
    mask_efficacy,
    permute_columns,
    search_for_good_permutation,
)

__all__ = [
    "ASP",
    "compute_sparse_mask_2to4",
    "invert_permutation",
    "mask_efficacy",
    "permute_columns",
    "search_for_good_permutation",
]
