from apex_tpu.contrib.sparsity.asp import ASP, compute_sparse_mask_2to4

__all__ = ["ASP", "compute_sparse_mask_2to4"]
