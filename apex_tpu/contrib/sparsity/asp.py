"""ASP — 2:4 structured sparsity.

Counterpart of ``apex/contrib/sparsity/asp.py:28-...`` (+
``permutation_lib.py``, ``permutation_search_kernels.cu``): maintain 2:4
(n:m) magnitude masks on whitelisted layers and re-apply them after each
optimizer step so training proceeds on the pruned support.

TPU reality check, stated rather than hidden: TPUs have **no sparse tensor
cores**, so 2:4 masks buy no TPU speedup — the capability exists for
training models destined for sparse inference elsewhere, and for accuracy
experiments. The channel-permutation search (a CUDA kernel whose only job
is preserving more magnitude under the mask) lives in
:mod:`apex_tpu.contrib.sparsity.permutation` — a vectorized JAX hill-climb
over column swaps with the reference's efficacy objective.

Functional API: masks are a pytree like the params; ``apply_masks`` is the
in-step analog of the reference's optimizer-step mask hook.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

__all__ = ["ASP", "compute_sparse_mask_2to4"]


def compute_sparse_mask_2to4(w: jax.Array, *, n: int = 2,
                             m: int = 4) -> jax.Array:
    """Boolean mask keeping the ``n`` largest-magnitude entries of every
    group of ``m`` along the last dim (reference default ``m4n2_1d``)."""
    if w.shape[-1] % m:
        raise ValueError(f"last dim ({w.shape[-1]}) not divisible by {m}")
    g = w.reshape(*w.shape[:-1], w.shape[-1] // m, m)
    # rank within each group by |w|; keep the top n
    order = jnp.argsort(jnp.abs(g), axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    mask = ranks >= (m - n)
    return mask.reshape(w.shape)


class ASP:
    """Reference workflow (``asp.py`` docstring): ``init_model_for_pruning``
    selects prunable leaves, ``compute_sparse_masks`` builds masks from
    current magnitudes, and the mask application runs after every optimizer
    step (``init_optimizer_for_pruning`` hook in torch; here
    :meth:`apply_masks` composes into the train step)."""

    def __init__(self, *, mask_calculator: str = "m4n2_1d",
                 whitelist: Optional[Callable[[str, jax.Array], bool]] = None):
        if not mask_calculator.startswith("m4n2"):
            raise NotImplementedError(
                "only the default m4n2 (2:4) calculator is provided")
        self._whitelist = whitelist or (
            lambda path, leaf: leaf.ndim == 2
            and leaf.shape[-1] % 4 == 0 and min(leaf.shape) >= 32)
        self._masks: Optional[Any] = None

    def init_model_for_pruning(self, params: Any) -> Any:
        """Returns the prunable-leaf selection (True where masked)."""
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        selected = {jax.tree_util.keystr(k): self._whitelist(
            jax.tree_util.keystr(k), v) for k, v in flat}
        self._selection = selected
        return selected

    def compute_sparse_masks(self, params: Any) -> Any:
        """Mask pytree: 2:4 masks on selected leaves, all-True elsewhere."""
        if not hasattr(self, "_selection"):
            self.init_model_for_pruning(params)

        def one(path, leaf):
            if self._selection.get(jax.tree_util.keystr(path), False):
                return compute_sparse_mask_2to4(leaf)
            return jnp.ones(leaf.shape, bool)

        self._masks = jax.tree_util.tree_map_with_path(one, params)
        return self._masks

    def apply_masks(self, params: Any, masks: Optional[Any] = None) -> Any:
        masks = masks if masks is not None else self._masks
        if masks is None:
            raise RuntimeError("call compute_sparse_masks first")
        return jax.tree.map(lambda p, m: p * m.astype(p.dtype), params, masks)

    @staticmethod
    def sparsity(params: Any, masks: Any) -> float:
        total = sum(m.size for m in jax.tree.leaves(masks))
        kept = sum(int(jnp.sum(m)) for m in jax.tree.leaves(masks))
        return 1.0 - kept / total
