"""Channel-permutation search for 2:4 sparsity.

Counterpart of the reference's ``apex/contrib/sparsity/permutation_lib.py``
(1.6k LoC host logic) + ``permutation_search_kernels.cu``: find a permutation
of a weight matrix's **input channels** (columns) that maximizes the
magnitude preserved when the 2:4 mask is applied afterwards. The reference
searches with CUDA-accelerated group-exhaustive swaps; this is an offline
prep step, so here it is a vectorized JAX hill-climb — a jitted scorer
rates candidate column swaps in batched chunks (the MXU-friendly
formulation), apply the best, repeat until no swap helps.

Efficacy metric (identical to the reference's): the sum of the ``n``
largest ``|w|`` in every group of ``m`` consecutive columns, summed over
rows — i.e. exactly the magnitude the 2:4 mask keeps.

Cross-layer bookkeeping (the reference propagates one permutation through
residual/conv chains) is the caller's job. With this module's gather
convention (``permute_columns(w2, perm) == w2[:, perm]``), the upstream
producer must have the *same* ``perm`` applied to its output rows —
``w1[perm, :]`` — for the composed function to be unchanged
(``w2[:, perm] @ (w1[perm, :] @ x) == w2 @ (w1 @ x)``).
:func:`invert_permutation` is for undoing a permutation (mapping permuted
positions back to originals), e.g. when exporting weights to a consumer
that expects the original channel order.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "mask_efficacy",
    "search_for_good_permutation",
    "permute_columns",
    "invert_permutation",
]


def _group_efficacy(absw: jax.Array, n: int, m: int) -> jax.Array:
    """Magnitude kept by an n:m mask on ``absw`` [rows, cols]: per group of
    ``m`` columns keep the ``n`` largest per row."""
    r, c = absw.shape
    g = absw.reshape(r, c // m, m)
    top = jax.lax.top_k(g, n)[0]
    return jnp.sum(top)


def mask_efficacy(w: jax.Array, *, n: int = 2, m: int = 4) -> jax.Array:
    """Fraction of total magnitude the n:m mask preserves on ``w``."""
    absw = jnp.abs(w.astype(jnp.float32))
    return _group_efficacy(absw, n, m) / jnp.maximum(jnp.sum(absw), 1e-30)


@functools.partial(jax.jit, static_argnums=(5, 6))
def _score_pairs(g, G, H, A, B, n, m):
    """Gain of swapping column (G, A) with (H, B) for a chunk of candidate
    pairs — only the two touched groups change efficacy, so each pair costs
    one [rows, 2, m] top-k, batched over the chunk."""
    base = jnp.sum(jax.lax.top_k(g, n)[0], axis=(0, 2))      # [ng]
    colG = g[:, G, :]                                        # [r, P, m]
    colH = g[:, H, :]
    idx = jnp.arange(G.shape[0])
    valGA = colG[:, idx, A]                                  # [r, P]
    valHB = colH[:, idx, B]
    swapG = colG.at[:, idx, A].set(valHB)
    swapH = colH.at[:, idx, B].set(valGA)
    effG = jnp.sum(jax.lax.top_k(swapG.transpose(1, 0, 2), n)[0], axis=(1, 2))
    effH = jnp.sum(jax.lax.top_k(swapH.transpose(1, 0, 2), n)[0], axis=(1, 2))
    scale = base[G] + base[H]
    return (effG + effH) - scale, scale                      # [P], [P]


def _best_swap(absw: np.ndarray, n: int, m: int,
               chunk: int = 16384) -> Tuple[float, float, int, int]:
    """Score every cross-group column swap (i, j); return
    ``(gain, scale, i, j)`` where ``scale`` is the winning pair's combined
    base efficacy (the magnitude the fp32 gain was computed at).

    Candidate pairs are scored in fixed-size chunks so wide layers (C up to
    several thousand) stay within memory: peak is O(rows * chunk * m)."""
    r, c = absw.shape
    ng = c // m
    g = jnp.asarray(absw).reshape(r, ng, m)
    G, H, A, B = np.meshgrid(np.arange(ng), np.arange(ng),
                             np.arange(m), np.arange(m), indexing="ij")
    sel = (G < H).reshape(-1)
    G, H, A, B = (x.reshape(-1)[sel] for x in (G, H, A, B))
    best_gain, best_scale, best_i, best_j = -np.inf, 0.0, 0, 0
    for s in range(0, G.size, chunk):
        e = min(s + chunk, G.size)
        gains, scales = _score_pairs(
            g, jnp.asarray(G[s:e]), jnp.asarray(H[s:e]),
            jnp.asarray(A[s:e]), jnp.asarray(B[s:e]), n, m)
        gains = np.asarray(gains)
        k = int(np.argmax(gains))
        if gains[k] > best_gain:
            best_gain = float(gains[k])
            best_scale = float(np.asarray(scales)[k])
            best_i = int(G[s + k] * m + A[s + k])
            best_j = int(H[s + k] * m + B[s + k])
    return best_gain, best_scale, best_i, best_j


def search_for_good_permutation(
    w: jax.Array,
    *,
    n: int = 2,
    m: int = 4,
    max_iterations: int = 100,
    min_gain: float = 1e-6,
) -> np.ndarray:
    """Greedy column-swap hill-climb; returns the permutation (int array
    ``perm`` such that ``w[:, perm]`` has maximal retained magnitude).

    Matches the reference's search objective
    (``permutation_lib.py`` / ``permutation_search_kernels.cu``); each
    iteration applies the single best swap among all O((C/m·m)²) candidates.
    """
    if w.ndim != 2:
        raise ValueError("permutation search expects a 2-D weight [out, in]")
    if w.shape[1] % m:
        raise ValueError(f"in-features ({w.shape[1]}) not divisible by {m}")
    absw = np.abs(np.asarray(w, np.float32))
    perm = np.arange(w.shape[1])
    for _ in range(max_iterations):
        gain, scale, i, j = _best_swap(absw, n, m)
        # the fp32 chunked scoring rounds at the scale of the pair's
        # efficacy sums: a gain below that noise floor is a tie (e.g. an
        # already-optimal matrix), not an improvement — swapping on it
        # would churn the permutation without raising retained magnitude
        noise = 32.0 * np.finfo(np.float32).eps * max(scale, 1.0)
        if gain <= max(min_gain, noise):
            break
        absw[:, [i, j]] = absw[:, [j, i]]
        perm[[i, j]] = perm[[j, i]]
    return perm


def permute_columns(w: jax.Array, perm) -> jax.Array:
    """Apply a found permutation to the input-channel dim."""
    return w[:, jnp.asarray(perm)]


def invert_permutation(perm) -> np.ndarray:
    """Inverse permutation: ``inv[perm] == arange``. Use it to undo a
    permutation (e.g. restore original channel order on export). NOTE —
    for cross-layer propagation apply ``perm`` itself, not the inverse, to
    the upstream layer's output rows (``w1[perm, :]``); see the module
    docstring."""
    perm = np.asarray(perm)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.size)
    return inv
