"""Halo exchange for spatial (H-split) convolution parallelism.

Counterpart of ``apex/contrib/bottleneck/halo_exchangers.py:11-...`` which
ships THREE transports (``HaloExchangerAllGather``, ``HaloExchangerSendRecv``
over raw NCCL p2p, ``HaloExchangerPeer`` over CUDA-IPC peer memory) because
NCCL neighbor exchange is slow enough to warrant hand-rolled alternatives.
On TPU every variant collapses onto a pair of ``lax.ppermute`` neighbor
shifts riding the ICI ring — the topology the hardware was built around —
so one implementation covers all three (and ``contrib/peer_memory``'s
``PeerHaloExchanger1d`` + ``contrib/csrc/nccl_p2p``'s
``left_right_halo_exchange``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.tensor_parallel.mappings import axis_bound, axis_size

__all__ = ["halo_exchange_1d", "HaloExchanger"]


def halo_exchange_1d(x: jax.Array, halo: int, *, dim: int = 1,
                     axis_name: str = "context",
                     wrap: bool = False) -> jax.Array:
    """Pad ``x`` along ``dim`` with ``halo`` rows from each ring neighbor.

    Returns ``x`` extended to ``size + 2*halo`` along ``dim``: the leading
    halo comes from the previous rank's trailing rows, the trailing halo
    from the next rank's leading rows (reference
    ``left_right_halo_exchange``, ``nccl_p2p.cpp:20-24``). Edge ranks get
    zeros unless ``wrap`` (matching the zero-padding a non-distributed conv
    would see).
    """
    if not axis_bound(axis_name):
        zeros = jnp.zeros_like(lax.slice_in_dim(x, 0, halo, axis=dim))
        return jnp.concatenate([zeros, x, zeros], axis=dim)
    size = axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    top = lax.slice_in_dim(x, 0, halo, axis=dim)
    bottom = lax.slice_in_dim(x, x.shape[dim] - halo, x.shape[dim], axis=dim)
    fwd = [(r, (r + 1) % size) for r in range(size)]
    bwd = [(r, (r - 1) % size) for r in range(size)]
    from_prev = lax.ppermute(bottom, axis_name, fwd)  # prev rank's bottom
    from_next = lax.ppermute(top, axis_name, bwd)     # next rank's top
    if not wrap:
        from_prev = jnp.where(rank == 0, jnp.zeros_like(from_prev),
                              from_prev)
        from_next = jnp.where(rank == size - 1, jnp.zeros_like(from_next),
                              from_next)
    return jnp.concatenate([from_prev, x, from_next], axis=dim)


@dataclass
class HaloExchanger:
    """Object form mirroring the reference exchanger classes; the transport
    distinction (AllGather / SendRecv / Peer) is meaningless on TPU, so one
    class with the reference's call shape."""

    axis_name: str = "context"
    wrap: bool = False

    def __call__(self, x: jax.Array, halo: int, dim: int = 1) -> jax.Array:
        return halo_exchange_1d(x, halo, dim=dim, axis_name=self.axis_name,
                                wrap=self.wrap)
