"""Fused ResNet bottleneck + spatial (H-split) parallel variant.

Counterpart of ``apex/contrib/bottleneck/bottleneck.py`` (``Bottleneck``
:134, ``SpatialBottleneck`` :265-749; 4k LoC of cuDNN-frontend fused conv
graphs in ``bottleneck.cpp``): the 1x1-3x3-1x1 residual block with
norm+ReLU epilogues, and a variant whose activations are sharded over the
image H dimension across devices, exchanging one-row halos for the 3x3 conv.

TPU design: convs are ``lax.conv_general_dilated`` in NHWC (the TPU-native
conv layout the reference's "channels_last" fights torch to get), epilogue
fusion is XLA's, and the halo exchange is the ``ppermute`` pair in
:mod:`.halo_exchangers`. Norms are frozen scale/bias folded next to each
conv (the reference's inference-style ``FrozenBatchNorm``
scale/bias arguments); training-time stats ride
:class:`apex_tpu.contrib.groupbn.BatchNorm2d_NHWC` when needed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from apex_tpu.contrib.bottleneck.halo_exchangers import halo_exchange_1d
from apex_tpu.utils.conv import conv_nhwc as _conv_nhwc, he_init as _he_init

__all__ = ["Bottleneck", "SpatialBottleneck"]


@dataclass
class Bottleneck:
    """1x1 (reduce) -> 3x3 -> 1x1 (expand) with residual, per-conv frozen
    scale/bias + ReLU (reference ``Bottleneck``, ``bottleneck.py:134-262``).
    """

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    use_cudnn: bool = True   # accepted for parity; ignored

    @property
    def has_downsample(self) -> bool:
        return self.stride != 1 or self.in_channels != self.out_channels

    def init(self, key: jax.Array) -> Dict[str, jax.Array]:
        cin, cb, cout = (self.in_channels, self.bottleneck_channels,
                         self.out_channels)
        keys = jax.random.split(key, 4)
        p = {
            "conv1": _he_init(keys[0], (1, 1, cin, cb)),
            "conv2": _he_init(keys[1], (3, 3, cb, cb)),
            "conv3": _he_init(keys[2], (1, 1, cb, cout)),
        }
        for i, c in (("1", cb), ("2", cb), ("3", cout)):
            p[f"scale{i}"] = jnp.ones((c,))
            p[f"bias{i}"] = jnp.zeros((c,))
        if self.has_downsample:
            p["conv4"] = _he_init(keys[3], (1, 1, cin, cout))
            p["scale4"] = jnp.ones((cout,))
            p["bias4"] = jnp.zeros((cout,))
        return p

    def spec(self):
        shapes = jax.eval_shape(self.init, jax.random.PRNGKey(0))
        return {k: PartitionSpec() for k in shapes}

    def _conv2(self, params, x):
        return _conv_nhwc(x, params["conv2"], stride=self.stride,
                          padding="SAME")

    def apply(self, params: Dict[str, jax.Array], x: jax.Array) -> jax.Array:
        """x: [N, H, W, C_in] NHWC."""
        out = _conv_nhwc(x, params["conv1"])
        out = jax.nn.relu(out * params["scale1"] + params["bias1"])
        out = self._conv2(params, out)
        out = jax.nn.relu(out * params["scale2"] + params["bias2"])
        out = _conv_nhwc(out, params["conv3"])
        out = out * params["scale3"] + params["bias3"]
        if self.has_downsample:
            residual = _conv_nhwc(x, params["conv4"], stride=self.stride)
            residual = residual * params["scale4"] + params["bias4"]
        else:
            residual = x
        return jax.nn.relu(out + residual)


@dataclass
class SpatialBottleneck(Bottleneck):
    """H-split spatial parallelism (reference ``SpatialBottleneck``,
    ``bottleneck.py:265-749``): activations sharded ``[N, H/ranks, W, C]``
    over ``spatial_axis``; only the 3x3 conv needs neighbor rows, fetched by
    a one-row halo exchange, then the padded conv runs with VALID height
    padding so results match the unsharded block exactly."""

    spatial_axis: str = "context"

    def _conv2(self, params, x):
        if self.stride != 1:
            raise NotImplementedError(
                "spatial H-split with strided 3x3 requires stride-aligned "
                "shards; shard the stride-1 stages (reference restriction)")
        padded = halo_exchange_1d(x, 1, dim=1, axis_name=self.spatial_axis)
        return lax.conv_general_dilated(
            padded, params["conv2"], window_strides=(1, 1),
            padding=((0, 0), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
