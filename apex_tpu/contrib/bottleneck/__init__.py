from apex_tpu.contrib.bottleneck.bottleneck import Bottleneck, SpatialBottleneck
from apex_tpu.contrib.bottleneck.halo_exchangers import (
    HaloExchanger,
    halo_exchange_1d,
)

__all__ = ["Bottleneck", "SpatialBottleneck", "HaloExchanger",
           "halo_exchange_1d"]
