"""Fused multi-head attention modules.

Counterpart of ``apex/contrib/multihead_attn`` (``self_multihead_attn.py:27-
137``, ``encdec_multihead_attn.py:27-100``; ~7.5k LoC of CUDA under
``contrib/csrc/multihead_attn/``): fairseq-layout ``[T, B, E]`` attention
with fused QKV projection, optional pre-LayerNorm + residual add
(``include_norm_add``), boolean key-padding or additive masks, and attention
dropout. The CUDA strided-batched-GEMM + fused-softmax pipeline maps to the
Pallas flash kernel (mask-free paths) or the fused scale-mask-softmax
(masked/dropout paths) — both MXU-tiled, no 512-token cap.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from apex_tpu.ops import (
    flash_attention,
    fused_layer_norm_affine,
    scaled_masked_softmax,
    scaled_softmax,
)

__all__ = ["SelfMultiheadAttn", "EncdecMultiheadAttn"]


def _xavier_uniform(key, shape, gain=1.0):
    fan_out, fan_in = shape[0], shape[1]
    bound = gain * (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(key, shape, minval=-bound, maxval=bound)


def _core_attention(q, k, v, *, scaling, key_padding_mask, attn_mask,
                    mask_additive, dropout, rng, is_training):
    """q/k/v: [B, H, T, dh]; returns [B, H, Tq, dh]."""
    no_mask = key_padding_mask is None and attn_mask is None
    if no_mask and (not is_training or dropout == 0.0):
        return flash_attention(q, k, v, softmax_scale=scaling)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k)
    if mask_additive:
        scores = scores * scaling
        if attn_mask is not None:
            scores = scores + attn_mask
        if key_padding_mask is not None:
            scores = scores + key_padding_mask[:, None, None, :]
        probs = scaled_softmax(scores, 1.0).astype(q.dtype)
    else:
        # boolean masks ride the fused scale+mask+softmax kernel
        # (reference csrc/megatron/scaled_masked_softmax semantics)
        mask = None
        if attn_mask is not None:
            mask = jnp.broadcast_to(attn_mask, scores.shape)
        if key_padding_mask is not None:
            kp = jnp.broadcast_to(key_padding_mask[:, None, None, :],
                                  scores.shape)
            mask = kp if mask is None else jnp.logical_or(mask, kp)
        probs = scaled_masked_softmax(scores, mask, scaling).astype(q.dtype)
    if is_training and dropout > 0.0 and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _split_heads(x, num_heads):
    # [T, B, E] -> [B, H, T, dh]
    t, b, e = x.shape
    return x.reshape(t, b, num_heads, e // num_heads).transpose(1, 2, 0, 3)


def _merge_heads(x):
    # [B, H, T, dh] -> [T, B, E]
    b, h, t, d = x.shape
    return x.transpose(2, 0, 1, 3).reshape(t, b, h * d)


@dataclass
class SelfMultiheadAttn:
    """Reference ``SelfMultiheadAttn`` (``self_multihead_attn.py:27-137``)."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"      # accepted for parity; one TPU path
    separate_qkv_params: bool = False
    mask_additive: bool = False

    def __post_init__(self):
        if self.embed_dim % self.num_heads:
            raise AssertionError("embed_dim must be divisible by num_heads")
        self.head_dim = self.embed_dim // self.num_heads
        self.scaling = self.head_dim ** -0.5
        if self.mask_additive and self.include_norm_add:
            raise AssertionError("additive mask not supported with layer norm")

    def init(self, key: jax.Array) -> Dict[str, jax.Array]:
        e = self.embed_dim
        keys = jax.random.split(key, 5)
        p: Dict[str, jax.Array] = {}
        if self.separate_qkv_params:
            p["q_weight"] = _xavier_uniform(keys[0], (e, e))
            p["k_weight"] = _xavier_uniform(keys[1], (e, e))
            p["v_weight"] = _xavier_uniform(keys[2], (e, e))
        else:
            # gain sqrt(2): [3e, e] initialized like [e, e]
            # (reference reset_parameters comment)
            p["in_proj_weight"] = _xavier_uniform(keys[0], (3 * e, e),
                                                  gain=2.0 ** 0.5)
        p["out_proj_weight"] = _xavier_uniform(keys[3], (e, e))
        if self.bias:
            if self.separate_qkv_params:
                p["q_bias"] = jnp.zeros((e,))
                p["k_bias"] = jnp.zeros((e,))
                p["v_bias"] = jnp.zeros((e,))
            else:
                p["in_proj_bias"] = jnp.zeros((3 * e,))
            p["out_proj_bias"] = jnp.zeros((e,))
        if self.include_norm_add:
            p["lyr_nrm_gamma_weights"] = jnp.ones((e,))
            p["lyr_nrm_beta_weights"] = jnp.zeros((e,))
        return p

    def spec(self):
        shapes = jax.eval_shape(self.init, jax.random.PRNGKey(0))
        return {k: PartitionSpec() for k in shapes}

    def apply(self, params, query, *, key_padding_mask=None, attn_mask=None,
              rng=None, is_training: bool = True):
        """query: ``[T, B, E]``. Returns ``[T, B, E]`` (with residual add
        when ``include_norm_add``)."""
        x = query
        if self.include_norm_add:
            x = fused_layer_norm_affine(
                x, params["lyr_nrm_gamma_weights"],
                params["lyr_nrm_beta_weights"], (self.embed_dim,))
        if self.separate_qkv_params:
            q = x @ params["q_weight"].T
            k = x @ params["k_weight"].T
            v = x @ params["v_weight"].T
            if self.bias:
                q, k, v = (q + params["q_bias"], k + params["k_bias"],
                           v + params["v_bias"])
        else:
            qkv = x @ params["in_proj_weight"].T
            if self.bias:
                qkv = qkv + params["in_proj_bias"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
        ctx = _core_attention(
            _split_heads(q, self.num_heads), _split_heads(k, self.num_heads),
            _split_heads(v, self.num_heads), scaling=self.scaling,
            key_padding_mask=key_padding_mask, attn_mask=attn_mask,
            mask_additive=self.mask_additive, dropout=self.dropout,
            rng=rng, is_training=is_training)
        out = _merge_heads(ctx) @ params["out_proj_weight"].T
        if self.bias:
            out = out + params["out_proj_bias"]
        if self.include_norm_add:
            out = out + query   # fused residual add (norm-add variant)
        return out


@dataclass
class EncdecMultiheadAttn:
    """Reference ``EncdecMultiheadAttn`` (``encdec_multihead_attn.py:27-100``):
    query from the decoder, fused K/V projection from the encoder."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"

    def __post_init__(self):
        if self.embed_dim % self.num_heads:
            raise AssertionError("embed_dim must be divisible by num_heads")
        self.head_dim = self.embed_dim // self.num_heads
        self.scaling = self.head_dim ** -0.5

    def init(self, key: jax.Array) -> Dict[str, jax.Array]:
        e = self.embed_dim
        keys = jax.random.split(key, 3)
        p = {
            "q_weight": _xavier_uniform(keys[0], (e, e)),
            "kv_weight": _xavier_uniform(keys[1], (2 * e, e),
                                         gain=2.0 ** 0.5),
            "out_proj_weight": _xavier_uniform(keys[2], (e, e)),
        }
        if self.bias:
            p["q_bias"] = jnp.zeros((e,))
            p["kv_bias"] = jnp.zeros((2 * e,))
            p["out_proj_bias"] = jnp.zeros((e,))
        if self.include_norm_add:
            p["lyr_nrm_gamma_weights"] = jnp.ones((e,))
            p["lyr_nrm_beta_weights"] = jnp.zeros((e,))
        return p

    def spec(self):
        shapes = jax.eval_shape(self.init, jax.random.PRNGKey(0))
        return {k: PartitionSpec() for k in shapes}

    def apply(self, params, query, key, *, key_padding_mask=None,
              attn_mask=None, rng=None, is_training: bool = True):
        """query: ``[Tq, B, E]`` (decoder); key: ``[Tk, B, E]`` (encoder)."""
        x = query
        if self.include_norm_add:
            x = fused_layer_norm_affine(
                x, params["lyr_nrm_gamma_weights"],
                params["lyr_nrm_beta_weights"], (self.embed_dim,))
        q = x @ params["q_weight"].T
        kv = key @ params["kv_weight"].T
        if self.bias:
            q = q + params["q_bias"]
            kv = kv + params["kv_bias"]
        k, v = jnp.split(kv, 2, axis=-1)
        ctx = _core_attention(
            _split_heads(q, self.num_heads), _split_heads(k, self.num_heads),
            _split_heads(v, self.num_heads), scaling=self.scaling,
            key_padding_mask=key_padding_mask, attn_mask=attn_mask,
            mask_additive=False, dropout=self.dropout, rng=rng,
            is_training=is_training)
        out = _merge_heads(ctx) @ params["out_proj_weight"].T
        if self.bias:
            out = out + params["out_proj_bias"]
        if self.include_norm_add:
            out = out + query
        return out
