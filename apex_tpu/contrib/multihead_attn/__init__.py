from apex_tpu.contrib.multihead_attn.attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)

__all__ = ["SelfMultiheadAttn", "EncdecMultiheadAttn"]
