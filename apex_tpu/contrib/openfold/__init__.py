"""OpenFold acceleration tier.

Counterpart of ``apex/contrib/openfold_triton`` (the reference's only
non-CUDA kernels — Triton LayerNorm fwd/bwd, MHA, and a fused Adam+SWA
optimizer, ``contrib/openfold_triton/__init__.py:41-97``). On TPU the
LayerNorm and MHA kernels are the framework's own Pallas ops (re-exported
here so OpenFold-style callers find them under one roof), and the Triton
autotune-cache broadcast (``sync_triton_auto_tune_cache_across_gpus``) has
no analog — XLA's compilation cache is process-global — so it is a no-op
kept for API parity.

:class:`FusedAdamSWA` is the real capability: one fused update doing the
Adam math and the stochastic-weight-averaging EMA in a single pass
(reference ``fused_adam_swa.py:102-199``).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops import (  # re-exports for OpenFold-style callers
    flash_attention as mha,
    fused_layer_norm_affine as layer_norm,
)
from apex_tpu.optimizers.fused_adam import FusedAdam

__all__ = ["FusedAdamSWA", "layer_norm", "mha",
           "sync_triton_auto_tune_cache_across_gpus"]


def sync_triton_auto_tune_cache_across_gpus(*_args, **_kw) -> None:
    """No-op: XLA's compile cache is shared process-wide (parity with
    ``openfold_triton.sync_triton_auto_tune_cache_across_gpus``)."""


class FusedAdamSWA(FusedAdam):
    """Adam + stochastic weight averaging in one fused step.

    Semantics of the reference's ``_swa_math`` (``fused_adam_swa.py:102-112``):
    the first averaged step copies params into the SWA buffer; later steps do
    ``swa += (1 - decay) * (p - swa)``. State carries ``swa_params`` and
    ``n_averaged`` alongside the Adam slots.
    """

    def __init__(self, lr: float = 1e-3, *, swa_decay_rate: float = 0.9,
                 **adam_kw):
        super().__init__(lr=lr, **adam_kw)
        self.swa_decay_rate = swa_decay_rate

    def init(self, params) -> dict:
        state = super().init(params)
        # forced copy: donating params + state together must never alias
        state["swa_params"] = jax.tree.map(
            lambda p: jnp.array(p, jnp.float32, copy=True), params)
        state["n_averaged"] = jnp.zeros((), jnp.int32)
        return state

    def step(self, grads, params, state, *, lr: Optional[Any] = None,
             grad_scale: Optional[jax.Array] = None,
             found_inf: Optional[jax.Array] = None) -> Tuple[Any, dict]:
        swa_old = state["swa_params"]
        n_avg = state["n_averaged"]
        new_params, new_state = super().step(
            grads, params, state, lr=lr, grad_scale=grad_scale,
            found_inf=found_inf)
        decay = self.swa_decay_rate

        def swa_upd(swa, p):
            p32 = p.astype(jnp.float32)
            return jnp.where(n_avg == 0, p32,
                             swa + (1.0 - decay) * (p32 - swa))

        new_swa = jax.tree.map(swa_upd, swa_old, new_params)
        stepped = jnp.asarray(True)
        if found_inf is not None:
            stepped = jnp.logical_not(found_inf)
            new_swa = jax.tree.map(
                lambda n, o: jnp.where(stepped, n, o), new_swa, swa_old)
        new_state["swa_params"] = new_swa
        new_state["n_averaged"] = n_avg + stepped.astype(jnp.int32)
        return new_params, new_state
