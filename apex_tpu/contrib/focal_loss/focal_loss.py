"""Fused sigmoid focal loss.

Counterpart of ``apex/contrib/focal_loss/focal_loss.py:6-60`` +
``focal_loss_cuda_kernel.cu`` (label-smoothing constants at ``:33-38``):
sigmoid focal loss (Lin et al.) over one-hot class targets, summed and
normalized by ``num_positives_sum``. The CUDA kernel exists to fuse the
one-hot materialization, BCE, modulating factor, and normalization into one
pass with a stashed partial gradient; XLA fuses the same chain, and autodiff
recomputes instead of stashing (cheaper than the HBM round-trip on TPU).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["focal_loss"]


def focal_loss(
    cls_output: jax.Array,
    cls_targets_at_level: jax.Array,
    num_positives_sum: jax.Array,
    num_real_classes: int,
    alpha: float,
    gamma: float,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Args mirror the reference function (``focal_loss.py:42-60``).

    cls_output: ``[..., K_padded]`` logits (K_padded >= num_real_classes;
    padded classes are ignored, matching the kernel's ``num_real_classes``
    argument). cls_targets_at_level: integer class ids, ``-1``/out-of-range
    = background (all-zero one-hot). Returns the scalar sum loss divided by
    ``num_positives_sum``.
    """
    K = num_real_classes
    x = cls_output[..., :K].astype(jnp.float32)
    t = jax.nn.one_hot(cls_targets_at_level, K, dtype=jnp.float32)
    if label_smoothing > 0.0:
        # smoothed target (kernel constants focal_loss_cuda_kernel.cu:33-38)
        t = t * (1.0 - label_smoothing) + label_smoothing / K

    p = jax.nn.sigmoid(x)
    # numerically-stable BCE with logits
    ce = jnp.maximum(x, 0.0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * t + (1.0 - p) * (1.0 - t)
    loss = ce * (1.0 - p_t) ** gamma
    if alpha >= 0:
        alpha_t = alpha * t + (1.0 - alpha) * (1.0 - t)
        loss = alpha_t * loss
    return jnp.sum(loss) / num_positives_sum
