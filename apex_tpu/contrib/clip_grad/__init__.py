from apex_tpu.contrib.clip_grad.clip_grad import clip_grad_norm_, clip_grad_norm

__all__ = ["clip_grad_norm_", "clip_grad_norm"]
