"""Fused gradient clipping.

Capability of ``apex.contrib.clip_grad.clip_grad_norm_``
(``apex/contrib/clip_grad/clip_grad.py:16-60``): one fused global-norm
reduction (``multi_tensor_l2norm``) + one fused scale
(``multi_tensor_scale``). Functional: returns the clipped tree and the norm.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.utils.tree import global_norm


def clip_grad_norm(grads: Any, max_norm: float,
                   norm_type: float = 2.0) -> Tuple[Any, jax.Array]:
    """Return ``(clipped_grads, total_norm)``."""
    if norm_type == 2.0:
        total = global_norm(grads)
    elif norm_type == float("inf"):
        leaves = jax.tree_util.tree_leaves(grads)
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(x)) for x in leaves]))
    else:
        leaves = jax.tree_util.tree_leaves(grads)
        total = jnp.sum(
            jnp.stack([jnp.sum(jnp.abs(x.astype(jnp.float32)) ** norm_type)
                       for x in leaves])) ** (1.0 / norm_type)
    coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    clipped = jax.tree_util.tree_map(lambda g: (g * coef).astype(g.dtype), grads)
    return clipped, total


# reference-named alias (the trailing underscore loses its in-place meaning here)
clip_grad_norm_ = clip_grad_norm
