"""NHWC GroupNorm with optional fused Swish/SiLU.

Counterpart of ``apex/contrib/group_norm/group_norm.py:44-127`` +
``group_norm_nhwc*.cu`` (~2.5k LoC of tuned one-pass/two-pass kernels for
diffusion workloads). On TPU the NHWC layout is already the native
convolution layout, and the reduce + normalize + affine + swish chain fuses
in XLA; the one-pass/two-pass distinction is a CUDA shared-memory concern
with no TPU analog, so ``algo`` is accepted and ignored.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

__all__ = ["GroupNorm", "group_norm_nhwc"]


def group_norm_nhwc(x: jax.Array, num_groups: int,
                    weight: Optional[jax.Array],
                    bias: Optional[jax.Array],
                    eps: float = 1e-5, act: str = "") -> jax.Array:
    """x: ``[N, H, W, C]``; normalizes over (H, W, C/G) per group.

    ``act`` in {"", "silu", "swish"} (reference sanity checks,
    ``group_norm.py:56-64``).
    """
    act = act.lower()
    if act not in ("", "silu", "swish"):
        raise ValueError("Unsupported activation.")
    n, h, w, c = x.shape
    if c % num_groups:
        raise ValueError("C % G != 0.")
    xdtype = x.dtype
    xg = x.astype(jnp.float32).reshape(n, h, w, num_groups, c // num_groups)
    mean = jnp.mean(xg, axis=(1, 2, 4), keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=(1, 2, 4), keepdims=True)
    y = (xg - mean) * jax.lax.rsqrt(var + eps)
    y = y.reshape(n, h, w, c)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    if act:
        y = y * jax.nn.sigmoid(y)
    return y.astype(xdtype)


@dataclass
class GroupNorm:
    """Reference ``apex.contrib.group_norm.GroupNorm``
    (``group_norm.py:127-...``), NHWC layout."""

    num_groups: int
    num_channels: int
    eps: float = 1e-5
    affine: bool = True
    act: str = ""

    def init(self, key: jax.Array) -> Dict[str, jax.Array]:
        if not self.affine:
            return {}
        return {"weight": jnp.ones((self.num_channels,)),
                "bias": jnp.zeros((self.num_channels,))}

    def spec(self) -> Dict[str, PartitionSpec]:
        if not self.affine:
            return {}
        return {"weight": PartitionSpec(), "bias": PartitionSpec()}

    def apply(self, params: Dict[str, jax.Array], x: jax.Array) -> jax.Array:
        return group_norm_nhwc(
            x, self.num_groups, params.get("weight"), params.get("bias"),
            self.eps, self.act)
