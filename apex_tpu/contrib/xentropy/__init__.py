"""Fused softmax cross entropy (reference ``apex/contrib/xentropy``).

The kernel (``xentropy_kernel.cu``, 718 LoC) exists to avoid materializing
softmax probabilities; the Pallas/XLA implementation lives in
:mod:`apex_tpu.ops.cross_entropy` and is re-exported here at the reference's
import path (``apex/contrib/xentropy/softmax_xentropy.py:6-30``).
"""

from apex_tpu.ops.cross_entropy import (
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)

__all__ = ["SoftmaxCrossEntropyLoss", "softmax_cross_entropy_loss"]
