"""FMHA — BERT-style fused multi-head attention over variable-length batches.

Counterpart of ``apex/contrib/fmha/fmha.py:33-90`` (+ ~6k LoC of sm80/90
kernels under ``contrib/csrc/fmha`` capped at seq 512): packed-QKV attention
where padding tokens are skipped via ``cu_seqlens`` offsets.

TPU semantics: XLA wants static shapes, so the packed ``[total, 3, h, d]`` +
``cu_seqlens`` interface becomes a padded ``[B, S, 3, h, d]`` + per-batch
``seqlens`` — the flash kernel's ``kv_lengths`` masking gives the identical
math (padded key positions contribute zero probability; padded query rows
are zeroed on output), with no 512 cap.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from apex_tpu.ops import flash_attention

__all__ = ["FMHA"]


@dataclass
class FMHA:
    """``config`` needs ``num_attention_heads``, ``hidden_size``, and
    ``attention_probs_dropout_prob`` (reference ``fmha.py:62-70``; dropout
    inside the kernel is not ported — compose dropout outside)."""

    num_attention_heads: int
    hidden_size: int
    attention_probs_dropout_prob: float = 0.0

    def __post_init__(self):
        self.h = self.num_attention_heads
        self.d = self.hidden_size // self.h
        if self.d * self.h != self.hidden_size:
            raise AssertionError("Invalid hidden size/num_heads")

    def __call__(self, qkv: jax.Array, seqlens: jax.Array,
                 is_training: bool = True) -> jax.Array:
        """qkv: ``[B, S, 3*hidden]`` (or ``[B, S, 3, h, d]``), seqlens:
        int32 ``[B]``. Returns ``[B, S, hidden]`` with padded rows zeroed."""
        B, S = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape(B, S, 3, self.h, self.d)
        q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
        ctx = flash_attention(q, k, v, kv_lengths=seqlens)
        out = ctx.transpose(0, 2, 1, 3).reshape(B, S, self.hidden_size)
        valid = jnp.arange(S)[None, :] < seqlens[:, None]
        return out * valid[..., None].astype(out.dtype)
