from apex_tpu.contrib.fmha.fmha import FMHA

__all__ = ["FMHA"]
