"""Peer-memory halo exchange (reference ``apex/contrib/peer_memory``).

The reference allocates CUDA-IPC peer memory pools (``peer_memory.py:5``,
``peer_memory_cuda.cu``) so ``PeerHaloExchanger1d`` can write halos directly
into a neighbor's buffer. XLA owns all TPU buffers — there is no user-level
peer memory — and the capability (neighbor halo exchange) is the
``ppermute`` implementation in :mod:`apex_tpu.contrib.bottleneck.
halo_exchangers`, re-exported here. ``PeerMemoryPool`` has intentionally no
TPU analog.
"""

from apex_tpu.contrib.bottleneck.halo_exchangers import (
    HaloExchanger as PeerHaloExchanger1d,
    halo_exchange_1d,
)

__all__ = ["PeerHaloExchanger1d", "halo_exchange_1d"]
