"""Direct-storage tensor IO (reference ``apex/contrib/gpu_direct_storage``).

The reference wraps cuFile (``gds.cpp``) for NVMe<->GPU DMA. TPUs have no
user-visible DMA path — the distributed, host-bypassing persistence story on
TPU is the orbax-backed sharded checkpointing in :mod:`apex_tpu.checkpoint`.
``GDSFile`` here provides the reference's load/save file API over numpy
memmap for raw-array interchange.
"""

from __future__ import annotations

import numpy as np

__all__ = ["GDSFile"]


class GDSFile:
    """Minimal ``GDSFile(name, mode)`` with ``load_data``/``save_data``
    (reference ``contrib/gpu_direct_storage/__init__.py``)."""

    def __init__(self, name: str, mode: str = "r"):
        if mode not in ("r", "w"):
            raise ValueError("mode must be 'r' or 'w'")
        self.name, self.mode = name, mode

    def save_data(self, array) -> None:
        if self.mode != "w":
            raise RuntimeError("file not opened for writing")
        np.save(self.name, np.asarray(array), allow_pickle=False)

    def load_data(self):
        if self.mode != "r":
            raise RuntimeError("file not opened for reading")
        return np.load(self.name if self.name.endswith(".npy")
                       else self.name + ".npy", mmap_mode="r")
