"""Fused gather-multiply.

Counterpart of ``apex/contrib/index_mul_2d/index_mul_2d.py:5-60`` +
``index_mul_2d_cuda``: ``out = in1[idx1] * in2`` with the backward's
scatter-add into ``d_in1``. One XLA gather fused with the multiply on TPU;
the scatter-add backward falls out of autodiff (the transpose of gather).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["index_mul_2d"]


def index_mul_2d(in1: jax.Array, in2: jax.Array,
                 idx1: jax.Array) -> jax.Array:
    """``out[i, :] = in1[idx1[i], :] * in2[i, :]`` (reference constraints:
    2-D operands, index over dim 0, ``in2.shape[0] == idx1.shape[0]``)."""
    if in1.ndim != 2 or in2.ndim != 2:
        raise RuntimeError("in1 and in2 must be 2-dimension tensor.")
    if idx1.ndim != 1:
        raise RuntimeError("idx1 must be 1-dimension tensor.")
    if in2.shape[0] != idx1.shape[0]:
        raise RuntimeError("in2.shape[0] must equal idx1.shape[0]")
    return jnp.take(in1, idx1, axis=0) * in2
