"""Optional extensions (capability of ``apex/contrib``).

Submodules (reference build flags in ``setup.py:110-860``):
``clip_grad``, ``focal_loss``, ``index_mul_2d``, ``group_norm``, ``groupbn``,
``cudnn_gbn``, ``multihead_attn``, ``fmha``, ``transducer``, ``bottleneck``
(+ ``peer_memory`` halo exchange), ``sparsity`` (ASP 2:4), ``xentropy``,
``layer_norm``, ``conv_bias_relu``, ``gpu_direct_storage``, ``openfold``
(the reference's ``openfold_triton``: Pallas LayerNorm/MHA re-exports +
``FusedAdamSWA``). ``nccl_p2p``/``nccl_allocator`` are NCCL plumbing
with no TPU analog (XLA owns collectives and buffers).
"""
