"""Optional extensions (capability of ``apex/contrib``)."""
