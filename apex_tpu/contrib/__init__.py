"""Optional extensions (capability of ``apex/contrib``).

Submodules (reference build flags in ``setup.py:110-860``):
``clip_grad``, ``focal_loss``, ``index_mul_2d``, ``group_norm``, ``groupbn``,
``cudnn_gbn``, ``multihead_attn``, ``fmha``, ``transducer``, ``bottleneck``
(+ ``peer_memory`` halo exchange), ``sparsity`` (ASP 2:4), ``xentropy``,
``layer_norm``, ``gpu_direct_storage``. The reference's ``openfold_triton``
is Triton-specific acceleration whose constituent ops (fused LayerNorm, MHA,
fused Adam+SWA) exist here as the general kernels in ``apex_tpu.ops`` /
``apex_tpu.optimizers``; ``nccl_p2p``/``nccl_allocator`` are NCCL plumbing
with no TPU analog (XLA owns collectives and buffers).
"""
