"""RNN-T transducer joint and loss.

Counterpart of ``apex/contrib/transducer/transducer.py:5-120`` +
``transducer_joint_kernel.cu`` (979 LoC) / ``transducer_loss_kernel.cu``
(767 LoC): the additive joint with fused ReLU/dropout, and the transducer
(RNN-T) loss via the alpha forward recursion.

TPU design of the loss: the CUDA kernel walks the (T, U) lattice with
per-diagonal thread teams. Here the alpha recursion runs as a ``lax.scan``
over T whose per-row emit recurrence (``alpha[t, u] = logaddexp(
alpha[t-1, u] + blank, alpha[t, u-1] + emit)``) is solved with a
**log-semiring associative scan** over U: the recurrence is affine in exp
space, so each row costs O(log U) depth on the VPU instead of a sequential
U-loop. Gradients come from autodiff (the reference hand-fuses the backward
with softmax; XLA fuses the same because the log-softmax feeding the lattice
is part of one jit).

Packed (``pack_output``/``packed_input``) variants are intentionally not
ported: packing exists to skip CUDA work on padding, which would make shapes
dynamic under XLA; masking achieves the same math on TPU (padding lanes are
already-scheduled VPU lanes, not saved work).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["TransducerJoint", "TransducerLoss", "transducer_loss"]

_NEG_INF = -1e30


@dataclass
class TransducerJoint:
    """Additive joint ``out[b,t,u] = f[b,t] + g[b,u]`` with optional fused
    ReLU and dropout (reference ``transducer.py:5-67``). ``pack_output`` is
    rejected (see module docstring); padding positions are zeroed via
    ``f_len``/``g_len`` masks instead."""

    pack_output: bool = False
    relu: bool = False
    dropout: bool = False
    dropout_prob: float = 0.0

    def __post_init__(self):
        if self.pack_output:
            raise NotImplementedError(
                "pack_output serves CUDA padding-skip; on TPU use the masked "
                "dense output")

    def __call__(self, f, g, f_len=None, g_len=None, *, rng=None,
                 deterministic: bool = True):
        """f: ``[B, T, H]``, g: ``[B, U, H]`` -> ``[B, T, U, H]``."""
        out = f[:, :, None, :] + g[:, None, :, :]
        if self.relu:
            out = jax.nn.relu(out)
        if self.dropout and not deterministic and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - self.dropout_prob,
                                        out.shape)
            out = jnp.where(keep, out / (1.0 - self.dropout_prob), 0.0)
        if f_len is not None:
            t_valid = jnp.arange(f.shape[1])[None, :] < f_len[:, None]
            out = out * t_valid[:, :, None, None]
        if g_len is not None:
            u_valid = jnp.arange(g.shape[1])[None, :] < g_len[:, None]
            out = out * u_valid[:, None, :, None]
        return out


def _row_recurrence(base, emit_shift):
    """Solve r[u] = logaddexp(base[u], r[u-1] + emit_shift[u]) for all u
    (emit_shift[0] is ignored — no left neighbor) via associative scan on
    affine log-semiring maps (A, B): r_out = logaddexp(B, A + r_in)."""

    def combine(left, right):
        a1, b1 = left
        a2, b2 = right
        return a1 + a2, jnp.logaddexp(b2, a2 + b1)

    A = emit_shift.at[..., 0].set(_NEG_INF)
    # element u applies r = logaddexp(base[u], A[u] + r_prev)
    a_scan, b_scan = lax.associative_scan(combine, (A, base), axis=-1)
    return b_scan


def transducer_loss(x, label, f_len, y_len, blank_idx: int,
                    *, x_is_log_probs: bool = False):
    """RNN-T loss (Graves 2012), reference semantics
    (``transducer.py:69-120``): ``x`` is ``[B, T, U, K]`` joint-network
    output (logits unless ``x_is_log_probs``); ``label`` is ``[B, U-1]``;
    ``f_len``/``y_len`` are per-batch time/label lengths (``U = max_y + 1``).
    Returns per-batch negative log-likelihood ``[B]``.
    """
    B, T, U, K = x.shape
    logp = x if x_is_log_probs else jax.nn.log_softmax(
        x.astype(jnp.float32), axis=-1)

    blank = logp[..., blank_idx]                          # [B, T, U]
    # emit[b, t, u] = logp of label[b, u] at lattice node (t, u), u < U-1
    lbl = jnp.minimum(label, K - 1)
    lbl_idx = jnp.broadcast_to(lbl[:, None, :, None], (B, T, U - 1, 1))
    emit = jnp.take_along_axis(
        logp[:, :, : U - 1, :], lbl_idx, axis=-1)[..., 0]           # [B,T,U-1]
    emit = jnp.concatenate(
        [emit, jnp.full((B, T, 1), _NEG_INF, emit.dtype)], axis=2)  # [B,T,U]
    # mask emissions beyond y_len (no label to emit there)
    u_idx = jnp.arange(U)[None, :]
    emit = jnp.where(u_idx[:, None] < y_len[:, None, None], emit, _NEG_INF)

    # alpha over rows t; within-row emit recurrence via associative scan
    alpha0_base = jnp.concatenate(
        [jnp.zeros((B, 1)), jnp.full((B, U - 1), _NEG_INF)], axis=1)
    emit_shift0 = jnp.concatenate(
        [jnp.full((B, 1), _NEG_INF), emit[:, 0, :-1]], axis=1)
    alpha0 = _row_recurrence(alpha0_base, emit_shift0)    # [B, U]

    def row(alpha_prev, t):
        base = alpha_prev + blank[:, t - 1, :]
        emit_shift = jnp.concatenate(
            [jnp.full((B, 1), _NEG_INF), emit[:, t, :-1]], axis=1)
        alpha_t = _row_recurrence(base, emit_shift)
        return alpha_t, alpha_t

    _, alphas = lax.scan(row, alpha0, jnp.arange(1, T))
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, U]

    # ll = alpha[f_len-1, y_len] + blank(f_len-1, y_len)
    t_last = jnp.maximum(f_len - 1, 0)
    a_final = alphas[t_last, jnp.arange(B), y_len]
    b_final = blank[jnp.arange(B), t_last, y_len]
    return -(a_final + b_final)


@dataclass
class TransducerLoss:
    """Module wrapper (reference ``transducer.py:69-120``);
    ``fuse_softmax_backward``/``opt`` are CUDA scheduling knobs accepted for
    API parity and ignored (XLA fuses the softmax backward regardless)."""

    fuse_softmax_backward: bool = True
    opt: int = 1
    packed_input: bool = False

    def __post_init__(self):
        if self.packed_input:
            raise NotImplementedError(
                "packed_input serves CUDA padding-skip; on TPU use the "
                "masked dense input")

    def __call__(self, x, label, f_len, y_len, blank_idx: int,
                 batch_offset=None, max_f_len=None, debug_list=None):
        return transducer_loss(x, label, f_len, y_len, blank_idx)
