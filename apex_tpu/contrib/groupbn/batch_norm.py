"""NHWC BatchNorm with fused add+ReLU and cross-device groups.

Counterpart of ``apex/contrib/groupbn/batch_norm.py:101-...`` ("group BN"):
persistent NHWC batchnorm with optional fused residual-add + ReLU, and
``bn_group > 1`` syncing statistics across a small cluster of devices. The
reference does the sync with raw CUDA-IPC peer memory and hand-rolled
handle exchange (``:150-180``); on TPU the same statistics sync is one
``lax.psum`` over a mesh axis — the ``cudnn_gbn.GroupBatchNorm2d``
capability collapses onto this module too.

Functional state: ``apply`` returns ``(y, new_state)`` with updated running
stats when ``training`` (torch mutates module buffers instead).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from apex_tpu.transformer.tensor_parallel.mappings import axis_bound, axis_size

__all__ = ["BatchNorm2d_NHWC"]


@dataclass
class BatchNorm2d_NHWC:
    """x: ``[N, H, W, C]``. ``bn_group_axis`` names the mesh axis whose
    ranks share statistics (the reference's ``bn_group`` peer set)."""

    num_features: int
    fuse_relu: bool = False
    bn_group: int = 1
    bn_group_axis: Optional[str] = None
    eps: float = 1e-5
    momentum: float = 0.1

    def init(self, key: Optional[jax.Array] = None) -> Dict[str, jax.Array]:
        c = self.num_features
        return {"weight": jnp.ones((c,)), "bias": jnp.zeros((c,))}

    def init_state(self) -> Dict[str, jax.Array]:
        c = self.num_features
        return {"running_mean": jnp.zeros((c,)),
                "running_var": jnp.ones((c,)),
                "num_batches_tracked": jnp.zeros((), jnp.int32)}

    def spec(self):
        return {"weight": PartitionSpec(), "bias": PartitionSpec()}

    def apply(self, params, state, x, z: Optional[jax.Array] = None,
              *, training: bool = True) -> Tuple[jax.Array, Dict]:
        """``z``: optional residual added before the (optional) ReLU — the
        fused add+relu path (reference ``bn_addrelu_*`` kernels)."""
        xdtype = x.dtype
        x32 = x.astype(jnp.float32)
        if training:
            mean = jnp.mean(x32, axis=(0, 1, 2))
            var = jnp.mean(jnp.square(x32 - mean), axis=(0, 1, 2))
            group = 1
            if self.bn_group > 1 and self.bn_group_axis and axis_bound(
                    self.bn_group_axis):
                # sync Welford-style stats across the group (reference IPC
                # peer reduction -> one psum over the axis)
                group = axis_size(self.bn_group_axis)
                if group != self.bn_group:
                    from apex_tpu.transformer.parallel_state import (
                        UndersizedMeshError,
                    )
                    raise UndersizedMeshError(
                        f"bn_group={self.bn_group} but mesh axis "
                        f"'{self.bn_group_axis}' has {group} ranks; shape "
                        f"the mesh so the axis matches the requested group")
                sq = var + mean * mean
                mean = lax.pmean(mean, self.bn_group_axis)
                sq = lax.pmean(sq, self.bn_group_axis)
                var = sq - mean * mean
            # unbiased correction over the element count that actually
            # contributed to `var` (local only unless the sync ran)
            n = x32.shape[0] * x32.shape[1] * x32.shape[2] * group
            unbiased = var * n / max(n - 1, 1)
            new_state = {
                "running_mean": (1 - self.momentum) * state["running_mean"]
                + self.momentum * mean,
                "running_var": (1 - self.momentum) * state["running_var"]
                + self.momentum * unbiased,
                "num_batches_tracked": state["num_batches_tracked"] + 1,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        y = (x32 - mean) * lax.rsqrt(var + self.eps)
        y = y * params["weight"] + params["bias"]
        if z is not None:
            y = y + z.astype(jnp.float32)
        if self.fuse_relu:
            y = jax.nn.relu(y)
        return y.astype(xdtype), new_state
