"""Fast layer norm (reference ``apex/contrib/layer_norm``).

``FastLayerNorm`` (``contrib/layer_norm/layer_norm.py:43``) is the tuned
hidden-size<=65k variant of the csrc fused LayerNorm; on TPU both map to the
same Pallas kernel, so this is the reference import path over
:class:`apex_tpu.normalization.FusedLayerNorm`.
"""

from apex_tpu.normalization import FusedLayerNorm as FastLayerNorm

__all__ = ["FastLayerNorm"]
