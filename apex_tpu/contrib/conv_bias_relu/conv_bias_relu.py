"""Fused conv epilogues.

Counterpart of ``apex/contrib/conv_bias_relu/conv_bias_relu.py:12-78``
(cuDNN-frontend fused graphs in ``contrib/csrc/conv_bias_relu/conv_bias_relu
.cpp``, 2 153 LoC): conv + bias (+ mask) (+ ReLU) and the frozen-BN
scale/bias variant. On TPU these are single jitted expressions — XLA fuses
the elementwise epilogue into the convolution's output tiles, which is the
entire reason the CUDA versions exist — so each "module" is a function.

Layout is NHWC (the reference requires channels_last memory format).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils.conv import conv_nhwc

__all__ = ["ConvBiasReLU", "ConvBias", "ConvBiasMaskReLU",
           "ConvFrozenScaleBiasReLU"]


def ConvBias(x: jax.Array, weight: jax.Array, bias: jax.Array,
             stride: int = 1, padding="SAME") -> jax.Array:
    """``conv(x, w) + b`` (reference ``ConvBias_``, epilogue
    ``CUDNN_POINTWISE_ADD``)."""
    return conv_nhwc(x, weight, stride, padding) + bias


def ConvBiasReLU(x: jax.Array, weight: jax.Array, bias: jax.Array,
                 stride: int = 1, padding="SAME") -> jax.Array:
    """``relu(conv(x, w) + b)`` (reference ``ConvBiasReLU_``)."""
    return jax.nn.relu(ConvBias(x, weight, bias, stride, padding))


def ConvBiasMaskReLU(x: jax.Array, weight: jax.Array, bias: jax.Array,
                     mask: jax.Array, stride: int = 1,
                     padding="SAME") -> jax.Array:
    """``relu((conv(x, w) + b) * mask)`` (reference ``ConvBiasMaskReLU_`` —
    the mask is the dropout/attention byte mask fused into the epilogue)."""
    return jax.nn.relu(ConvBias(x, weight, bias, stride, padding) * mask)


def ConvFrozenScaleBiasReLU(x: jax.Array, weight: jax.Array,
                            scale: jax.Array, bias: jax.Array,
                            stride: int = 1, padding="SAME") -> jax.Array:
    """``relu(conv(x, w) * scale + bias)`` — frozen-BN folding (reference
    ``ConvFrozenScaleBiasReLU_``)."""
    return jax.nn.relu(conv_nhwc(x, weight, stride, padding) * scale + bias)
