"""Group (cross-device) batch norm (reference ``apex/contrib/cudnn_gbn``).

``GroupBatchNorm2d`` (``cudnn_gbn/batch_norm.py:44``) is the cuDNN-graph
flavor of the groupbn capability; on TPU both are the psum-synced NHWC
batchnorm, so this re-exports :class:`apex_tpu.contrib.groupbn.
BatchNorm2d_NHWC` under the reference name.
"""

from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC as GroupBatchNorm2d

__all__ = ["GroupBatchNorm2d"]
