"""ctypes bindings for the C++ host runtime (``apex_tpu/csrc``).

The reference ships its host plumbing as pybind11 C++ extensions (``apex_C``
flatten/unflatten, bucket bookkeeping inside DDP, allocator plumbing in
``contrib/csrc/nccl_allocator``). Here the native library is built once from
``csrc/host_runtime.cpp`` with the system ``g++`` (no pybind11 in the image —
plain C ABI + ctypes) and cached next to the source; a pure-numpy fallback
keeps every API functional when no compiler is available.

Public surface:

- :func:`flatten` / :func:`unflatten` — tensor-list <-> one contiguous
  numpy buffer (multithreaded memcpy in C++),
- :func:`bucket_plan` — apex-DDP-style arrival-order bucket assignment,
- :class:`StagingPool` stats / trim — aligned host staging-buffer pool,
- :class:`TokenQueue` — blocking MPMC queue backing
  :mod:`apex_tpu.data`'s prefetch loader.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
import weakref
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["available", "flatten", "unflatten", "bucket_plan", "TokenQueue",
           "staging_buffer", "staging_stats", "staging_trim"]

_SRC = os.path.join(os.path.dirname(__file__), os.pardir, "csrc",
                    "host_runtime.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return None
    with open(src, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_BUILD_DIR, f"libapex_host_{tag}.so")
    if not os.path.exists(so):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = so + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
               src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=300)
            os.replace(tmp, so)
        except (OSError, subprocess.SubprocessError):
            return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    lib.apex_flatten.argtypes = [ctypes.POINTER(ctypes.c_void_p),
                                 ctypes.POINTER(ctypes.c_int64),
                                 ctypes.c_int, ctypes.c_void_p]
    lib.apex_unflatten.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_void_p)]
    lib.apex_bucket_plan.argtypes = [ctypes.POINTER(ctypes.c_int64),
                                     ctypes.c_int, ctypes.c_int64,
                                     ctypes.POINTER(ctypes.c_int32)]
    lib.apex_bucket_plan.restype = ctypes.c_int
    lib.apex_queue_create.argtypes = [ctypes.c_int64]
    lib.apex_queue_create.restype = ctypes.c_void_p
    lib.apex_queue_destroy.argtypes = [ctypes.c_void_p]
    lib.apex_queue_put.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.apex_queue_put.restype = ctypes.c_int
    lib.apex_queue_get.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                   ctypes.POINTER(ctypes.c_int64)]
    lib.apex_queue_get.restype = ctypes.c_int
    lib.apex_queue_close.argtypes = [ctypes.c_void_p]
    lib.apex_queue_size.argtypes = [ctypes.c_void_p]
    lib.apex_queue_size.restype = ctypes.c_int64
    lib.apex_staging_stats.argtypes = [ctypes.POINTER(ctypes.c_int64),
                                       ctypes.POINTER(ctypes.c_int64)]
    lib.apex_staging_trim.argtypes = []
    lib.apex_staging_alloc.argtypes = [ctypes.c_int64]
    lib.apex_staging_alloc.restype = ctypes.c_void_p
    lib.apex_staging_free.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is None and not _tried:
        with _lock:
            if _lib is None and not _tried:
                _lib = _build_and_load()
                _tried = True
    return _lib


def available() -> bool:
    """True when the C++ runtime built and loaded."""
    return _get_lib() is not None


def _as_arrays(arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
    return [np.ascontiguousarray(a) for a in arrays]


def staging_buffer(nbytes: int) -> np.ndarray:
    """A uint8 array backed by the C++ aligned staging pool; the buffer
    returns to the pool when the array (and its views) are collected. Falls
    back to a plain numpy allocation without the native library."""
    lib = _get_lib()
    if lib is None or nbytes == 0:
        return np.empty(nbytes, np.uint8)
    ptr = lib.apex_staging_alloc(int(nbytes))
    if not ptr:
        return np.empty(nbytes, np.uint8)
    mem = (ctypes.c_uint8 * nbytes).from_address(ptr)
    arr = np.frombuffer(mem, dtype=np.uint8, count=nbytes)
    # finalize `mem`, NOT `arr`: numpy collapses base chains, so any view of
    # `arr` bases directly on `mem` — attaching the free there guarantees the
    # buffer outlives every view
    weakref.finalize(mem, lib.apex_staging_free, ptr, int(nbytes))
    return arr


def flatten(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """Concatenate arbitrary-dtype host arrays into one uint8 buffer
    (``apex_C.flatten`` role, reference ``csrc/flatten_unflatten.cpp:15``)."""
    arrays = _as_arrays(arrays)
    sizes = [a.nbytes for a in arrays]
    out = staging_buffer(sum(sizes))
    lib = _get_lib()
    if lib is None or not arrays:
        off = 0
        for a, n in zip(arrays, sizes):
            out[off:off + n] = a.view(np.uint8).reshape(-1)
            off += n
        return out
    n = len(arrays)
    srcs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
    nbytes = (ctypes.c_int64 * n)(*sizes)
    lib.apex_flatten(srcs, nbytes, n, out.ctypes.data)
    return out


def unflatten(flat: np.ndarray, like: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Split a flat uint8 buffer back into arrays shaped/typed like ``like``
    (``apex_C.unflatten`` role)."""
    flat = np.ascontiguousarray(flat.view(np.uint8).reshape(-1))
    outs = [np.empty(a.shape, a.dtype) for a in like]
    sizes = [o.nbytes for o in outs]
    if sum(sizes) != flat.nbytes:
        raise ValueError(f"flat buffer has {flat.nbytes} bytes; templates "
                         f"need {sum(sizes)}")
    lib = _get_lib()
    if lib is None or not outs:
        off = 0
        for o, n in zip(outs, sizes):
            o.view(np.uint8).reshape(-1)[:] = flat[off:off + n]
            off += n
        return outs
    n = len(outs)
    dsts = (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
    nbytes = (ctypes.c_int64 * n)(*sizes)
    lib.apex_unflatten(flat.ctypes.data, nbytes, n, dsts)
    return outs


def bucket_plan(nbytes: Sequence[int], cap_bytes: int) -> np.ndarray:
    """Arrival-order bucket ids capped at ``cap_bytes`` per bucket (apex DDP
    bucket learning, reference ``parallel/distributed.py:366-390``)."""
    n = len(nbytes)
    ids = np.zeros(n, dtype=np.int32)
    lib = _get_lib()
    if lib is None:
        bucket, fill = 0, 0
        for i, nb in enumerate(nbytes):
            if fill > 0 and fill + nb > cap_bytes:
                bucket, fill = bucket + 1, 0
            ids[i] = bucket
            fill += nb
            if fill >= cap_bytes:
                bucket, fill = bucket + 1, 0
        return ids
    arr = (ctypes.c_int64 * n)(*[int(x) for x in nbytes])
    lib.apex_bucket_plan(arr, n, int(cap_bytes),
                         ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return ids


def staging_stats():
    """(outstanding allocations, pooled free bytes) of the C++ staging pool."""
    lib = _get_lib()
    if lib is None:
        return (0, 0)
    a, b = ctypes.c_int64(), ctypes.c_int64()
    lib.apex_staging_stats(ctypes.byref(a), ctypes.byref(b))
    return (a.value, b.value)


def staging_trim() -> None:
    lib = _get_lib()
    if lib is not None:
        lib.apex_staging_trim()


class TokenQueue:
    """Blocking bounded MPMC queue over the C++ condvar ring; falls back to
    ``queue.Queue`` when the native library is unavailable."""

    def __init__(self, capacity: int):
        self._lib = _get_lib()
        if self._lib is not None:
            self._q = self._lib.apex_queue_create(capacity)
            self._py = None
        else:
            import queue
            self._q = None
            self._py = queue.Queue(maxsize=capacity)
            self._closed_ev = threading.Event()

    def put(self, token: int) -> bool:
        """Blocks while full. False once the queue is closed."""
        if self._py is not None:
            import queue as _qm
            while not self._closed_ev.is_set():
                try:
                    # poll in slices so close() is observed mid-block
                    self._py.put(int(token), timeout=0.1)
                    return True
                except _qm.Full:
                    continue
            return False
        return self._lib.apex_queue_put(self._q, int(token)) == 0

    def get(self, timeout_ms: int = -1) -> Optional[int]:
        """Blocks while empty. None at end-of-stream (closed + drained);
        raises TimeoutError on timeout."""
        if self._py is not None:
            import queue as _qm
            while True:
                try:
                    # poll in slices so close() is observed even with an
                    # infinite timeout
                    return self._py.get(
                        timeout=0.1 if timeout_ms < 0 else timeout_ms / 1e3)
                except _qm.Empty:
                    if self._closed_ev.is_set() and self._py.empty():
                        return None
                    if timeout_ms >= 0:
                        raise TimeoutError("queue.get timed out")
        tok = ctypes.c_int64()
        rc = self._lib.apex_queue_get(self._q, int(timeout_ms),
                                      ctypes.byref(tok))
        if rc == 0:
            return tok.value
        if rc == -1:
            return None
        raise TimeoutError("queue.get timed out")

    def close(self) -> None:
        if self._py is not None:
            self._closed_ev.set()
            return
        if self._q is not None:
            self._lib.apex_queue_close(self._q)

    def __len__(self) -> int:
        if self._py is not None:
            return self._py.qsize()
        return int(self._lib.apex_queue_size(self._q))

    def __del__(self):
        try:
            if self._py is None and self._q is not None:
                self._lib.apex_queue_close(self._q)
                self._lib.apex_queue_destroy(self._q)
                self._q = None
        except Exception:
            pass
