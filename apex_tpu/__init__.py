"""apex_tpu — a TPU-native training-acceleration framework.

A from-scratch reimplementation of the *capabilities* of NVIDIA Apex
(reference: zhaoguochun1995/apex, ``apex/__init__.py:8``) on JAX/XLA/Pallas:

- ``apex_tpu.amp``            — mixed-precision policies (O0–O3) + dynamic loss scaling
- ``apex_tpu.optimizers``     — fused optimizers (Adam/LAMB/SGD/NovoGrad/Adagrad/…)
- ``apex_tpu.normalization``  — fused LayerNorm / RMSNorm (Pallas kernels)
- ``apex_tpu.parallel``       — data parallelism, SyncBatchNorm, LARC
- ``apex_tpu.transformer``    — Megatron-style tensor/sequence/pipeline/context parallelism
- ``apex_tpu.ops``            — Pallas TPU kernels (norms, softmax, rope, attention, xentropy)
- ``apex_tpu.contrib``        — optional extensions (focal loss, group norm, transducer, …)
- ``apex_tpu.native``         — C++ host runtime (flatten/bucketing/staging pool/queues)
- ``apex_tpu.data``           — prefetching host→device pipeline on the native queue
- ``apex_tpu.resilience``     — fault-tolerant training driver (watchdog, rollback, retrying checkpoints)
- ``apex_tpu.observability``  — metrics/tracing (step metrics, MFU, sinks) + ``python -m apex_tpu.monitor`` run reports

Where the reference dispatches CUDA kernels through pybind11 extensions
(``setup.py:110-860``), this package dispatches Pallas TPU kernels with pure-XLA
fallbacks; where the reference speaks NCCL through ``torch.distributed``
(SURVEY.md §2.5), this package speaks XLA collectives over a ``jax.sharding.Mesh``.
"""

from apex_tpu import amp
from apex_tpu import checkpoint
from apex_tpu import data
from apex_tpu import fp16_utils
from apex_tpu import fused_dense
from apex_tpu import mlp
from apex_tpu import multi_tensor_apply
from apex_tpu import native
from apex_tpu import normalization
from apex_tpu import observability
from apex_tpu import ops
from apex_tpu import optimizers
from apex_tpu import parallel
from apex_tpu import resilience
from apex_tpu import rnn
from apex_tpu import transformer
from apex_tpu.utils.logging import get_logger, RankInfoFormatter
from apex_tpu.utils.deprecation import deprecated_warning

__version__ = "0.1.0"

__all__ = [
    "amp",
    "checkpoint",
    "data",
    "native",
    "fp16_utils",
    "fused_dense",
    "mlp",
    "multi_tensor_apply",
    "normalization",
    "observability",
    "ops",
    "optimizers",
    "parallel",
    "resilience",
    "rnn",
    "transformer",
    "get_logger",
    "RankInfoFormatter",
    "deprecated_warning",
    "__version__",
]
