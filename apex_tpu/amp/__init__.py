"""Mixed-precision training (capability of ``apex/amp``).

The reference implements amp by monkey-patching torch namespaces at runtime
(``apex/amp/amp.py:74-183``) — not possible or desirable under JAX tracing.
The TPU-native design is a *policy* applied at function boundaries
(``Policy(param_dtype, compute_dtype, output_dtype)``) plus a functional
dynamic loss scaler carried as jittable state, preserving the reference's
semantics: O0–O3 opt levels (``apex/amp/frontend.py:104-193``), dynamic loss
scaling with overflow skip-step (``apex/amp/scaler.py:33-217``,
``apex/amp/handle.py:17-158``), and scaler ``state_dict`` round-trip
(``apex/amp/frontend.py:365-404``).
"""

from apex_tpu.amp.policy import (
    Policy,
    disable_casts,
    half_function,
    float_function,
    master_params,
    promote_function,
    register_float_function,
    register_half_function,
    register_promote_function,
)
from apex_tpu.amp.scaler import LossScaler, LossScalerState, all_finite
from apex_tpu.amp.frontend import (
    AmpState,
    Properties,
    initialize,
    state_dict,
    load_state_dict,
    OPT_LEVELS,
)
from apex_tpu.amp.handle import scale_loss, unscale_and_update, apply_if_finite
from apex_tpu.amp import fp8

__all__ = [
    "fp8",
    "Policy",
    "disable_casts",
    "half_function",
    "float_function",
    "master_params",
    "promote_function",
    "register_float_function",
    "register_half_function",
    "register_promote_function",
    "LossScaler",
    "LossScalerState",
    "all_finite",
    "AmpState",
    "Properties",
    "initialize",
    "state_dict",
    "load_state_dict",
    "OPT_LEVELS",
    "scale_loss",
    "unscale_and_update",
    "apply_if_finite",
]
