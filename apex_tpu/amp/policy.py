"""Precision policies applied at function boundaries.

Replaces the reference's per-op cast lists and monkey-patching
(``apex/amp/lists/*.py``, ``apex/amp/wrap.py:10-276``) with an explicit,
trace-friendly policy object. The decorators below reproduce the public
``amp.half_function`` / ``float_function`` / ``promote_function`` registration
API (``apex/amp/amp.py:30-48``) as plain function wrappers.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils.tree import tree_cast

# amp.disable_casts flips this (reference ``handle.py:164-167`` turns the
# handle inactive); wrappers built by this module check it per call. A
# ContextVar so a disable in one thread/async context never leaks into a
# concurrently training one.
_casts_enabled = contextvars.ContextVar(
    "apex_tpu_amp_casts_enabled", default=True)


@contextlib.contextmanager
def disable_casts():
    """Context manager suspending all policy/decorator casts
    (reference ``amp.disable_casts``, ``apex/amp/handle.py:164``).

    TRACE-TIME SEMANTICS: under ``jax.jit`` the flag is read when the
    function is *traced* — which happens at the first CALL, not at
    ``jax.jit(...)`` construction — and cached traces are reused, so
    entering this context around an already-warm jitted function does NOT
    retrace it. Keep separate jitted variants and make each one's first
    (tracing) call inside the right context::

        eval_fn = jax.jit(fn)                      # casts baked in
        debug_fn = jax.jit(lambda *a: fn(*a))      # distinct cache
        with amp.disable_casts():
            debug_fn(example_args)                 # traces NOW, casts off
        debug_fn(real_args)                        # reuses the no-cast trace
    """
    token = _casts_enabled.set(False)
    try:
        yield
    finally:
        _casts_enabled.reset(token)


@dataclasses.dataclass(frozen=True)
class Policy:
    """``Policy(param_dtype, compute_dtype, output_dtype)``.

    ``bf16`` is the TPU-native half type (fp16 is supported for parity; it is
    what makes the loss scaler load-bearing).
    """

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32

    def cast_to_param(self, tree):
        return tree_cast(tree, self.param_dtype)

    def cast_to_compute(self, tree):
        return tree_cast(tree, self.compute_dtype)

    def cast_to_output(self, tree):
        return tree_cast(tree, self.output_dtype)

    def wrap(self, fn: Callable) -> Callable:
        """Run ``fn`` with inputs cast to compute dtype, outputs to output dtype."""

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            if not _casts_enabled.get():
                return fn(*args, **kwargs)
            args = self.cast_to_compute(args)
            kwargs = self.cast_to_compute(kwargs)
            out = fn(*args, **kwargs)
            return self.cast_to_output(out)

        return wrapped

    @staticmethod
    def from_names(names: str) -> "Policy":
        """Parse ``"params=float32,compute=bfloat16,output=float32"`` or the
        short form ``"p=f32,c=bf16,o=f32"``."""
        mapping = {
            "f32": jnp.float32,
            "float32": jnp.float32,
            "bf16": jnp.bfloat16,
            "bfloat16": jnp.bfloat16,
            "f16": jnp.float16,
            "float16": jnp.float16,
        }
        kw = {}
        for part in names.split(","):
            k, v = part.split("=")
            k = {"p": "param_dtype", "params": "param_dtype",
                 "c": "compute_dtype", "compute": "compute_dtype",
                 "o": "output_dtype", "output": "output_dtype"}[k.strip()]
            kw[k] = mapping[v.strip()]
        return Policy(**kw)


def _cast_fn(fn: Callable, dtype) -> Callable:
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if not _casts_enabled.get():
            return fn(*args, **kwargs)
        args = tree_cast(args, dtype)
        kwargs = tree_cast(kwargs, dtype)
        return fn(*args, **kwargs)

    return wrapped


def half_function(fn: Callable, dtype=jnp.bfloat16) -> Callable:
    """Always run ``fn`` in half precision (reference: ``amp/amp.py:30``)."""
    return _cast_fn(fn, dtype)


def float_function(fn: Callable) -> Callable:
    """Always run ``fn`` in fp32 (reference: ``amp/amp.py:38``)."""
    return _cast_fn(fn, jnp.float32)


def promote_function(fn: Callable) -> Callable:
    """Run ``fn`` in the widest floating dtype among its arguments
    (reference: ``amp/amp.py:46``; promote wrapper ``amp/wrap.py:92-116``)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if not _casts_enabled.get():
            return fn(*args, **kwargs)
        leaves = jax.tree_util.tree_leaves((args, kwargs))
        f_dtypes = [x.dtype for x in leaves
                    if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)]
        if not f_dtypes:
            return fn(*args, **kwargs)
        target = functools.reduce(jnp.promote_types, f_dtypes)
        args = tree_cast(args, target)
        kwargs = tree_cast(kwargs, target)
        return fn(*args, **kwargs)

    return wrapped


def register_half_function(module, name: str, dtype=jnp.bfloat16) -> None:
    """Rebind ``module.name`` to its half-cast wrapper in place
    (reference: ``amp.register_half_function``, ``apex/amp/amp.py:52``) —
    the one deliberate monkey-patch kept from the reference's design, for
    third-party functions you can't decorate at definition site."""
    setattr(module, name, half_function(getattr(module, name), dtype))


def register_float_function(module, name: str) -> None:
    """Reference: ``amp.register_float_function`` (``amp/amp.py:59``)."""
    setattr(module, name, float_function(getattr(module, name)))


def register_promote_function(module, name: str) -> None:
    """Reference: ``amp.register_promote_function`` (``amp/amp.py:66``)."""
    setattr(module, name, promote_function(getattr(module, name)))


def master_params(opt_state) -> list:
    """The fp32 master storage held by an optimizer state, when the
    optimizer keeps it (``master_weights=True`` / O2), else ``[]``
    (reference: ``amp.master_params``, ``apex/amp/_amp_state.py:50-58``,
    which yields whatever the optimizer's param groups own).

    Shape caveat, same as the reference: leaves mirror the optimizer's own
    storage layout. ``FusedAdam(master_weights=True)`` & co. keep one fp32
    leaf per parameter; ZeRO-sharded optimizers
    (``DistributedFusedAdam/LAMB``) keep a single zero-padded
    ``[dp, ..., chunk]`` flat buffer — for per-parameter views of those, use
    the optimizer's ``state_dict``."""
    if isinstance(opt_state, dict) and "master" in opt_state:
        return jax.tree_util.tree_leaves(opt_state["master"])
    if isinstance(opt_state, dict) and "master_rem" in opt_state:
        # DistributedFusedAdam(store_param_remainders=True): the master is
        # SPLIT — the params hold its top 16 bits, the state only the
        # int16 remainder, so there is no standalone fp32 buffer to hand
        # out and silently returning [] would misreport an O2-style run
        raise ValueError(
            "this optimizer state stores masters as bf16-param + int16 "
            "remainder (store_param_remainders=True); reconstruct them "
            "with DistributedFusedAdam._master_from_remainder(param_shard, "
            "state['master_rem']) — there is no standalone fp32 master "
            "buffer to return")
    master = getattr(opt_state, "master_params", None)   # FP16OptimizerState
    if master is not None:
        return jax.tree_util.tree_leaves(master)
    return []
