"""Precision policies applied at function boundaries.

Replaces the reference's per-op cast lists and monkey-patching
(``apex/amp/lists/*.py``, ``apex/amp/wrap.py:10-276``) with an explicit,
trace-friendly policy object. The decorators below reproduce the public
``amp.half_function`` / ``float_function`` / ``promote_function`` registration
API (``apex/amp/amp.py:30-48``) as plain function wrappers.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils.tree import tree_cast


@dataclasses.dataclass(frozen=True)
class Policy:
    """``Policy(param_dtype, compute_dtype, output_dtype)``.

    ``bf16`` is the TPU-native half type (fp16 is supported for parity; it is
    what makes the loss scaler load-bearing).
    """

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32

    def cast_to_param(self, tree):
        return tree_cast(tree, self.param_dtype)

    def cast_to_compute(self, tree):
        return tree_cast(tree, self.compute_dtype)

    def cast_to_output(self, tree):
        return tree_cast(tree, self.output_dtype)

    def wrap(self, fn: Callable) -> Callable:
        """Run ``fn`` with inputs cast to compute dtype, outputs to output dtype."""

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            args = self.cast_to_compute(args)
            kwargs = self.cast_to_compute(kwargs)
            out = fn(*args, **kwargs)
            return self.cast_to_output(out)

        return wrapped

    @staticmethod
    def from_names(names: str) -> "Policy":
        """Parse ``"params=float32,compute=bfloat16,output=float32"`` or the
        short form ``"p=f32,c=bf16,o=f32"``."""
        mapping = {
            "f32": jnp.float32,
            "float32": jnp.float32,
            "bf16": jnp.bfloat16,
            "bfloat16": jnp.bfloat16,
            "f16": jnp.float16,
            "float16": jnp.float16,
        }
        kw = {}
        for part in names.split(","):
            k, v = part.split("=")
            k = {"p": "param_dtype", "params": "param_dtype",
                 "c": "compute_dtype", "compute": "compute_dtype",
                 "o": "output_dtype", "output": "output_dtype"}[k.strip()]
            kw[k] = mapping[v.strip()]
        return Policy(**kw)


def _cast_fn(fn: Callable, dtype) -> Callable:
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        args = tree_cast(args, dtype)
        kwargs = tree_cast(kwargs, dtype)
        return fn(*args, **kwargs)

    return wrapped


def half_function(fn: Callable, dtype=jnp.bfloat16) -> Callable:
    """Always run ``fn`` in half precision (reference: ``amp/amp.py:30``)."""
    return _cast_fn(fn, dtype)


def float_function(fn: Callable) -> Callable:
    """Always run ``fn`` in fp32 (reference: ``amp/amp.py:38``)."""
    return _cast_fn(fn, jnp.float32)


def promote_function(fn: Callable) -> Callable:
    """Run ``fn`` in the widest floating dtype among its arguments
    (reference: ``amp/amp.py:46``; promote wrapper ``amp/wrap.py:92-116``)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        leaves = jax.tree_util.tree_leaves((args, kwargs))
        f_dtypes = [x.dtype for x in leaves
                    if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)]
        if not f_dtypes:
            return fn(*args, **kwargs)
        target = functools.reduce(jnp.promote_types, f_dtypes)
        args = tree_cast(args, target)
        kwargs = tree_cast(kwargs, target)
        return fn(*args, **kwargs)

    return wrapped
