"""fp8 delayed scaling — amax tracking, scale computation, quantize hooks.

Capability analog of the reference's fp8 plumbing: the reference itself only
builds the AMAX reduction process groups (``apex/transformer/parallel_state.py
:280-292``) that TransformerEngine-style delayed scaling consumes; the actual
recipe (amax history window -> scale, margin, e4m3 fwd / e5m2 bwd) is the
public TE delayed-scaling algorithm, implemented here fresh in functional JAX
form so it jits and shards:

- per-tensor state = ``{"amax_history": [H], "scale": []}`` carried as a
  pytree through the train step (no mutable globals — the TPU analog of the
  reference's capturable no-host-sync design);
- amax reduction over *mesh axes* instead of a process group:
  ``lax.pmax(amax, parallel_state.amax_reduction_axes())`` inside
  ``shard_map`` — every rank holding shards/replicas of one tensor agrees on
  its scale (reference group = TP x DP per pipeline stage);
- quantization is qdq (quantize-dequantize): values round-trip through the
  fp8 storage dtype and come back in the compute dtype, so any matmul can be
  "fp8-simulated" today and swapped for native fp8 ``dot_general`` where the
  TPU generation supports it (v5p+/Trillium).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "Fp8Recipe",
    "E4M3",
    "E5M2",
    "fp8_max",
    "init_fp8_state",
    "compute_amax",
    "reduce_amaxes",
    "update_fp8_state",
    "quantize",
    "dequantize",
    "qdq",
    "fp8_dense",
    "native_fp8_dot_supported",
]

# Storage dtypes: e4m3 for forward activations/weights (more mantissa),
# e5m2 for backward gradients (more range) — the standard hybrid recipe.
E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2

_FP8_MAX = {E4M3: 448.0, E5M2: 57344.0}


def fp8_max(dtype) -> float:
    """Largest finite value representable in the fp8 storage dtype."""
    return _FP8_MAX[jnp.dtype(dtype).type if not isinstance(dtype, type)
                    else dtype]


@dataclasses.dataclass(frozen=True)
class Fp8Recipe:
    """Delayed-scaling hyperparameters (TE ``DelayedScaling`` semantics)."""

    margin: int = 0                    # scale headroom: 2**margin
    amax_history_len: int = 16         # rolling window of per-step amaxes
    amax_compute_algo: str = "max"     # "max" over window | "most_recent"
    fwd_dtype: Any = E4M3
    bwd_dtype: Any = E5M2

    def __post_init__(self):
        if self.amax_history_len < 1:
            raise ValueError("amax_history_len must be >= 1")
        if self.amax_compute_algo not in ("max", "most_recent"):
            raise ValueError(
                f"amax_compute_algo must be 'max' or 'most_recent', got "
                f"{self.amax_compute_algo!r}")


def init_fp8_state(names: Sequence[str],
                   recipe: Fp8Recipe = Fp8Recipe()) -> Dict[str, Any]:
    """State pytree: one ``{"amax_history": [H], "scale": []}`` per tensor
    name. Scales start at 1.0 (identity until the first update)."""
    return {
        n: {
            "amax_history": jnp.zeros((recipe.amax_history_len,),
                                      jnp.float32),
            "scale": jnp.ones((), jnp.float32),
        }
        for n in names
    }


def compute_amax(x: jax.Array) -> jax.Array:
    """Current-step absolute maximum (fp32 scalar)."""
    return jnp.max(jnp.abs(x)).astype(jnp.float32)


def reduce_amaxes(amaxes, axis_names: Optional[Sequence[str]] = None):
    """pmax each amax over the bound reduction axes — the collective the
    reference's ``_AMAX_REDUCTION_GROUP`` exists for. Outside ``shard_map``
    (or with no bound axes) this is the identity."""
    if axis_names is None:
        from apex_tpu.transformer.parallel_state import amax_reduction_axes
        axis_names = amax_reduction_axes()
    from apex_tpu.utils.sharding import bound_axes
    axes = bound_axes(axis_names)
    if not axes:
        return amaxes
    return jax.tree.map(lambda a: lax.pmax(a, axes), amaxes)


def _new_scale(history: jax.Array, old_scale: jax.Array,
               recipe: Fp8Recipe, dtype) -> jax.Array:
    amax = (jnp.max(history) if recipe.amax_compute_algo == "max"
            else history[0])
    sf = fp8_max(dtype) / (amax * (2.0 ** recipe.margin))
    # amax == 0 (nothing observed yet) or non-finite keeps the previous
    # scale; requiring sf > 0 also rejects amax = inf -> sf = 0.0
    return jnp.where(jnp.isfinite(amax) & (sf > 0.0) & jnp.isfinite(sf),
                     sf, old_scale)


def update_fp8_state(state: Dict[str, Any], amaxes: Dict[str, jax.Array],
                     recipe: Fp8Recipe = Fp8Recipe(), *,
                     axis_names: Optional[Sequence[str]] = None,
                     dtypes: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """One delayed-scaling step: reduce this step's ``amaxes`` over the
    amax-reduction axes, roll each history window, recompute scales.

    ``dtypes`` optionally maps tensor name -> storage dtype (default:
    ``recipe.fwd_dtype``; pass ``recipe.bwd_dtype`` for gradient tensors).
    """
    amaxes = reduce_amaxes(amaxes, axis_names)
    new = {}
    for name, s in state.items():
        # overflow steps record amax=inf; storing it would pin the window
        # max at inf (scale frozen for the whole history) and a naive
        # fp8_max/inf = 0.0 scale would NaN every dequantize — record 0
        # instead (TE behavior: non-finite amaxes don't update the scale)
        a = amaxes[name]
        a = jnp.where(jnp.isfinite(a), a, 0.0)
        hist = jnp.roll(s["amax_history"], 1)
        hist = hist.at[0].set(a)
        dt = (dtypes or {}).get(name, recipe.fwd_dtype)
        new[name] = {
            "amax_history": hist,
            "scale": _new_scale(hist, s["scale"], recipe, dt),
        }
    return new


def quantize(x: jax.Array, scale: jax.Array, dtype=E4M3) -> jax.Array:
    """Scale into the fp8 representable range and cast to storage dtype."""
    clipped = jnp.clip(x.astype(jnp.float32) * scale,
                       -fp8_max(dtype), fp8_max(dtype))
    return clipped.astype(dtype)


def dequantize(xq: jax.Array, scale: jax.Array, dtype=jnp.float32):
    return (xq.astype(jnp.float32) / scale).astype(dtype)


def qdq(x: jax.Array, scale: jax.Array, dtype=E4M3) -> jax.Array:
    """Quantize-dequantize: fp8 rounding applied, original dtype returned —
    the simulation hook a Policy/layer wraps around matmul operands until
    native fp8 ``dot_general`` is wired for the target TPU generation."""
    return dequantize(quantize(x, scale, dtype), scale, x.dtype)


@functools.lru_cache(maxsize=None)
def native_fp8_dot_supported() -> bool:
    """Probe: can this backend compile AND run ``dot_general`` directly on
    fp8 storage dtypes. True on current TPU backends (older generations
    upcast internally — numerics identical, speed gain arrives on
    fp8-capable MXUs, v6e/Trillium+); False lets callers keep the qdq
    simulation. Cached per process."""
    try:
        # the probe may be reached while tracing (fp8_dense under jit):
        # escape the trace so it runs eagerly on the backend — otherwise
        # the test-execution would stage into the caller's graph and fail,
        # caching a spurious False
        with jax.ensure_compile_time_eval():
            a = jnp.zeros((8, 8), E4M3)
            y = jax.jit(lambda a, b: lax.dot_general(
                a, b, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))(a, a)
            y.block_until_ready()
        return True
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def _log_fp8_path_once(native: bool) -> None:
    """One-time notice of which fp8 dot path auto-probe selected: the
    native and qdq paths round at different points (documented in
    fp8_dense), so the same model yields tolerance-level different
    losses across backends — the user should know which one ran
    (ADVICE r3). Pass ``native=`` explicitly to pin a path and silence
    this."""
    from apex_tpu.utils.logging import get_logger

    # warning level: the repo logger's default threshold — the notice must
    # reach users with unconfigured logging (it explains tolerance-level
    # loss differences across backends)
    get_logger().warning(
        "fp8_dense auto-probe selected the %s path on this backend "
        "(native and qdq round at different points; pass native= to pin)",
        "native-fp8 dot" if native else "qdq simulation")


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _native_fp8_matmul(x, w, xs, ws, recipe):
    """``y = (q(x) @ q(w)) / (xs*ws)`` with the dot running ON the fp8
    storage dtypes (native path). Forward operands are e4m3; the backward
    quantizes the incoming cotangent to e5m2 with current scaling and runs
    both grad dots on fp8 operands too (TE hybrid recipe)."""
    xq = quantize(x, xs, recipe.fwd_dtype)
    wq = quantize(w, ws, recipe.fwd_dtype)
    y = lax.dot_general(xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    return (y / (xs * ws)).astype(x.dtype)


def _native_fwd(x, w, xs, ws, recipe):
    xq = quantize(x, xs, recipe.fwd_dtype)
    wq = quantize(w, ws, recipe.fwd_dtype)
    y = lax.dot_general(xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    return ((y / (xs * ws)).astype(x.dtype),
            # zero-size carriers: residuals must be JAX types, not dtypes
            (xq, wq, xs, ws, jnp.zeros((0,), x.dtype),
             jnp.zeros((0,), w.dtype)))


def _native_bwd(recipe, res, g):
    xq, wq, xs, ws, xdt_c, wdt_c = res
    xdt, wdt = xdt_c.dtype, wdt_c.dtype
    amax = jnp.max(jnp.abs(g)).astype(jnp.float32)
    gs = jnp.where((amax > 0.0) & jnp.isfinite(amax),
                   fp8_max(recipe.bwd_dtype) / amax, 1.0)
    gq = quantize(g, gs, recipe.bwd_dtype)
    # dx = g @ w^T ; dw = x^T @ g — both on fp8 operands
    dx = lax.dot_general(gq, wq, (((g.ndim - 1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dx = (dx / (gs * ws)).astype(xdt)
    lead = tuple(range(g.ndim - 1))
    dw = lax.dot_general(xq, gq, ((lead, lead), ((), ())),
                         preferred_element_type=jnp.float32)
    dw = (dw / (xs * gs)).astype(wdt)
    return dx, dw, jnp.zeros_like(xs), jnp.zeros_like(ws)


_native_fp8_matmul.defvjp(_native_fwd, _native_bwd)


def fp8_dense(x: jax.Array, w: jax.Array, state: Dict[str, Any],
              *, x_name: str = "x", w_name: str = "w",
              recipe: Fp8Recipe = Fp8Recipe(),
              axis_names: Optional[Sequence[str]] = None,
              native: Optional[bool] = None
              ) -> Tuple[jax.Array, Dict[str, Any]]:
    """fp8 delayed-scaling matmul hook: ``y = qdq(x) @ qdq(w)`` with the
    CURRENT scales, returning ``(y, new_state)`` where the state absorbed
    this step's amaxes (reduced over the amax axes). The standard usage —
    scales trail the data by one step, exactly TE delayed scaling:

        y, fp8_state = fp8.fp8_dense(x, w, fp8_state)

    The backward: quantization itself is straight-through (identity
    derivative), and the incoming cotangent is qdq'd into
    ``recipe.bwd_dtype`` (e5m2) with *current* scaling — its scale computed
    from the cotangent's own amax on the spot, since the backward cannot
    thread delayed state out of the vjp — so gradient-path fp8 effects are
    simulated too (TE's hybrid recipe; current scaling is one of its
    supported amax modes).

    ``native`` routes the dot through fp8 storage dtypes directly
    (``_native_fp8_matmul``) instead of the qdq simulation; ``None``
    auto-probes the backend (``native_fp8_dot_supported``). Both paths
    share the delayed-scaling state machinery and differ only in where the
    fp8 values live during the dot (fp32 accumulation either way).
    """
    xs = state[x_name]["scale"]
    ws = state[w_name]["scale"]
    if native is None:
        native = native_fp8_dot_supported()
        _log_fp8_path_once(native)
    if native:
        y = _native_fp8_matmul(x, w, xs, ws, recipe)
    else:
        xq = _ste_qdq(x, xs, recipe.fwd_dtype, recipe.bwd_dtype)
        wq = _ste_qdq(w, ws, recipe.fwd_dtype, recipe.bwd_dtype)
        y = xq @ wq
    new_state = dict(state)
    upd = update_fp8_state(
        {x_name: state[x_name], w_name: state[w_name]},
        {x_name: compute_amax(x), w_name: compute_amax(w)},
        recipe, axis_names=axis_names)
    new_state.update(upd)
    return y, new_state


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _ste_qdq(x, scale, dtype, bwd_dtype=None):
    return qdq(x, scale, dtype)


def _ste_fwd(x, scale, dtype, bwd_dtype):
    return qdq(x, scale, dtype), scale


def _ste_bwd(dtype, bwd_dtype, scale, g):
    # straight-through: d qdq/dx ~= 1 (no cotangent into the scale, which
    # is statistics-driven, not loss-driven). The cotangent itself is
    # e5m2-simulated with current scaling when a bwd_dtype is set.
    if bwd_dtype is not None:
        amax = jnp.max(jnp.abs(g)).astype(jnp.float32)
        gs = jnp.where((amax > 0.0) & jnp.isfinite(amax),
                       fp8_max(bwd_dtype) / amax, 1.0)
        g = qdq(g, gs, bwd_dtype)
    return g, jnp.zeros_like(scale)


_ste_qdq.defvjp(_ste_fwd, _ste_bwd)
