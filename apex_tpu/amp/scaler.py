"""Dynamic loss scaling as jittable functional state.

Semantics ported (not code) from the reference's ``LossScaler``
(``apex/amp/scaler.py:33-217``) and the hysteresis variant used by the
capturable/CUDA-graph path (``csrc/update_scale_hysteresis.cu:5-48``):

- overflow → consume one hysteresis credit; when credits are exhausted the
  scale is multiplied by ``1/scale_factor`` (floored at ``min_loss_scale``)
  and the growth tracker resets;
- ``scale_window`` consecutive finite steps → scale grows by ``scale_factor``
  (capped at ``max_loss_scale``) and hysteresis credits refill.

Unlike the reference's eager path — which does a device→host sync per step to
read the overflow flag (``scaler.py:197-217``) — everything here stays on
device; the "skip step" is a ``jnp.where`` select, mirroring the design of the
capturable FusedAdam (``apex/optimizers/fused_adam.py:199-263``).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import chex
import jax
import jax.numpy as jnp


def all_finite(tree: Any) -> jax.Array:
    """Fused finiteness check over a pytree (capability of
    ``amp_C.multi_tensor_scale``'s inf/nan flag, ``csrc/multi_tensor_scale_kernel.cu``)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.array(True)
    finite = [jnp.all(jnp.isfinite(x)) for x in leaves]
    return jnp.stack(finite).all()


@chex.dataclass
class LossScalerState:
    loss_scale: jax.Array          # f32 scalar
    growth_tracker: jax.Array      # i32 scalar — consecutive finite steps
    hysteresis_tracker: jax.Array  # i32 scalar — overflow credits remaining
    unskipped: jax.Array           # i32 scalar — steps since last skip (state_dict parity)


class LossScaler:
    """Static or dynamic loss scaler.

    ``LossScaler("dynamic")`` matches the reference default
    (init 2**16, factor 2, window 2000, ``apex/amp/scaler.py:33-60``);
    ``LossScaler(128.0)`` gives a static scale.
    """

    def __init__(
        self,
        loss_scale: Any = "dynamic",
        init_scale: float = 2.0 ** 16,
        scale_factor: float = 2.0,
        scale_window: int = 2000,
        min_loss_scale: Optional[float] = None,
        max_loss_scale: float = 2.0 ** 24,
        hysteresis: int = 1,
    ):
        self.dynamic = loss_scale == "dynamic"
        self._init_scale = float(init_scale if self.dynamic else loss_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_loss_scale = float(min_loss_scale) if min_loss_scale is not None else 1.0
        self.max_loss_scale = float(max_loss_scale)
        self.hysteresis = int(hysteresis)

    # -- state ------------------------------------------------------------
    def init(self) -> LossScalerState:
        return LossScalerState(
            loss_scale=jnp.asarray(self._init_scale, jnp.float32),
            growth_tracker=jnp.zeros((), jnp.int32),
            hysteresis_tracker=jnp.asarray(self.hysteresis, jnp.int32),
            unskipped=jnp.zeros((), jnp.int32),
        )

    # -- per-step ops (all jittable) --------------------------------------
    def scale(self, loss: jax.Array, state: LossScalerState) -> jax.Array:
        """``amp.scale_loss`` body (``apex/amp/handle.py:113``)."""
        return loss.astype(jnp.float32) * state.loss_scale

    def unscale(
        self, grads: Any, state: LossScalerState
    ) -> Tuple[Any, jax.Array]:
        """Unscale gradients and report overflow.

        One fused multiply over the grad pytree + finiteness reduction —
        the ``multi_tensor_scale`` capability (``apex/amp/scaler.py:105-119``).
        Non-finite gradients are zeroed so downstream optimizer math stays
        finite; the step is skipped via :func:`update` / ``apply_if_finite``.
        """
        inv = 1.0 / state.loss_scale
        found_inf = jnp.logical_not(all_finite(grads))
        grads = jax.tree_util.tree_map(
            lambda g: jnp.where(
                jnp.isfinite(g), g.astype(jnp.float32) * inv, 0.0
            ).astype(g.dtype),
            grads,
        )
        return grads, found_inf

    def update(self, state: LossScalerState, found_inf: jax.Array) -> LossScalerState:
        """Scale-update with hysteresis (``update_scale_hysteresis.cu:5-48``)."""
        if not self.dynamic:
            return LossScalerState(
                loss_scale=state.loss_scale,
                growth_tracker=state.growth_tracker,
                hysteresis_tracker=state.hysteresis_tracker,
                unskipped=jnp.where(found_inf, 0, state.unskipped + 1),
            )
        hyst = jnp.where(found_inf, state.hysteresis_tracker - 1, state.hysteresis_tracker)
        do_backoff = jnp.logical_and(found_inf, hyst <= 0)
        new_scale = jnp.where(
            do_backoff,
            jnp.maximum(state.loss_scale / self.scale_factor, self.min_loss_scale),
            state.loss_scale,
        )
        growth = jnp.where(found_inf, 0, state.growth_tracker + 1)
        do_growth = growth >= self.scale_window
        new_scale = jnp.where(
            do_growth,
            jnp.minimum(new_scale * self.scale_factor, self.max_loss_scale),
            new_scale,
        )
        growth = jnp.where(do_growth, 0, growth)
        hyst = jnp.where(do_backoff | do_growth, self.hysteresis, hyst)
        return LossScalerState(
            loss_scale=new_scale,
            growth_tracker=growth.astype(jnp.int32),
            hysteresis_tracker=hyst.astype(jnp.int32),
            unskipped=jnp.where(found_inf, 0, state.unskipped + 1).astype(jnp.int32),
        )

    # -- persistence (reference: apex/amp/frontend.py:365-404) -------------
    def state_dict(self, state: LossScalerState) -> dict:
        return {
            "loss_scale": float(state.loss_scale),
            "growth_tracker": int(state.growth_tracker),
            "hysteresis_tracker": int(state.hysteresis_tracker),
            "unskipped": int(state.unskipped),
        }

    def load_state_dict(self, d: dict) -> LossScalerState:
        return LossScalerState(
            loss_scale=jnp.asarray(d["loss_scale"], jnp.float32),
            growth_tracker=jnp.asarray(d.get("growth_tracker", 0), jnp.int32),
            hysteresis_tracker=jnp.asarray(
                d.get("hysteresis_tracker", self.hysteresis), jnp.int32
            ),
            unskipped=jnp.asarray(d.get("unskipped", 0), jnp.int32),
        )
