"""amp opt-level frontend.

Reimagines ``amp.initialize(models, optimizers, opt_level="O0..O3")``
(``apex/amp/frontend.py:197``) for a functional framework: instead of mutating
models/optimizers in place, :func:`initialize` returns an :class:`AmpState`
bundling the precision :class:`Policy`, loss scalers (one per loss,
``num_losses`` parity), and the O-level properties table
(``apex/amp/frontend.py:104-193``).

Opt-level semantics, translated to TPU dtypes (bf16 default half type):

- **O0** — fp32 everything; loss scale 1.
- **O1** — fp32 params, half compute at op boundaries ("cast per-call");
  dynamic loss scale. The reference patches torch namespaces; here the policy
  is applied via ``Policy.wrap`` / module integration.
- **O2** — half params + half compute, fp32 master weights in the optimizer,
  fp32 batchnorm, dynamic loss scale.
- **O3** — half everything, no master weights, loss scale 1.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import jax.numpy as jnp

from apex_tpu.amp.policy import Policy
from apex_tpu.amp.scaler import LossScaler, LossScalerState
from apex_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclasses.dataclass
class Properties:
    """Mirror of amp ``Properties`` (``apex/amp/frontend.py:9-101``)."""

    enabled: bool = False
    opt_level: Optional[str] = None
    cast_model_type: Optional[Any] = None
    cast_ops: bool = False              # "patch_torch_functions" analog
    keep_batchnorm_fp32: Optional[bool] = None
    master_weights: Optional[bool] = None
    loss_scale: Any = 1.0


def _o0() -> Properties:
    return Properties(enabled=True, opt_level="O0", cast_model_type=jnp.float32,
                      cast_ops=False, keep_batchnorm_fp32=None,
                      master_weights=False, loss_scale=1.0)


def _o1() -> Properties:
    return Properties(enabled=True, opt_level="O1", cast_model_type=None,
                      cast_ops=True, keep_batchnorm_fp32=None,
                      master_weights=False, loss_scale="dynamic")


def _o2() -> Properties:
    return Properties(enabled=True, opt_level="O2", cast_model_type=jnp.bfloat16,
                      cast_ops=False, keep_batchnorm_fp32=True,
                      master_weights=True, loss_scale="dynamic")


def _o3() -> Properties:
    return Properties(enabled=True, opt_level="O3", cast_model_type=jnp.bfloat16,
                      cast_ops=False, keep_batchnorm_fp32=False,
                      master_weights=False, loss_scale=1.0)


OPT_LEVELS = {"O0": _o0, "O1": _o1, "O2": _o2, "O3": _o3}


@dataclasses.dataclass
class AmpState:
    properties: Properties
    policy: Policy
    scaler: LossScaler
    scaler_states: List[LossScalerState]

    @property
    def loss_scale(self):
        return self.scaler_states[0].loss_scale


def initialize(
    opt_level: str = "O1",
    *,
    half_dtype=jnp.bfloat16,
    cast_model_type=None,
    keep_batchnorm_fp32: Optional[bool] = None,
    master_weights: Optional[bool] = None,
    loss_scale: Any = None,
    min_loss_scale: Optional[float] = None,
    max_loss_scale: float = 2.0 ** 24,
    num_losses: int = 1,
) -> AmpState:
    """Build amp state for an opt level, with the reference's override rules
    (explicit kwargs override the O-level defaults, ``frontend.py:331-360``)."""
    if opt_level not in OPT_LEVELS:
        raise ValueError(
            f"Unexpected optimization level {opt_level!r}; options are 'O0', 'O1', 'O2', 'O3'"
        )
    props = OPT_LEVELS[opt_level]()
    if cast_model_type is not None:
        props.cast_model_type = cast_model_type
    if keep_batchnorm_fp32 is not None:
        props.keep_batchnorm_fp32 = keep_batchnorm_fp32
    if master_weights is not None:
        props.master_weights = master_weights
    if loss_scale is not None:
        props.loss_scale = loss_scale

    if props.cast_model_type == jnp.bfloat16 and half_dtype != jnp.bfloat16:
        props.cast_model_type = half_dtype

    if props.opt_level == "O0":
        policy = Policy(jnp.float32, jnp.float32, jnp.float32)
    elif props.opt_level == "O1":
        policy = Policy(jnp.float32, half_dtype, jnp.float32)
    elif props.opt_level == "O2":
        policy = Policy(half_dtype, half_dtype, half_dtype)
    else:  # O3
        policy = Policy(half_dtype, half_dtype, half_dtype)

    scaler = LossScaler(
        props.loss_scale,
        min_loss_scale=min_loss_scale,
        max_loss_scale=max_loss_scale,
    )
    states = [scaler.init() for _ in range(num_losses)]
    logger.info("amp initialized: %s (policy=%s)", props, policy)
    return AmpState(properties=props, policy=policy, scaler=scaler, scaler_states=states)


def state_dict(amp_state: AmpState) -> Dict[str, dict]:
    """Reference: ``apex/amp/frontend.py:365-384`` — one entry per loss scaler."""
    return {
        f"loss_scaler{i}": amp_state.scaler.state_dict(s)
        for i, s in enumerate(amp_state.scaler_states)
    }


def load_state_dict(amp_state: AmpState, d: Dict[str, dict]) -> AmpState:
    """Reference: ``apex/amp/frontend.py:387-404``."""
    states = list(amp_state.scaler_states)
    for i in range(len(states)):
        key = f"loss_scaler{i}"
        if key in d:
            states[i] = amp_state.scaler.load_state_dict(d[key])
    return dataclasses.replace(amp_state, scaler_states=states)
