"""Loss scaling flow helpers (``apex/amp/handle.py:17-158`` capability).

The reference's ``with amp.scale_loss(loss, optimizer) as scaled_loss`` context
manager scales, backprops, unscales, checks overflow, and patches
``optimizer.step`` into a no-op on overflow. The functional equivalent:

    scaled = amp.scale_loss(loss, state)                     # inside value_and_grad fn
    grads, found_inf = scaler.unscale(grads, state)
    new_params, new_opt = amp.apply_if_finite(found_inf, step_fn, params, opt_state)
    state = scaler.update(state, found_inf)

or in one call: ``unscale_and_update``.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, LossScalerState


def scale_loss(loss: jax.Array, scaler_state: LossScalerState) -> jax.Array:
    return loss.astype(jnp.float32) * scaler_state.loss_scale


def unscale_and_update(
    grads: Any,
    scaler: LossScaler,
    scaler_state: LossScalerState,
) -> Tuple[Any, jax.Array, LossScalerState]:
    """Unscale grads, detect overflow, advance scaler state. Jittable."""
    grads, found_inf = scaler.unscale(grads, scaler_state)
    new_state = scaler.update(scaler_state, found_inf)
    return grads, found_inf, new_state


def apply_if_finite(found_inf: jax.Array, step_fn: Callable, *trees: Any) -> Any:
    """Run ``step_fn(*trees)`` and keep its result only when grads were finite —
    the on-device analog of patching ``optimizer.step`` to a no-op
    (``apex/amp/handle.py:128-154``), with no host sync."""
    new_trees = step_fn(*trees)
    skip = found_inf

    def _select(new, old):
        return jax.tree_util.tree_map(
            lambda n, o: jnp.where(skip, o, n), new, old
        )

    if len(trees) == 1:
        return _select(new_trees, trees[0])
    return tuple(_select(n, o) for n, o in zip(new_trees, trees))
