"""Deterministic fault injection for the resilience layer.

The reference's robustness machinery (amp's skip-step loop, AutoResume) is
only ever exercised by real faults on real clusters; here every recovery
path of :mod:`apex_tpu.resilience` is driven in tier-1 CPU tests by a
scripted :class:`FaultInjector`:

- **NaN gradients** — scheduled step calls get their batch poisoned to NaN,
  which propagates to NaN loss/grads exactly as a numeric blow-up would
  (the scaler sees ``found_inf``, the optimizer skips, the watchdog counts);
- **checkpoint write failures** — scheduled save steps raise ``IOError``
  from the save hook for the first N attempts, exercising the
  retry/backoff loop (N < retry budget) or terminal save failure
  (N >= budget);
- **simulated preemption** — a scheduled step call reports "preempt now",
  driving the same emergency-save-and-exit flow as a real SIGTERM;
- **post-commit corruption** — :func:`corrupt_checkpoint` garbles a
  committed step directory on disk (bit rot / a writer killed after the
  data write raced the commit), so restore must fall back to an older step;
- **shard-level corruption** — :func:`corrupt_shard` (bit-flip, truncate,
  or delete ONE shard file of a committed sharded-format step) and
  :func:`tear_manifest` (garble the manifest after commit): damage the
  per-shard sha256 / manifest-sha256 verification must catch, driving
  checksum-verified fallback instead of a silently-wrong restore;
- **value-level poisoning** — :func:`corrupt_checkpoint_weights`
  overwrites a committed step's floating-point shards with non-finite
  values AND re-checksums the manifest + commit marker, so every
  integrity check passes on the poisoned bytes. This is what a
  checkpoint *trained into* a bad state (or poisoned upstream of
  checksumming) looks like: only live traffic can catch it — the fault
  kind behind the ``canary_rollback`` deployment scenario;
- **slow writes** — ``save_delays`` stretches a scheduled save attempt by
  sleeping in the save hook, pinning an async background write in flight
  while the test preempts/drains/abandons around it.

Fault schedules key on the injector's own **call counter** (one tick per
train-step invocation), not on the training-state step number: after a
rollback the re-run of the same state steps proceeds clean, modelling
transient faults — a schedule keyed on state steps would re-trip forever.

:class:`ServingFaultInjector` is the serving-side sibling, driving every
recovery path of :class:`apex_tpu.serving.EngineSupervisor` and the
engine's slot quarantine deterministically: poisoned decode output on
slot N at decode call M, decode/prefill exceptions, hung ticks. The same
transient-fault convention holds — counters are the INJECTOR's and keep
advancing across engine rebuilds, so a schedule fires once and the
restarted engine proceeds clean.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["FaultInjector", "StepFaults", "poison_batch",
           "corrupt_checkpoint", "corrupt_shard",
           "corrupt_checkpoint_weights", "tear_manifest",
           "InjectedEngineFault", "ServingFaultInjector"]


@dataclass
class StepFaults:
    """What the injector wants done to one train-step invocation."""
    call: int
    nan_grads: bool = False
    preempt: bool = False


def poison_batch(batch: Any) -> Any:
    """NaN every floating leaf of ``batch`` — the injected fault that turns
    into NaN gradients through the model's own backward pass."""
    return jax.tree_util.tree_map(
        lambda x: (jnp.full_like(x, jnp.nan)
                   if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                   else x),
        batch)


def corrupt_checkpoint(directory: str, step: int) -> int:
    """Overwrite every file of a *committed* orbax step directory with
    garbage, simulating storage corruption that the commit protocol cannot
    catch. Returns the number of files garbled (0 means the step directory
    was not found — a test bug, assert on it)."""
    step_dir = os.path.join(os.path.abspath(os.fspath(directory)), str(step))
    count = 0
    for root, _, files in os.walk(step_dir):
        for name in files:
            with open(os.path.join(root, name), "wb") as f:
                f.write(b"corrupt")
            count += 1
    return count


def corrupt_shard(directory: str, step: int, *, leaf: int = 0,
                  shard: int = 0, kind: str = "bitflip") -> str:
    """Damage exactly ONE shard file of a committed sharded-format step
    (layout of :class:`apex_tpu.checkpoint.ShardedCheckpointManager`):
    ``"bitflip"`` flips a single bit mid-file, ``"truncate"`` cuts the
    file in half, ``"missing"`` deletes it. All three leave the manifest
    and commit marker intact — the step still *claims* to be healthy, so
    only per-shard checksum/size verification can catch it. Returns the
    damaged file's path; raises ``FileNotFoundError`` when the addressed
    shard does not exist (a test bug)."""
    step_dir = os.path.join(os.path.abspath(os.fspath(directory)), str(step))
    path = os.path.join(step_dir, f"leaf{int(leaf):04d}_s{int(shard):02d}.npy")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no shard file {path}")
    if kind == "missing":
        os.remove(path)
    elif kind == "truncate":
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
    elif kind == "bitflip":
        with open(path, "r+b") as f:
            data = bytearray(f.read())
            data[len(data) // 2] ^= 0x40
            f.seek(0)
            f.write(data)
    else:
        raise ValueError(f"kind must be 'bitflip', 'truncate' or "
                         f"'missing', got {kind!r}")
    return path


def corrupt_checkpoint_weights(directory: str, step: int, *,
                               value: float = float("nan")) -> int:
    """Poison the VALUES of a committed sharded-format step while
    keeping every integrity check green: each floating-point shard file
    is rewritten as ``value`` (non-finite by default) in the original
    shape/dtype/format, then the manifest's per-shard ``bytes``/
    ``sha256`` entries and the commit marker's manifest sha are
    re-stamped to match the poisoned bytes.

    Distinct from :func:`corrupt_shard`: that damages bytes the
    checksums CATCH (restore falls back); this is damage the checksums
    CANNOT catch — manifest + COMMIT intact, per-shard hashes pass,
    weights are garbage. ``verify_step(deep=True)`` reports healthy and
    elastic restore succeeds; only serving the weights to live traffic
    (the deploy canary's SLO score) detects it. Returns the number of
    shard files poisoned (0 ⇒ no floating leaves — a test bug, assert
    on it). Integer leaves are left untouched (step counters etc. stay
    valid)."""
    from io import BytesIO

    from apex_tpu.checkpoint.manifest import (
        load_manifest,
        sha256_bytes,
        write_commit,
        write_manifest,
    )
    step_dir = os.path.join(os.path.abspath(os.fspath(directory)), str(step))
    manifest = load_manifest(step_dir)
    count = 0
    for _, leaf in sorted(manifest["leaves"].items()):
        if not np.issubdtype(np.dtype(leaf["dtype"]), np.floating):
            continue
        for shard in leaf["shards"]:
            path = os.path.join(step_dir, shard["file"])
            poisoned = np.full_like(np.load(path), value)
            buf = BytesIO()
            np.save(buf, poisoned, allow_pickle=False)
            data = buf.getvalue()
            with open(path, "wb") as f:
                f.write(data)
            shard["bytes"] = len(data)
            shard["sha256"] = sha256_bytes(data)
            count += 1
    sha = write_manifest(step_dir, manifest)
    write_commit(step_dir, sha, int(manifest.get("step", step)))
    return count


def tear_manifest(directory: str, step: int) -> str:
    """Truncate a committed step's ``manifest.json`` to half its length —
    a manifest torn *after* commit (partial overwrite, bit rot). The
    commit marker still pins the original manifest sha256, so loading
    must detect the mismatch and treat the step as corrupt. Returns the
    manifest path."""
    step_dir = os.path.join(os.path.abspath(os.fspath(directory)), str(step))
    path = os.path.join(step_dir, "manifest.json")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, size // 2))
    return path


class FaultInjector:
    """Scripted fault schedule for :func:`apex_tpu.resilience.run_training`.

    Args:
      nan_grad_calls: call indices (0-based ticks of the train-step loop)
        whose batch is poisoned to NaN.
      preempt_at_call: first call index at which the injector reports a
        preemption (the driver then emergency-saves and exits cleanly).
      save_failures: ``{checkpoint_step: n}`` — the save hook raises
        ``IOError`` for the first ``n`` attempts at that step.
      save_delays: ``{checkpoint_step: seconds}`` — the save hook sleeps
        before the first attempt at that step (one-shot), holding an
        async background write in flight for preemption-mid-save tests.
    """

    def __init__(self, *, nan_grad_calls: Iterable[int] = (),
                 preempt_at_call: Optional[int] = None,
                 save_failures: Optional[Dict[int, int]] = None,
                 save_delays: Optional[Dict[int, float]] = None):
        self.nan_grad_calls = frozenset(int(c) for c in nan_grad_calls)
        self.preempt_at_call = preempt_at_call
        self._save_failures = dict(save_failures or {})
        self._save_delays = dict(save_delays or {})
        self._call = 0
        self.log = []  # list[StepFaults] — what actually fired, for tests

    # -- train-step loop ---------------------------------------------------
    def begin_step(self) -> StepFaults:
        """Advance the call counter and report this invocation's faults."""
        call = self._call
        self._call += 1
        faults = StepFaults(
            call=call,
            nan_grads=call in self.nan_grad_calls,
            preempt=(self.preempt_at_call is not None
                     and call >= self.preempt_at_call),
        )
        if faults.nan_grads or faults.preempt:
            self.log.append(faults)
        return faults

    @property
    def calls(self) -> int:
        return self._call

    # -- checkpoint layer --------------------------------------------------
    def before_checkpoint_save(self, step: int) -> None:
        """Hook for ``RetryingCheckpointManager(before_save=...)``: delay
        and/or fail the first scheduled attempts at ``step``. For async
        saves this runs on the background writer thread — a delay holds
        that write in flight without stalling the train loop."""
        delay = self._save_delays.pop(step, 0.0)
        if delay > 0:
            time.sleep(delay)
        remaining = self._save_failures.get(step, 0)
        if remaining > 0:
            self._save_failures[step] = remaining - 1
            raise IOError(
                f"injected checkpoint write failure at step {step} "
                f"({remaining - 1} failures remaining)")


class InjectedEngineFault(RuntimeError):
    """Deterministic serving-path fault raised by
    :class:`ServingFaultInjector` — the stand-in for a real decode/prefill
    blow-up (XLA error, device OOM, lost collective)."""


class ServingFaultInjector:
    """Scripted serving faults for ``InferenceEngine``/``EngineSupervisor``.

    Pass one as ``faults=`` to either; the engine calls the three hooks
    from fixed host-side points. All injection is deliberately OFF the
    compiled path — a fault must never retrace the decode program, and a
    restarted engine re-running the same positions proceeds clean because
    the schedule keys on the injector's own monotonically-advancing call
    counters (mirroring :class:`FaultInjector`'s transient-fault
    convention).

    Args:
      poison_decode: ``{decode_call: (slot, kind)}`` — corrupt the decode
        OUTPUT for one slot after the jitted step returns. ``kind``
        ``"nonfinite"`` clears the slot's in-jit ``isfinite`` flag (what
        NaN logits look like to the host); ``"oov"`` replaces the sampled
        token with an out-of-vocab id. Both drive the engine's
        quarantine path.
      decode_raise_calls: decode call indices that raise
        :class:`InjectedEngineFault` before the step runs.
      prefill_raise_calls: prefill call indices that raise likewise.
      decode_hang: ``{decode_call: seconds}`` — sleep before the step,
        simulating a hung tick for the supervisor's wall-clock budget.
    """

    def __init__(self, *,
                 poison_decode: Optional[Dict[int, Tuple[int, str]]] = None,
                 decode_raise_calls: Iterable[int] = (),
                 prefill_raise_calls: Iterable[int] = (),
                 decode_hang: Optional[Dict[int, float]] = None):
        self.poison_decode = dict(poison_decode or {})
        for call, (_, kind) in self.poison_decode.items():
            if kind not in ("nonfinite", "oov"):
                raise ValueError(
                    f"poison_decode[{call}] kind must be 'nonfinite' or "
                    f"'oov', got {kind!r}")
        self.decode_raise_calls = frozenset(
            int(c) for c in decode_raise_calls)
        self.prefill_raise_calls = frozenset(
            int(c) for c in prefill_raise_calls)
        self.decode_hang = dict(decode_hang or {})
        self.decode_calls = 0
        self.prefill_calls = 0
        self.log = []   # what actually fired, in order, for tests

    # -- engine hook points ------------------------------------------------
    def before_decode(self) -> None:
        """Called right before the jitted decode step; may sleep (hung
        tick) or raise (decode failure)."""
        call = self.decode_calls
        self.decode_calls += 1
        hang = self.decode_hang.get(call)
        if hang:
            self.log.append(("hang", call, hang))
            time.sleep(hang)
        if call in self.decode_raise_calls:
            self.log.append(("decode_raise", call))
            raise InjectedEngineFault(
                f"injected decode failure at decode call {call}")

    def corrupt_decode(self, tokens: np.ndarray, finite: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray]:
        """Called with the decode step's host-side outputs; returns the
        (possibly corrupted) pair the engine's integrity check consumes."""
        spec = self.poison_decode.get(self.decode_calls - 1)
        if spec is not None:
            slot, kind = spec
            tokens = np.array(tokens)    # device views are read-only
            finite = np.array(finite)
            if kind == "nonfinite":
                finite[slot] = False
            else:
                tokens[slot] = -1        # out-of-vocab sentinel
            self.log.append(("poison", self.decode_calls - 1, slot, kind))
        return tokens, finite

    def before_prefill(self) -> None:
        """Called right before the jitted prefill; may raise."""
        call = self.prefill_calls
        self.prefill_calls += 1
        if call in self.prefill_raise_calls:
            self.log.append(("prefill_raise", call))
            raise InjectedEngineFault(
                f"injected prefill failure at prefill call {call}")
