"""Deterministic fault injection for the resilience layer.

The reference's robustness machinery (amp's skip-step loop, AutoResume) is
only ever exercised by real faults on real clusters; here every recovery
path of :mod:`apex_tpu.resilience` is driven in tier-1 CPU tests by a
scripted :class:`FaultInjector`:

- **NaN gradients** — scheduled step calls get their batch poisoned to NaN,
  which propagates to NaN loss/grads exactly as a numeric blow-up would
  (the scaler sees ``found_inf``, the optimizer skips, the watchdog counts);
- **checkpoint write failures** — scheduled save steps raise ``IOError``
  from the save hook for the first N attempts, exercising the
  retry/backoff loop (N < retry budget) or terminal save failure
  (N >= budget);
- **simulated preemption** — a scheduled step call reports "preempt now",
  driving the same emergency-save-and-exit flow as a real SIGTERM;
- **post-commit corruption** — :func:`corrupt_checkpoint` garbles a
  committed step directory on disk (bit rot / a writer killed after the
  data write raced the commit), so restore must fall back to an older step.

Fault schedules key on the injector's own **call counter** (one tick per
train-step invocation), not on the training-state step number: after a
rollback the re-run of the same state steps proceeds clean, modelling
transient faults — a schedule keyed on state steps would re-trip forever.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, Iterable, Optional

import jax
import jax.numpy as jnp

__all__ = ["FaultInjector", "StepFaults", "poison_batch",
           "corrupt_checkpoint"]


@dataclass
class StepFaults:
    """What the injector wants done to one train-step invocation."""
    call: int
    nan_grads: bool = False
    preempt: bool = False


def poison_batch(batch: Any) -> Any:
    """NaN every floating leaf of ``batch`` — the injected fault that turns
    into NaN gradients through the model's own backward pass."""
    return jax.tree_util.tree_map(
        lambda x: (jnp.full_like(x, jnp.nan)
                   if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                   else x),
        batch)


def corrupt_checkpoint(directory: str, step: int) -> int:
    """Overwrite every file of a *committed* orbax step directory with
    garbage, simulating storage corruption that the commit protocol cannot
    catch. Returns the number of files garbled (0 means the step directory
    was not found — a test bug, assert on it)."""
    step_dir = os.path.join(os.path.abspath(os.fspath(directory)), str(step))
    count = 0
    for root, _, files in os.walk(step_dir):
        for name in files:
            with open(os.path.join(root, name), "wb") as f:
                f.write(b"corrupt")
            count += 1
    return count


class FaultInjector:
    """Scripted fault schedule for :func:`apex_tpu.resilience.run_training`.

    Args:
      nan_grad_calls: call indices (0-based ticks of the train-step loop)
        whose batch is poisoned to NaN.
      preempt_at_call: first call index at which the injector reports a
        preemption (the driver then emergency-saves and exits cleanly).
      save_failures: ``{checkpoint_step: n}`` — the save hook raises
        ``IOError`` for the first ``n`` attempts at that step.
    """

    def __init__(self, *, nan_grad_calls: Iterable[int] = (),
                 preempt_at_call: Optional[int] = None,
                 save_failures: Optional[Dict[int, int]] = None):
        self.nan_grad_calls = frozenset(int(c) for c in nan_grad_calls)
        self.preempt_at_call = preempt_at_call
        self._save_failures = dict(save_failures or {})
        self._call = 0
        self.log = []  # list[StepFaults] — what actually fired, for tests

    # -- train-step loop ---------------------------------------------------
    def begin_step(self) -> StepFaults:
        """Advance the call counter and report this invocation's faults."""
        call = self._call
        self._call += 1
        faults = StepFaults(
            call=call,
            nan_grads=call in self.nan_grad_calls,
            preempt=(self.preempt_at_call is not None
                     and call >= self.preempt_at_call),
        )
        if faults.nan_grads or faults.preempt:
            self.log.append(faults)
        return faults

    @property
    def calls(self) -> int:
        return self._call

    # -- checkpoint layer --------------------------------------------------
    def before_checkpoint_save(self, step: int) -> None:
        """Hook for ``RetryingCheckpointManager(before_save=...)``: fail the
        first scheduled ``n`` attempts at ``step``."""
        remaining = self._save_failures.get(step, 0)
        if remaining > 0:
            self._save_failures[step] = remaining - 1
            raise IOError(
                f"injected checkpoint write failure at step {step} "
                f"({remaining - 1} failures remaining)")
