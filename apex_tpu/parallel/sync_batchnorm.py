"""Synchronized BatchNorm over the data-parallel axis.

Math parity with the reference's optimized SyncBN
(``apex/parallel/optimized_sync_batchnorm_kernel.py:7-120``, CUDA
``csrc/welford.cu``): local Welford statistics per shard, a cross-replica
merge, normalization, and a backward whose ``sum_dy``/``sum_dy_xmu`` terms are
reduced across replicas. On TPU the merge is a ``psum`` of
``(count, count·mean, count·E[x²])`` over the mesh axis; the backward
reductions fall out of JAX autodiff *through the psum*, which is exactly the
all-reduce the reference implements by hand.

Two usage modes:

- inside ``shard_map`` with ``axis_name`` set → explicit cross-shard stats;
- under plain ``pjit`` (GSPMD) with ``axis_name=None`` → a global ``jnp.mean``
  over the batch dim *is* the synchronized statistic (XLA inserts the
  collective), so SyncBN degenerates to regular BN — the TPU-native free lunch.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def _sync_moments(x32: jax.Array, reduce_axes, axis_name: Optional[str],
                  initializing: bool = False, sample_mask=None):
    """Return (mean, var, count) over ``reduce_axes`` and, if given,
    ``axis_name``.

    ``sample_mask`` (bool/0-1, ``[batch]``) marks which batch rows are real:
    masked rows contribute neither to the sums nor to the count, so the
    cross-replica merge is **count-weighted** — the SPMD expression of the
    reference's unequal per-rank batch sizes (``csrc/welford.cu``
    ``welford_parallel`` merges (count, mean, M2) triples;
    ``tests/distributed/synced_batchnorm/two_gpu_test_different_batch_size
    .py`` pins it). Under shard_map every rank's SHAPES are equal by
    construction, so ranks with fewer real samples pad and mask.
    """
    from apex_tpu.transformer.tensor_parallel.mappings import axis_bound

    # outside shard_map the axis is unbound and the collectives degrade to
    # the identity — the same single-program convention as the TP mappings
    # (a convert_syncbn_model'd module then runs standalone for debugging)
    sync = (axis_name is not None and not initializing
            and axis_bound(axis_name))
    if sample_mask is None:
        n_local = 1
        for a in reduce_axes:
            n_local *= x32.shape[a]
        count = jnp.asarray(n_local, jnp.float32)
        w = None
    else:
        per_sample = 1
        for a in reduce_axes:
            if a % x32.ndim != 0:
                per_sample *= x32.shape[a]
        w = sample_mask.reshape((-1,) + (1,) * (x32.ndim - 1)) != 0
        count = jnp.sum(w.astype(jnp.float32)) * per_sample
        # where, not multiply: 0 * NaN/Inf in a padded row would poison
        # the whole batch's statistics
        x32 = jnp.where(w, x32, 0.0)
    local_sum = jnp.sum(x32, axis=reduce_axes)
    if sync:
        local_sum = jax.lax.psum(local_sum, axis_name)
        count = jax.lax.psum(count, axis_name)
    # an all-padded (global) batch has no statistics; guard the 0/0 so it
    # degrades to zeros instead of NaN-poisoning running stats
    mean = local_sum / jnp.maximum(count, 1.0)
    # two-pass variance: centering before squaring avoids the catastrophic
    # cancellation of E[x²]-mean² — the stability property the reference's
    # Welford kernels (csrc/welford.cu) exist to provide
    shape = [1] * x32.ndim
    for a in range(x32.ndim):
        if a not in [ax % x32.ndim for ax in reduce_axes]:
            shape[a] = x32.shape[a]
    centered = x32 - mean.reshape(shape)
    if w is not None:
        centered = jnp.where(w, centered, 0.0)
    sqsum = jnp.sum(centered * centered, axis=reduce_axes)
    if sync:
        sqsum = jax.lax.psum(sqsum, axis_name)
    var = sqsum / jnp.maximum(count, 1.0)
    return mean, var, count


class SyncBatchNorm(nn.Module):
    """Drop-in BN synchronized across ``axis_name``
    (module parity: ``apex/parallel/optimized_sync_batchnorm.py:9-107``).

    ``channel_last=False`` expects NCHW-like inputs with channels at dim 1;
    ``channel_last=True`` expects channels at the last dim (the reference's
    NHWC fast path — on TPU NHWC is the native conv layout anyway).
    """

    # None: inferred from the input's channel axis at call time (the
    # convert_syncbn_model path — flax BatchNorm carries no static width)
    num_features: Optional[int] = None
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    channel_last: bool = True
    axis_name: Optional[str] = None
    fuse_relu: bool = False
    param_dtype: Any = jnp.float32
    # True (default): running var stores the UNBIASED estimator (torch /
    # reference apex semantics). convert_syncbn_model sets False to
    # preserve flax BatchNorm's biased-batch-variance eval behavior.
    unbiased_running_var: bool = True

    @nn.compact
    def __call__(self, x, use_running_stats: bool = False, sample_mask=None):
        """``sample_mask`` (``[batch]`` bool) marks real rows: padded rows
        are excluded from the (count-weighted, cross-replica) statistics —
        how unequal per-rank batch sizes are expressed under SPMD (the
        reference's ``two_gpu_test_different_batch_size.py`` capability).
        Masked rows still produce normalized outputs; mask them downstream.
        """
        if self.channel_last:
            reduce_axes = tuple(range(x.ndim - 1))
            c = (self.num_features if self.num_features is not None
                 else x.shape[-1])
        else:
            reduce_axes = (0,) + tuple(range(2, x.ndim))
            c = (self.num_features if self.num_features is not None
                 else x.shape[1])

        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((c,), jnp.float32))

        x32 = x.astype(jnp.float32)
        if use_running_stats:
            mean, var = ra_mean.value, ra_var.value
        else:
            mean, var, count = _sync_moments(
                x32, reduce_axes, self.axis_name,
                initializing=self.is_initializing(),
                sample_mask=sample_mask)
            if self.track_running_stats and not self.is_initializing():
                # unbiased variance for running stats (reference matches
                # torch BN semantics); a fully-masked global batch
                # (count == 0) must be a true no-op on the running stats —
                # the count guard zeroes mean/var, and blending those in
                # would decay the stats toward 0 (ADVICE r4)
                unbiased = (var * count / jnp.maximum(count - 1.0, 1.0)
                            if self.unbiased_running_var else var)
                keep = count > 0
                ra_mean.value = jnp.where(
                    keep, (1 - self.momentum) * ra_mean.value
                    + self.momentum * mean, ra_mean.value)
                ra_var.value = jnp.where(
                    keep, (1 - self.momentum) * ra_var.value
                    + self.momentum * unbiased, ra_var.value)

        shape = [1] * x.ndim
        ch_axis = x.ndim - 1 if self.channel_last else 1
        shape[ch_axis] = c
        inv = jax.lax.rsqrt(var + self.eps).reshape(shape)
        y = (x32 - mean.reshape(shape)) * inv
        if self.affine:
            weight = self.param("scale", nn.initializers.ones, (c,), self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros, (c,), self.param_dtype)
            y = y * weight.reshape(shape) + bias.reshape(shape)
        if self.fuse_relu:
            y = jnp.maximum(y, 0.0)
        return y.astype(x.dtype)


def _syncbn_of(bn: nn.BatchNorm, axis_name: Optional[str]) -> "SyncBatchNorm":
    """Map one ``flax.linen.BatchNorm`` to an equivalent SyncBatchNorm.
    Collection/param names match (params scale/bias, batch_stats mean/var)
    and the module ``name`` is preserved, so an existing train state keeps
    working on the converted model. flax's ``momentum`` is the EMA
    RETENTION (ra = m*ra + (1-m)*new); ours is the torch-style update
    weight — hence ``1 - momentum``."""
    if bn.axis != -1:
        raise NotImplementedError(
            f"convert_syncbn_model: BatchNorm over axis {bn.axis}; only the "
            f"trailing channel axis (-1) maps onto SyncBatchNorm")
    if bn.use_scale != bn.use_bias:
        raise NotImplementedError(
            "convert_syncbn_model: BatchNorm with use_scale != use_bias has "
            "no SyncBatchNorm equivalent (affine is a single flag)")
    if bn.use_running_average:
        raise NotImplementedError(
            "convert_syncbn_model: use_running_average=True is an eval-mode "
            "module; pass use_running_stats=True at call time instead")
    defaults = nn.BatchNorm(use_running_average=False)
    if (bn.scale_init is not defaults.scale_init
            or bn.bias_init is not defaults.bias_init):
        raise NotImplementedError(
            "convert_syncbn_model: custom scale_init/bias_init are not "
            "representable on SyncBatchNorm (params are transferred, so "
            "initializers only matter for fresh init — init the original "
            "model and convert, or drop the custom initializers)")
    if bn.axis_index_groups is not None:
        raise NotImplementedError(
            "convert_syncbn_model: axis_index_groups subgroup sync has no "
            "SyncBatchNorm field — run the module under a sub-axis of the "
            "mesh instead (docs/parallel.md, process-group subsets)")
    if bn.dtype is not None:
        raise NotImplementedError(
            "convert_syncbn_model: BatchNorm dtype overrides the compute/"
            "output dtype; SyncBatchNorm always computes statistics in "
            "fp32 and returns the input dtype, so a non-default dtype "
            "cannot be honored — drop it (fp32 stats subsume it) or keep "
            "the flax module")
    if getattr(bn, "use_fast_variance", True) is not True:
        raise NotImplementedError(
            "convert_syncbn_model: use_fast_variance has no SyncBatchNorm "
            "field — its variance is always the two-pass centered form "
            "(the csrc/welford.cu stability property), which is the "
            "use_fast_variance=False math; drop the flag from the source "
            "module")
    # a BatchNorm that already syncs over its own axis_name keeps that
    # axis unless the converter names one explicitly — dropping it would
    # silently de-synchronize the statistics
    sync_axis = axis_name if axis_name is not None else bn.axis_name
    return SyncBatchNorm(
        num_features=None,                    # inferred at call time
        eps=bn.epsilon,
        momentum=1.0 - bn.momentum,
        affine=bn.use_scale,
        channel_last=True,
        axis_name=sync_axis,
        param_dtype=bn.param_dtype,
        # flax stores the BIASED batch variance in its running stats
        # (torch — and this module's default — stores unbiased): preserve
        # the SOURCE module's eval-mode behavior
        unbiased_running_var=False,
        name=bn.name,
    )


def convert_syncbn_model(module: nn.Module,
                         axis_name: Optional[str] = None) -> nn.Module:
    """Functional analog of ``apex.parallel.convert_syncbn_model``
    (``apex/parallel/__init__.py:21-77``): return a copy of ``module`` with
    every ``flax.linen.BatchNorm`` replaced by :class:`SyncBatchNorm`
    synchronizing over ``axis_name``.

    The reference walks ``named_children`` of a mutable torch module tree;
    the flax equivalent rebuilds the (frozen) dataclass tree, converting
    submodules held in dataclass fields, lists/tuples and dicts. Converted
    modules keep their names and the flax BN param/collection layout
    (params ``scale``/``bias``, batch_stats ``mean``/``var``), so existing
    parameters transfer unchanged. Limitation (inherent to flax):
    submodules constructed inside ``setup()``/``@nn.compact`` bodies are
    not dataclass fields and cannot be rewritten from outside — models in
    this framework take a norm factory for that case (see
    ``apex_tpu.models.resnet``)."""
    import dataclasses
    from collections.abc import Mapping

    def conv(v):
        if isinstance(v, nn.BatchNorm):
            return _syncbn_of(v, axis_name)
        if isinstance(v, nn.Module) and dataclasses.is_dataclass(v):
            updates = {}
            for f in dataclasses.fields(v):
                if f.name in ("parent", "name") or not f.init:
                    continue
                val = getattr(v, f.name)
                nv = conv(val)
                if nv is not val:
                    updates[f.name] = nv
            if not updates:
                return v
            return v.clone(**updates)
        if isinstance(v, (list, tuple)):
            items = [conv(x) for x in v]
            if all(a is b for a, b in zip(items, v)):
                return v
            if hasattr(v, "_fields"):          # NamedTuple
                return type(v)(*items)
            return type(v)(items)
        if isinstance(v, Mapping):
            items = {k: conv(x) for k, x in v.items()}
            if all(items[k] is v[k] for k in v):
                return v
            return type(v)(items)              # preserves FrozenDict etc.
        return v

    return conv(module)
