"""Data-parallel gradient synchronization.

Functional counterparts of ``apex.parallel.DistributedDataParallel``
(``apex/parallel/distributed.py:131-643``). The bucketing/stream machinery
(``create_hooks``/``comm_ready_buckets``/``allreduce_bucket``,
``:323-560``) has no TPU analog — XLA fuses and schedules gradient ``psum``
into the backward pass. Retained semantics:

- ``gradient_average``: divide by the data-parallel world size (``:457-466``);
- ``gradient_predivide_factor``: divide before the reduce, multiply the
  remainder after (``:167-179``) for overflow headroom in fp16 sums;
- ``allreduce_always_fp32``: upcast before reducing (``:452-455``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state
from apex_tpu.utils.sharding import axis_size


def all_reduce_gradients(
    grads: Any,
    axis_name: str = parallel_state.DATA_AXIS,
    *,
    gradient_average: bool = True,
    allreduce_always_fp32: bool = False,
    gradient_predivide_factor: float = 1.0,
) -> Any:
    """psum gradients over ``axis_name``. Call inside ``shard_map``.

    Under plain ``pjit`` with batch-sharded inputs this is unnecessary — XLA
    inserts the reduction — but ``shard_map`` training steps need it, exactly
    where the reference needed NCCL allreduce.
    """
    world = axis_size(axis_name)

    def _reduce(g):
        orig_dtype = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        g = jax.lax.psum(g, axis_name)
        if gradient_average:
            postdiv = world / gradient_predivide_factor
            if postdiv != 1.0:
                g = g / postdiv
        elif gradient_predivide_factor != 1.0:
            g = g * gradient_predivide_factor
        return g.astype(orig_dtype)

    return jax.tree_util.tree_map(_reduce, grads)


def flat_dist_call(tree: Any, op: Callable, axis_name: str) -> Any:
    """Apply a collective to every leaf (reference flattens into dtype buckets
    first, ``distributed.py:15-35``; XLA does that coalescing itself)."""
    return jax.tree_util.tree_map(lambda x: op(x, axis_name), tree)


class Reducer:
    """Parity with ``apex.parallel.Reducer`` (``distributed.py:91-128``):
    manual "reduce when you choose" — here a psum-mean over the data axis."""

    def __init__(self, axis_name: str = parallel_state.DATA_AXIS):
        self.axis_name = axis_name

    def reduce(self, tree: Any) -> Any:
        return jax.tree_util.tree_map(
            lambda x: jax.lax.pmean(x, self.axis_name), tree)


class DistributedDataParallel:
    """Parity-API wrapper bundling the reduction options.

    Typical use inside a ``shard_map``-based train step::

        ddp = DistributedDataParallel(allreduce_always_fp32=True)
        grads = jax.grad(loss_fn)(params, batch_shard)
        grads = ddp.reduce_gradients(grads)

    ``delay_allreduce`` (reference ``:164``) corresponds to simply not calling
    ``reduce_gradients`` until the end of gradient accumulation — the
    ``no_sync`` context capability.
    """

    def __init__(
        self,
        axis_name: str = parallel_state.DATA_AXIS,
        message_size: int = 10_000_000,      # accepted for parity; XLA buckets itself
        delay_allreduce: bool = False,
        allreduce_always_fp32: bool = False,
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
    ):
        self.axis_name = axis_name
        self.delay_allreduce = delay_allreduce
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor

    def reduce_gradients(self, grads: Any) -> Any:
        return all_reduce_gradients(
            grads,
            self.axis_name,
            gradient_average=self.gradient_average,
            allreduce_always_fp32=self.allreduce_always_fp32,
            gradient_predivide_factor=self.gradient_predivide_factor,
        )

    def broadcast_params(self, params: Any, src_index: int = 0) -> Any:
        """Reference broadcasts rank-0 params at construction (``:258``);
        the SPMD analog selects source-device values across the axis."""
        def _bcast(x):
            # all devices already hold a replicated copy under pjit; under
            # shard_map, take the value from the source coordinate
            return jax.lax.all_gather(x, self.axis_name)[src_index]

        return jax.tree_util.tree_map(_bcast, params)
