"""Data parallelism over the mesh ``data`` axis (capability of ``apex/parallel``).

The reference's ``DistributedDataParallel`` exists to overlap bucketed NCCL
allreduces with backward (``apex/parallel/distributed.py:131``). Under
XLA/SPMD the same overlap is the *compiler's* job: gradients produced inside a
``pjit``/``shard_map`` step are reduced with ``psum`` and XLA schedules the
collectives into the backward automatically. What remains load-bearing —
predivide/postdivide, fp32 allreduce, gradient averaging, the no-sync
accumulation context — is provided here as explicit functions.
"""

from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    Reducer,
    all_reduce_gradients,
    flat_dist_call,
)
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm, convert_syncbn_model
from apex_tpu.parallel.larc import LARC

__all__ = [
    "DistributedDataParallel",
    "Reducer",
    "all_reduce_gradients",
    "flat_dist_call",
    "SyncBatchNorm",
    "convert_syncbn_model",
    "LARC",
]
