"""LARC — Layer-wise Adaptive Rate Clipping/Scaling.

Semantics of ``apex.parallel.LARC`` (``apex/parallel/LARC.py:5-100``): wraps
any optimizer; before the inner step each tensor's gradient is rescaled by the
local learning rate

    local_lr = trust_coefficient * ||p|| / (||g|| + weight_decay * ||p|| + eps)

with ``clip=True`` → ``min(local_lr / lr, 1)`` (clipping mode) or
``clip=False`` → ``local_lr`` (scaling mode).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers.base import FusedOptimizer, f32, tree_map


class LARC:
    def __init__(self, optimizer: FusedOptimizer, trust_coefficient: float = 0.02,
                 clip: bool = True, eps: float = 1e-8):
        self.inner = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps

    def init(self, params) -> Any:
        return self.inner.init(params)

    def _adapt(self, grads, params, lr):
        wd = getattr(self.inner, "weight_decay", 0.0)

        def one(g, p):
            g32, p32 = g.astype(jnp.float32), p.astype(jnp.float32)
            pn = jnp.sqrt(jnp.sum(p32 * p32))
            gn = jnp.sqrt(jnp.sum(g32 * g32))
            local_lr = self.trust_coefficient * pn / (gn + wd * pn + self.eps)
            ok = (pn > 0) & (gn > 0)
            if self.clip:
                scale = jnp.where(ok, jnp.minimum(local_lr / lr, 1.0), 1.0)
            else:
                scale = jnp.where(ok, local_lr, 1.0)
            # apex folds weight decay into the adapted gradient so the trust
            # ratio scales it too, and zeroes the inner optimizer's wd
            # (LARC.py step: p.grad += wd*p before scaling)
            return ((g32 + wd * p32) * scale).astype(g.dtype)

        return tree_map(one, grads, params)

    def step(self, grads, params, state, *, lr=None, **kw) -> Tuple[Any, Any]:
        eff_lr = self.inner.lr if lr is None else lr
        grads = self._adapt(grads, params, eff_lr)
        saved_wd = self.inner.weight_decay
        self.inner.weight_decay = 0.0  # wd already applied in the adapted grad
        try:
            return self.inner.step(grads, params, state, lr=lr, **kw)
        finally:
            self.inner.weight_decay = saved_wd
