"""Multi-host launcher helper.

Counterpart of ``apex/parallel/multiproc.py:1-36`` (trivial one-node
launcher: one process per GPU with ``--rank`` args). TPU pods invert the
model — one process per *host*, all chips of that host in-process, and
``jax.distributed`` stitches hosts into one global device set — so the
launcher's job collapses to environment-driven initialization:

    python -m apex_tpu.parallel.multiproc train.py ...

initializes ``jax.distributed`` from the standard env vars
(``COORDINATOR_ADDRESS``, ``NUM_PROCESSES``, ``PROCESS_ID`` — or the TPU
metadata auto-detection when unset) and ``exec``s the script, which then
sees the full multi-host ``jax.devices()``.
"""

from __future__ import annotations

import os
import runpy
import sys

__all__ = ["init_distributed", "main"]


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None) -> int:
    """Initialize jax.distributed (idempotent); returns process count.

    On TPU pods with no explicit args, ``jax.distributed.initialize()``
    auto-detects topology from the metadata server.
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS")
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    except RuntimeError as e:
        # double-init message differs across jax versions ("already
        # initialized" / "should only be called once")
        msg = str(e)
        if "already initialized" not in msg and "only be called once" not in msg:
            raise
    return jax.process_count()


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        raise SystemExit(
            "usage: python -m apex_tpu.parallel.multiproc SCRIPT [args...]")
    n = init_distributed()
    print(f"apex_tpu.multiproc: {n} process(es) joined", flush=True)
    script, sys.argv = argv[0], argv
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    main()
