"""Parallel train-step builder.

The reference has no trainer — users wire ``amp`` + DDP + fused optimizers
into their own loops (``examples/imagenet/main_amp.py:333-362``). On TPU the
equivalent wiring is one ``shard_map`` over the global mesh: per-rank autodiff
(torch's one-process-per-rank model), explicit collective regions inside the
model (the ``tensor_parallel.mappings`` custom-vjp functions), and a
data-axis gradient ``pmean`` standing in for DDP's bucketed all-reduce
(``apex/parallel/distributed.py:429-480`` — bucketing/overlap are XLA's job).

``make_train_step`` returns a jitted function
``(params, opt_state, batch, rng) -> (params, opt_state, loss)`` with params
and optimizer state donated (in-place update semantics, the analog of the
reference's in-place ``multi_tensor`` optimizer kernels).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec

from apex_tpu.transformer.parallel_state import DATA_AXIS

__all__ = ["make_train_step", "sync_data_parallel_grads"]


def sync_data_parallel_grads(grads, axis_names: Sequence[str],
                             param_spec=None):
    """pmean grads over the bound data axes (DDP's allreduce + divide,
    reference ``distributed.py:429-480`` predivide/postdivide semantics).

    With ``param_spec`` (full or prefix pytree, same semantics as shard_map
    in_specs), leaves *sharded over a data axis* (expert-parallel parameters
    riding the data axis) are handled per-leaf: their local grads already
    accumulate every rank's token contributions through the ``all_to_all``
    transpose, so averaging them across that axis would mix different
    experts — instead they are divided by the axis size so every leaf's
    synced grad equals d(global mean loss)/d(leaf), matching the pmean
    convention of the replicated leaves.
    """
    from apex_tpu.utils.sharding import (
        axis_size,
        bound_axes,
        broadcast_spec,
        spec_axis_names,
    )

    axes = bound_axes(axis_names)
    if not axes:
        return grads
    if param_spec is None:
        return jax.tree.map(lambda g: lax.pmean(g, axes), grads)

    def one(g, spec):
        used = spec_axis_names(spec)
        rest = tuple(a for a in axes if a not in used)
        if rest:
            g = lax.pmean(g, rest)
        for a in axes:
            if a in used:
                g = g / axis_size(a)
        return g

    g_leaves, treedef = jax.tree_util.tree_flatten(grads)
    spec_leaves = broadcast_spec(param_spec, grads)
    return jax.tree_util.tree_unflatten(
        treedef, [one(g, s) for g, s in zip(g_leaves, spec_leaves)])


def make_train_step(
    loss_fn: Callable,
    optimizer,
    mesh: Mesh,
    param_spec,
    batch_spec,
    *,
    opt_state_spec=None,
    params_template=None,
    data_axes: Sequence[str] = (DATA_AXIS,),
    donate: bool = True,
) -> Callable:
    """Build ``step(params, opt_state, batch, rng) -> (params, opt_state, loss)``.

    Args:
      loss_fn: ``loss_fn(params, batch, rng) -> scalar`` written against the
        per-rank (local shard) view — i.e. a model ``apply`` built from the
        tensor_parallel layers.
      optimizer: a :class:`~apex_tpu.optimizers.base.FusedOptimizer`.
      mesh: the global device mesh (see ``parallel_state``).
      param_spec / batch_spec: PartitionSpec pytrees for params and batch.
      opt_state_spec: optional; derived via ``optimizer.state_spec`` from
        ``params_template`` when omitted.
      data_axes: mesh axes carrying replicated model copies whose grads are
        averaged (the DDP axis; add the context axis when batch also shards
        over it).
    """
    if opt_state_spec is None:
        if params_template is None:
            raise ValueError(
                "need opt_state_spec or params_template to derive it")
        opt_state_spec = optimizer.state_spec(params_template, param_spec)

    # A ZeRO-style optimizer syncs grads itself, but only over its own axis
    # (its reduce-scatter IS the DP allreduce on that axis — reference
    # DistributedFusedAdam grad pipeline); any other data axes still need
    # the pmean here.
    if getattr(optimizer, "handles_grad_sync", False):
        opt_axis = getattr(optimizer, "axis_name", None)
        grad_sync_axes = tuple(a for a in data_axes if a != opt_axis)
    else:
        grad_sync_axes = tuple(data_axes)

    def per_rank(params, opt_state, batch, rng):
        if rng is not None:
            # independent dropout streams per data shard (DDP's per-rank RNG);
            # the tensor axis is folded inside model-parallel regions only.
            # Unbound axes fold index 0 so the single-device fast path below
            # draws the identical stream as a size-1 shard_map would.
            for a in data_axes:
                try:
                    idx = lax.axis_index(a)
                except NameError:
                    idx = 0
                rng = jax.random.fold_in(rng, idx)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
        grads = sync_data_parallel_grads(grads, grad_sync_axes, param_spec)
        loss = sync_data_parallel_grads(loss, data_axes)
        new_params, new_state = optimizer.step(grads, params, opt_state)
        return new_params, new_state, loss

    if mesh.size == 1:
        # single-device mesh: manual partitioning buys nothing and costs a
        # lot (tunneled PJRT backends execute SPMD-partitioned programs an
        # order of magnitude slower; measured 9x on GPT-124M) — run the
        # per-rank body directly. Semantics match: every mesh axis has size
        # 1, and all collective regions no-op behind axis_bound() guards.
        return jax.jit(per_rank, donate_argnums=(0, 1) if donate else ())

    from apex_tpu.utils.sharding import shard_map

    sharded = shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(param_spec, opt_state_spec, batch_spec, PartitionSpec()),
        out_specs=(param_spec, opt_state_spec, PartitionSpec()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())
