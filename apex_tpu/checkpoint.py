"""Distributed checkpoint / resume.

The reference leaves model checkpointing to user scripts
(``examples/imagenet/main_amp.py:254-260`` uses ``torch.save``) and layers
three pieces on top (SURVEY.md §5):

- amp scaler state round-trip (``apex/amp/frontend.py:365-404``, recommended
  flow ``README.md:63-103``),
- fp32 master groups in ``FP16_Optimizer.state_dict``
  (``apex/fp16_utils/fp16_optimizer.py:212-273``),
- sharded optimizer state gather/scatter in ``DistributedFusedAdam``.

On TPU all three collapse into one capability: **save and restore an
arbitrarily-sharded JAX pytree without gathering it to one host**, provided
here on orbax — each host writes exactly the array shards it owns (the
analog of the reference's shard-aware gather/scatter, minus the gather).
Loss-scaler state, fp32 masters, and ZeRO shards are ordinary pytree leaves,
so the whole train state round-trips through one call pair.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, List, Optional, Tuple

import jax

__all__ = [
    "save_checkpoint",
    "load_checkpoint",
    "CheckpointManager",
    "RetryingCheckpointManager",
    "CheckpointSaveError",
]


_CKPTR = None


def _checkpointer():
    # one long-lived checkpointer: orbax spins up async-IO resources per
    # instance, so per-call construction leaks in long training loops
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _as_restore_target(template: Any) -> Any:
    """Template pytree -> ShapeDtypeStruct pytree carrying shardings, so each
    leaf is restored with the layout the training state expects."""
    return jax.tree.map(
        lambda x: (x if isinstance(x, jax.ShapeDtypeStruct)
                   else jax.ShapeDtypeStruct(
                       x.shape, x.dtype,
                       sharding=getattr(x, "sharding", None))),
        template)


def save_checkpoint(path: str, state: Any, *, force: bool = True) -> None:
    """Write ``state`` (any pytree of jax.Arrays, sharded or not) to
    ``path``. Sharded leaves are written distributed: every host persists its
    own shards (no host gather — contrast the reference's
    ``DistributedFusedAdam.state_dict`` gather)."""
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(os.fspath(path)), state, force=force)
    ckptr.wait_until_finished()


def load_checkpoint(path: str, template: Optional[Any] = None) -> Any:
    """Restore a checkpoint. ``template`` (a pytree of arrays or
    ``jax.ShapeDtypeStruct``, possibly carrying shardings) restores each leaf
    with the requested sharding/dtype; without it, arrays come back
    replicated on the default device."""
    ckptr = _checkpointer()
    path = os.path.abspath(os.fspath(path))
    if template is None:
        return ckptr.restore(path)
    return ckptr.restore(path, _as_restore_target(template))


class CheckpointManager:
    """Rotating step-indexed checkpoints with resume — the role the
    reference's AutoResume hook + user save scripts play
    (``pipeline_parallel/utils.py:142-144``, ``examples/imagenet``).

    ``save(step, state)`` / ``restore(template) -> (step, state) | None``;
    keeps the newest ``max_to_keep``.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(os.fspath(directory)),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """``force=True`` bypasses ``save_interval_steps`` gating (and
        overwrites an existing step) — the emergency-save path."""
        import orbax.checkpoint as ocp
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state),
                               force=force)
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        """Committed checkpoint steps, ascending. Uncommitted (killed
        mid-write) step directories are excluded by orbax's atomicity
        protocol, so everything listed here finished its write."""
        return sorted(self._mgr.all_steps())

    def restore(self, template: Any):
        step = self._mgr.latest_step()
        if step is None:
            return None
        return step, self.restore_step(step, template)

    def restore_step(self, step: int, template: Any) -> Any:
        import orbax.checkpoint as ocp
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(_as_restore_target(template)))

    def delete(self, step: int) -> None:
        self._mgr.delete(step)

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


class CheckpointSaveError(RuntimeError):
    """A checkpoint save failed after exhausting its retry budget."""


class RetryingCheckpointManager:
    """Fault-tolerant wrapper over :class:`CheckpointManager` (the
    storage-robustness slice of TorchTitan-style resilient checkpointing):

    - ``save`` retries with exponential backoff — flaky storage must not
      kill a training run over a transient error;
    - ``restore_latest`` / ``restore_before`` treat a failed restore as a
      corrupt checkpoint and fall back to the next-older step (optionally
      deleting the corrupt one so it is never picked again);
    - atomicity itself comes from orbax's commit protocol (a save killed
      mid-write never becomes a listed step) — this layer adds recovery
      for the committed-but-unreadable case (bit rot, truncated shards).

    ``before_save`` is a hook called as ``before_save(step)`` at the top of
    every save *attempt*; raising from it fails that attempt. It exists for
    deterministic fault injection
    (:class:`apex_tpu.testing_faults.FaultInjector`) but any callable works.

    ``telemetry`` counts ``save_attempts`` / ``save_retries`` /
    ``save_failures`` / ``restore_fallbacks`` / ``deleted_corrupt`` for the
    structured failure logs.
    """

    def __init__(self, manager: CheckpointManager, *, max_retries: int = 3,
                 backoff_base: float = 0.5, backoff_max: float = 8.0,
                 delete_corrupt: bool = True,
                 before_save: Optional[Callable[[int], None]] = None):
        self.manager = manager
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.delete_corrupt = bool(delete_corrupt)
        self.before_save = before_save
        self.telemetry = {"save_attempts": 0, "save_retries": 0,
                          "save_failures": 0, "restore_fallbacks": 0,
                          "deleted_corrupt": 0}

    # -- save --------------------------------------------------------------
    def save(self, step: int, state: Any, *, force: bool = False,
             raise_on_failure: bool = False) -> bool:
        """Save with retries. Returns True once a save attempt commits,
        False when the step was gated by ``save_interval_steps`` or (with
        ``raise_on_failure=False``) every retry failed — a failed periodic
        save is logged and counted, not fatal; the caller keeps training
        and the next interval tries again."""
        from apex_tpu.utils.logging import get_logger, log_event

        log = get_logger(__name__)
        delay = self.backoff_base
        last_err: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            self.telemetry["save_attempts"] += 1
            try:
                if self.before_save is not None:
                    self.before_save(step)
                if force or attempt > 0:
                    # orbax force= only bypasses interval gating — an
                    # existing step still raises StepAlreadyExists. A
                    # forced save (emergency, retry, re-save after
                    # rollback) replaces it.
                    try:
                        if step in self.manager.all_steps():
                            self.manager.delete(step)
                    except Exception:  # noqa: BLE001
                        pass
                # retries force: the failed attempt may have registered the
                # step, and interval gating must not swallow the retry
                saved = self.manager.save(step, state,
                                          force=force or attempt > 0)
                # surface async write errors here, inside the retry loop
                self.manager.wait_until_finished()
                return saved
            except Exception as e:  # noqa: BLE001 — storage errors are varied
                last_err = e
                if attempt < self.max_retries:
                    self.telemetry["save_retries"] += 1
                    log_event(log, "checkpoint_save_retry", step=step,
                              attempt=attempt, error=repr(e))
                    if delay > 0:
                        time.sleep(min(delay, self.backoff_max))
                    delay *= 2.0
        self.telemetry["save_failures"] += 1
        log_event(log, "checkpoint_save_failed", step=step,
                  retries=self.max_retries, error=repr(last_err),
                  level="error")
        if raise_on_failure:
            raise CheckpointSaveError(
                f"checkpoint save at step {step} failed after "
                f"{self.max_retries} retries") from last_err
        return False

    # -- restore -----------------------------------------------------------
    def restore_latest(self, template: Any) -> Optional[Tuple[int, Any]]:
        """Restore the newest readable checkpoint, walking older on
        corruption. Returns ``(step, state)`` or None when nothing is
        restorable."""
        return self.restore_before(None, template)

    def restore_before(self, step_exclusive: Optional[int],
                       template: Any) -> Optional[Tuple[int, Any]]:
        """Like :meth:`restore_latest` but only considers steps strictly
        below ``step_exclusive`` — the rollback path's "newest checkpoint
        from before the poisoned window"."""
        from apex_tpu.utils.logging import get_logger, log_event

        log = get_logger(__name__)
        steps = self.manager.all_steps()
        if step_exclusive is not None:
            steps = [s for s in steps if s < step_exclusive]
        for step in reversed(steps):
            try:
                return step, self.manager.restore_step(step, template)
            except Exception as e:  # noqa: BLE001 — corruption is varied
                self.telemetry["restore_fallbacks"] += 1
                log_event(log, "checkpoint_restore_fallback", step=step,
                          error=repr(e))
                if self.delete_corrupt:
                    try:
                        self.manager.delete(step)
                        self.telemetry["deleted_corrupt"] += 1
                    except Exception:  # noqa: BLE001
                        pass  # unreadable AND undeletable: just skip it
        return None

    def wait_until_finished(self) -> None:
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.close()
