"""Fused MLP.

Capability counterpart of ``apex/mlp/mlp.py:11-86`` + ``csrc/mlp_cuda.cu``:
the reference chains cuBLAS GEMMs with hand-written bias/ReLU/sigmoid
epilogue kernels in one C++ call to avoid per-layer launch overhead. Under
XLA the whole chain is one compiled program and the bias+activation epilogues
fuse into the matmuls by construction, so the TPU implementation is the
direct functional composition — the fusion the CUDA code fights for is the
compiler's default here.

Semantics parity: ``mlp_sizes`` like ``[in, h1, h2]`` builds 2 layers;
``activation`` in {"none", "relu", "sigmoid"} applied after every layer
(including the last, matching ``mlp_cuda.cu``); weights init
``N(0, sqrt(2/(fan_in+fan_out)))``, biases ``N(0, sqrt(1/out))``
(``mlp.py:70-78``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

__all__ = ["MLP", "mlp_function"]

_ACTIVATIONS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


def mlp_function(activation: str, x: jax.Array, weights: List[jax.Array],
                 biases: List[jax.Array]) -> jax.Array:
    """Functional forward (reference ``mlp_function``/``MlpFunction``,
    ``mlp.py:11-30``): y_i = act(y_{i-1} @ W_i^T + b_i)."""
    act = _ACTIVATIONS[activation]
    for i, w in enumerate(weights):
        x = x @ w.T.astype(x.dtype)
        if biases:
            x = x + biases[i].astype(x.dtype)
        x = act(x)
    return x


@dataclass
class MLP:
    """Reference ``apex.mlp.MLP`` (``mlp.py:33-86``)."""

    mlp_sizes: List[int]
    bias: bool = True
    activation: str = "relu"

    def __post_init__(self):
        if self.activation not in _ACTIVATIONS:
            raise TypeError("activation must be relu or none or sigmoid")
        self.num_layers = len(self.mlp_sizes) - 1

    def init(self, key: jax.Array) -> Dict[str, jax.Array]:
        params = {}
        keys = jax.random.split(key, 2 * self.num_layers)
        for i in range(self.num_layers):
            fan_in, fan_out = self.mlp_sizes[i], self.mlp_sizes[i + 1]
            std = (2.0 / (fan_in + fan_out)) ** 0.5
            params[f"weight_{i}"] = std * jax.random.normal(
                keys[2 * i], (fan_out, fan_in))
            if self.bias:
                params[f"bias_{i}"] = (1.0 / fan_out) ** 0.5 * \
                    jax.random.normal(keys[2 * i + 1], (fan_out,))
        return params

    def spec(self) -> Dict[str, PartitionSpec]:
        s = {}
        for i in range(self.num_layers):
            s[f"weight_{i}"] = PartitionSpec()
            if self.bias:
                s[f"bias_{i}"] = PartitionSpec()
        return s

    def apply(self, params: Dict[str, jax.Array], x: jax.Array) -> jax.Array:
        weights = [params[f"weight_{i}"] for i in range(self.num_layers)]
        biases = ([params[f"bias_{i}"] for i in range(self.num_layers)]
                  if self.bias else [])
        return mlp_function(self.activation, x, weights, biases)
