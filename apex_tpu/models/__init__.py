"""Model zoo built on the parallel transformer toolkit.

The reference ships its Megatron LM building blocks and standalone GPT/BERT
as test fixtures (``apex/transformer/testing/standalone_transformer_lm.py``,
``standalone_gpt.py``, ``standalone_bert.py``); here they are first-class
models, plus the vision models exercised by the reference examples
(``examples/imagenet``, ``examples/dcgan``).
"""

from apex_tpu.models.transformer import (
    TransformerConfig,
    ParallelMLP,
    ParallelAttention,
    ParallelTransformerLayer,
    ParallelTransformer,
)
from apex_tpu.models.gpt import GPTModel
from apex_tpu.models.bert import BertModel
from apex_tpu.models.pipelined import PipelinedGPT

__all__ = [
    "TransformerConfig",
    "ParallelMLP",
    "ParallelAttention",
    "ParallelTransformerLayer",
    "ParallelTransformer",
    "GPTModel",
    "BertModel",
    "PipelinedGPT",
]
