"""Model zoo built on the parallel transformer toolkit.

The reference ships its Megatron LM building blocks and standalone GPT/BERT
as test fixtures (``apex/transformer/testing/standalone_transformer_lm.py``,
``standalone_gpt.py``, ``standalone_bert.py``); here they are first-class
models, plus the vision models exercised by the reference examples
(``examples/imagenet``, ``examples/dcgan``).
"""

from apex_tpu.models.transformer import (
    TransformerConfig,
    ParallelMLP,
    ParallelAttention,
    ParallelTransformerLayer,
    ParallelTransformer,
)
from apex_tpu.models.gpt import GPTModel
from apex_tpu.models.bert import BertModel
from apex_tpu.models.encoder_decoder import EncoderDecoderModel
from apex_tpu.models.pipelined import PipelinedEncoderDecoder, PipelinedGPT
from apex_tpu.models.generation import decode_step, generate, init_kv_caches
from apex_tpu.models.resnet import (
    ResNet,
    ResNetConfig,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
)
from apex_tpu.models.dcgan import DCGANConfig, Discriminator, Generator
from apex_tpu.models.vit import ViTConfig, ViTModel, vit_b16, vit_l16, vit_h14

__all__ = [
    "ResNet",
    "ResNetConfig",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
    "DCGANConfig",
    "Generator",
    "Discriminator",
    "ViTConfig",
    "ViTModel",
    "vit_b16",
    "vit_l16",
    "vit_h14",
    "TransformerConfig",
    "ParallelMLP",
    "ParallelAttention",
    "ParallelTransformerLayer",
    "ParallelTransformer",
    "GPTModel",
    "BertModel",
    "EncoderDecoderModel",
    "PipelinedEncoderDecoder",
    "PipelinedGPT",
    "generate",
    "decode_step",
    "init_kv_caches",
]
