"""DCGAN generator/discriminator, TPU-native NHWC.

Capability counterpart of the reference's mixed-precision GAN example
(``/root/reference/examples/dcgan/main_amp.py``: 64x64 DCGAN trained with two
optimizers and two loss scalers through ``amp.initialize(num_losses=3)``) —
one of BASELINE.json's parity configs. The interesting apex capability it
exercises is *multiple models/optimizers/losses under one amp context*;
here both nets are plain functional modules, and the multi-loss-scaler story
is :class:`apex_tpu.amp.DynamicLossScaler` instances carried per loss.

Design: transposed convs via ``lax.conv_transpose`` (generator) and strided
convs (discriminator), NHWC, BN with carried state as in
:mod:`apex_tpu.models.resnet`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.utils.batch_norm import bn_apply as _bn_apply, bn_init as _bn_init

__all__ = ["DCGANConfig", "Generator", "Discriminator"]


@dataclass(frozen=True)
class DCGANConfig:
    latent_dim: int = 100        # nz
    gen_features: int = 64       # ngf
    disc_features: int = 64      # ndf
    channels: int = 3            # nc
    bn_eps: float = 1e-5
    bn_momentum: float = 0.1
    compute_dtype: Any = jnp.float32


def _winit(key, shape):
    # DCGAN recipe: N(0, 0.02) conv weights (examples/dcgan weights_init)
    return jax.random.normal(key, shape, jnp.float32) * 0.02


class _Net:
    def __init__(self, config: DCGANConfig):
        self.config = config

    def _bn(self, p, s, x, train):
        cfg = self.config
        return _bn_apply(p, s, x, train=train, momentum=cfg.bn_momentum,
                         eps=cfg.bn_eps, axis_name=None)


class Generator(_Net):
    """z [N, latent] -> image [N, 64, 64, C] in [-1, 1]."""

    def init(self, key: jax.Array):
        cfg = self.config
        f = cfg.gen_features
        chans = [(cfg.latent_dim, f * 8), (f * 8, f * 4), (f * 4, f * 2),
                 (f * 2, f), (f, cfg.channels)]
        keys = jax.random.split(key, len(chans))
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        for i, (cin, cout) in enumerate(chans):
            params[f"deconv{i}"] = _winit(keys[i], (4, 4, cin, cout))
            if i < len(chans) - 1:
                params[f"bn{i}"], state[f"bn{i}"] = _bn_init(cout)
        return params, state

    def apply(self, params, state, z, *, train: bool = False):
        cfg = self.config
        x = z.reshape(z.shape[0], 1, 1, cfg.latent_dim)
        x = x.astype(cfg.compute_dtype)
        new_state: Dict[str, Any] = {}
        n_layers = 5
        for i in range(n_layers):
            w = params[f"deconv{i}"].astype(cfg.compute_dtype)
            first, last = i == 0, i == n_layers - 1
            x = lax.conv_transpose(
                x, w, strides=(1, 1) if first else (2, 2),
                padding="VALID" if first else "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            if last:
                return jnp.tanh(x), new_state
            x, new_state[f"bn{i}"] = self._bn(
                params[f"bn{i}"], state[f"bn{i}"], x, train)
            x = jax.nn.relu(x)


class Discriminator(_Net):
    """image [N, 64, 64, C] -> logit [N] (no sigmoid; pair with BCE-with-
    logits, numerically safer than the example's Sigmoid+BCELoss which amp
    must blacklist — ``examples/dcgan/main_amp.py`` notes this exact issue)."""

    def init(self, key: jax.Array):
        cfg = self.config
        f = cfg.disc_features
        chans = [(cfg.channels, f), (f, f * 2), (f * 2, f * 4),
                 (f * 4, f * 8), (f * 8, 1)]
        keys = jax.random.split(key, len(chans))
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        for i, (cin, cout) in enumerate(chans):
            params[f"conv{i}"] = _winit(keys[i], (4, 4, cin, cout))
            if 0 < i < len(chans) - 1:
                params[f"bn{i}"], state[f"bn{i}"] = _bn_init(cout)
        return params, state

    def apply(self, params, state, x, *, train: bool = False):
        cfg = self.config
        x = x.astype(cfg.compute_dtype)
        new_state: Dict[str, Any] = {}
        n_layers = 5
        for i in range(n_layers):
            w = params[f"conv{i}"].astype(cfg.compute_dtype)
            last = i == n_layers - 1
            x = lax.conv_general_dilated(
                x, w, window_strides=(1, 1) if last else (2, 2),
                padding="VALID" if last else "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            if last:
                return x.reshape(x.shape[0]).astype(jnp.float32), new_state
            if i > 0:
                x, new_state[f"bn{i}"] = self._bn(
                    params[f"bn{i}"], state[f"bn{i}"], x, train)
            x = jax.nn.leaky_relu(x, 0.2)
