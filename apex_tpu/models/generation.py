"""Autoregressive generation with KV caching for :class:`GPTModel`.

The reference ships no inference utilities (its `get_ltor_masks...` helper
is training-side), so this exceeds parity: jit-compiled incremental decoding
— one token per step, K/V written into preallocated caches, greedy or
temperature/top-k sampling — the standard TPU decode shape (static shapes,
``lax.scan`` over steps, no host round-trips inside the loop).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.utils.sharding import axis_size

__all__ = ["init_kv_caches", "init_paged_kv_caches", "decode_step",
           "generate", "cast_decode_params", "flatten_decode_caches",
           "preslice_layer_params"]


def cast_decode_params(params, compute_dtype):
    """Cast fp32 params to the compute dtype ONCE for decoding — except
    MoE router weights, which stay fp32 (the router matmul reads fp32;
    rounding them would let decode pick different experts than the full
    forward near top-k boundaries). Inside a decode scan every layer's
    f32->bf16 weight cast is loop-invariant, but XLA re-materializes it
    per step (~0.3 GB/step at GPT-2 124M — the 154 MB tied embedding
    alone re-cast every token)."""
    from jax.tree_util import tree_map_with_path

    def cast(path, x):
        if any("router" in str(getattr(p, "key", p)) for p in path):
            return x
        return x.astype(compute_dtype) if x.dtype == jnp.float32 else x

    return tree_map_with_path(cast, params)


def flatten_decode_caches(caches, num_layers: int):
    """Prefill caches -> the FLAT per-layer list form ``[(k, v)]`` of
    ``[b, S, h*d]`` — the fast decode form (see :func:`init_kv_caches`).
    Accepts the stacked ``(k, v)`` ``[L, b, h, S, d]`` pair or the
    per-layer list of 4D ``(k, v)`` pairs."""

    def fl(x):
        b, h, s, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b, s, h * d)

    if isinstance(caches, list):
        return [(fl(k), fl(v)) for k, v in caches]
    ck, cv = caches
    return [(fl(ck[i]), fl(cv[i])) for i in range(num_layers)]


def preslice_layer_params(params, num_layers: int):
    """Pre-slice stacked ``params['transformer']['layers']`` into a
    per-layer list behind an ``optimization_barrier``: inside a decode
    scan XLA re-slices (and lays out copies of) the stacked weights
    EVERY step (~115 us/step at GPT-2 124M bs8 — PERF.md round 5); the
    barrier pins the slices as buffers so XLA cannot sink them back.
    No-op when the params are already a list or have no stacked
    transformer layers."""
    if "transformer" not in params or "layers" not in params["transformer"]:
        return params
    lp = params["transformer"]["layers"]
    if isinstance(lp, (list, tuple)):
        return params
    params = dict(params)
    params["transformer"] = dict(params["transformer"])
    params["transformer"]["layers"] = jax.lax.optimization_barrier(
        [jax.tree.map(lambda x: x[i], lp) for i in range(num_layers)])
    return params


def init_kv_caches(model, batch_size: int, max_len: int,
                   dtype=None, *, stacked: bool = True, flat: bool = False):
    """Preallocate K/V caches. ``stacked=True`` (default): ``(k, v)``, each
    ``[num_layers, batch, local_kv_heads, max_len, head_dim]`` — the scan
    form. ``stacked=False``: a LIST of per-layer ``(k, v)`` pairs, each
    ``[batch, local_kv_heads, max_len, head_dim]`` — the fast decode form
    (per-layer buffers update in place; scanning over a stacked cache
    pays full-cache slice/restack copies every step, measured 2.4x slower
    at bs8 — PERF.md round 4). ``stacked=False, flat=True``: the per-layer
    pairs are FLAT ``[batch, max_len, local_kv_heads * head_dim]`` — the
    fastest decode form (the 4D carry's minor dim is head_dim = half a
    128-lane tile, so XLA pads the cache 2x and reads it at ~50% HBM
    bandwidth; the flat minor dim stays full-lane — PERF.md round 5).
    ``generate()`` uses the flat list form.

    Heads are K/V heads (``config.kv_heads``), which under GQA/MQA is
    ``num_query_groups``, not the query head count. Inside ``shard_map``
    with a bound tensor axis the head count is the TP-local slice
    (``kv_heads // tp``), matching the per-rank QKV shapes.
    """
    from apex_tpu.transformer.tensor_parallel.mappings import axis_bound

    c = model.config
    dtype = dtype or c.compute_dtype
    heads = c.kv_heads                     # == query heads unless GQA/MQA
    if axis_bound(c.axis_name):
        tp = axis_size(c.axis_name)
        if heads % tp:
            raise ValueError(
                f"kv heads ({heads}) must be divisible by the "
                f"tensor-parallel size ({tp}); with GQA/MQA keep "
                f"num_query_groups a multiple of tp")
        heads //= tp
    per_layer = (batch_size, heads, max_len, c.head_dim)
    if not stacked:
        if flat:
            per_layer = (batch_size, max_len, heads * c.head_dim)
        return [(jnp.zeros(per_layer, dtype), jnp.zeros(per_layer, dtype))
                for _ in range(c.num_layers)]
    if flat:
        raise ValueError("flat=True is a per-layer (stacked=False) form")
    shape = (c.num_layers,) + per_layer
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def init_paged_kv_caches(model, n_pages: int, page_size: int, dtype=None,
                         *, quantized: bool = False):
    """Preallocate the PAGED decode cache: a list of per-layer
    ``(k_pages, v_pages)`` pairs, each ``[n_pages, page_size,
    local_kv_heads * head_dim]`` — the serving engine's
    ``kv_layout="paged"`` pool (docs/serving.md#paged-kv). The pool keeps
    the flat form's fused heads-minor dim (full-lane page reads, and the
    dim the sharded engine splits over the tensor axis); slots map onto
    pool rows through a host-owned page table, so HBM is committed to
    actual context length instead of ``max_slots * max_len``. Head count
    is TP-local inside ``shard_map``, exactly as in
    :func:`init_kv_caches`.

    ``quantized=True`` (``kv_dtype="int8"``,
    docs/serving.md#kv-quantization): pools are int8 and each of k/v
    nests as a ``(pages, scales)`` pair, ``scales`` the per-(page,
    kv-head) float32 sidecar ``[n_pages, local_kv_heads]`` the fused
    decode op dequantizes from — halving the decode-step HBM stream."""
    from apex_tpu.transformer.tensor_parallel.mappings import axis_bound

    c = model.config
    dtype = dtype or c.compute_dtype
    heads = c.kv_heads
    if axis_bound(c.axis_name):
        tp = axis_size(c.axis_name)
        if heads % tp:
            raise ValueError(
                f"kv heads ({heads}) must be divisible by the "
                f"tensor-parallel size ({tp}); with GQA/MQA keep "
                f"num_query_groups a multiple of tp")
        heads //= tp
    shape = (n_pages, page_size, heads * c.head_dim)
    if quantized:
        sshape = (n_pages, heads)
        return [((jnp.zeros(shape, jnp.int8),
                  jnp.zeros(sshape, jnp.float32)),
                 (jnp.zeros(shape, jnp.int8),
                  jnp.zeros(sshape, jnp.float32)))
                for _ in range(c.num_layers)]
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(c.num_layers)]


def _gather_vocab(logits: jax.Array, axis_name: str) -> jax.Array:
    """Vocab-parallel logits -> full vocab (argmax/categorical need global
    token ids; shard-local winners would be garbage under TP)."""
    from apex_tpu.transformer.tensor_parallel.mappings import axis_bound

    if axis_bound(axis_name):
        logits = lax.all_gather(logits, axis_name, axis=-1, tiled=True)
    return logits


def _cached_forward(model, params, caches, tokens: jax.Array, index,
                    last_only: bool = False, last_index=None,
                    paged_state=None, lora=None):
    """Run ``tokens`` [batch, s] occupying cache slots [index, index+s) ->
    (fp32 full-vocab logits [s, batch, V], new caches). ``last_only``:
    compute the LM head for the FINAL position only (returns [1, b, V]) —
    a 1024-token prefill otherwise materializes [s, b, V] fp32 logits
    (1.65 GB at GPT-2 vocab) of which sampling reads one row.
    ``last_index`` (scalar, may be traced): compute the LM head for that
    SINGLE sequence position instead — the bucketed-prefill form, where
    the prompt is right-padded to a bucket length and the last real token
    sits mid-sequence. ``index`` may be a ``[batch]`` vector of per-row
    cache offsets (continuous-batching decode over FLAT caches): each
    row then reads its own learned-position rows / rope angles and
    writes K/V at its own offset."""
    c = model.config
    emb_p = params["embedding"]
    s = tokens.shape[1]
    emb = model.embedding.apply(emb_p["word_embeddings"], tokens)  # [b,s,h]
    if c.position_embedding_type == "learned":
        if getattr(index, "ndim", 0) == 1:
            positions = index[:, None] + jnp.arange(s)[None, :]    # [b, s]
            pos = jnp.take(emb_p["position_embeddings"], positions,
                           axis=0)                                 # [b,s,h]
            emb = emb + pos
        else:
            pos = lax.dynamic_slice_in_dim(emb_p["position_embeddings"],
                                           index, s, axis=0)       # [s, h]
            emb = emb + pos[None]
    # (rope rotates q/k inside attention at offset ``index``; nothing to add)
    hidden = emb.transpose(1, 0, 2)                                 # [s,b,h]
    hidden = hidden.astype(c.compute_dtype)
    hidden, new_caches = model.transformer.apply(
        params["transformer"], hidden, kv_caches=caches, cache_index=index,
        paged_state=paged_state, lora=lora)
    from apex_tpu.models.gpt import lm_head_loss
    if last_only:
        hidden = hidden[-1:]
    elif last_index is not None:
        hidden = lax.dynamic_slice_in_dim(hidden, last_index, 1, axis=0)
    logits = lm_head_loss(
        emb_p["word_embeddings"]["weight"], hidden, None, None, c)
    logits = _gather_vocab(logits, c.axis_name)
    return logits.astype(jnp.float32), new_caches


def decode_step(model, params, caches, tokens: jax.Array, index,
                paged_state=None, lora=None):
    """One incremental step: ``tokens`` [batch] at position ``index`` ->
    (fp32 full-vocab logits [batch, V], updated caches). ``caches`` is
    either form :func:`init_kv_caches` produces — the stacked ``(k, v)``
    pair or the per-layer list (the form ``generate()`` decodes with) —
    and the return matches the input form. ``index`` may be a ``[batch]``
    vector of per-row positions on the FLAT list form (continuous
    batching — the serving engine's batched decode over independent
    slots). With ``paged_state`` (a ``[batch, pages_per_slot]`` page
    table) ``caches`` is the :func:`init_paged_kv_caches` pool list and
    ``index`` MUST be the per-row position vector. MoE models route
    drop-free on the cache path (prefill and decode; see
    :func:`generate`)."""
    logits, new_caches = _cached_forward(model, params, caches,
                                         tokens[:, None], index,
                                         paged_state=paged_state, lora=lora)
    return logits[0], new_caches


def generate(model, params, prompt: jax.Array, max_new_tokens: int, *,
             max_len: Optional[int] = None, temperature: float = 0.0,
             top_k: Optional[int] = None,
             rng: Optional[jax.Array] = None,
             eos_token: Optional[int] = None) -> jax.Array:
    """Generate ``[batch, prompt_len + max_new_tokens]`` token ids.

    ``temperature == 0`` is greedy; otherwise softmax sampling (optionally
    truncated to ``top_k`` logits) with ``rng``. ``eos_token`` freezes
    finished rows (they keep emitting ``eos_token``). Fully jittable; decode
    runs as one ``lax.scan``.

    MoE models route DROP-FREE on the whole generation path — batched
    prefill and single-token decode alike (round 5; factor-based capacity
    drops are a training-time load-balancing trade) — so cached logits
    match the drop-free serving forward
    (``model.apply(..., moe_drop_free=True)``) at ANY
    ``moe_capacity_factor``: no capacity-induced divergence remains. (As
    in any MoE system, a router whose top-k gap for some token is below
    the numerical noise between two differently-shaped computations can
    still flip that token's expert; trained routers are confident,
    random-init ones are not.)
    """
    if max_new_tokens < 1:
        # max_new_tokens=0 would make total == prompt_len, so the
        # out.at[:, prompt_len] first-token write silently clamps onto the
        # last prompt slot — reject instead of corrupting the prompt
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if top_k is not None and top_k < 1:
        # lax.top_k(logits, 0) would yield an empty kth slice (and a
        # shape error only deep inside the sampling trace)
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if temperature > 0.0 and rng is None:
        raise ValueError("sampling (temperature > 0) needs rng")
    # pre-cast fp32 params to the compute dtype ONCE (decode is inference;
    # bf16 weights are the standard serving precision). The barrier pins
    # the cast params as materialized buffers; without it XLA sinks the
    # (loop-invariant) casts back into the scan body.
    c = model.config
    if c.compute_dtype != jnp.float32:
        params = jax.lax.optimization_barrier(
            cast_decode_params(params, c.compute_dtype))
    b, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    if (model.config.position_embedding_type == "learned"
            and total > model.config.max_position_embeddings):
        raise ValueError(
            f"prompt + new tokens ({total}) exceeds "
            f"max_position_embeddings "
            f"({model.config.max_position_embeddings}); the clamped "
            "position lookup would silently repeat the last row")
    S = max_len or total
    if S < total:
        raise ValueError(f"max_len {S} < prompt+new tokens {total}")
    # prefill runs on the per-layer LIST form (unrolled layer loop): the
    # stacked form's scan re-slices and re-stacks the whole [L, ...]
    # cache every layer (~2 ms of a ~20 ms 124M bs8 prefill — PERF.md
    # round 5); the deeper unrolled HLO is a one-time compile cost
    caches = init_kv_caches(model, b, S, stacked=False)
    params = preslice_layer_params(params, c.num_layers)
    rng = jax.random.PRNGKey(0) if rng is None else rng

    out = jnp.zeros((b, total), prompt.dtype)
    out = out.at[:, :prompt_len].set(prompt)

    def pick_next(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        logits = logits / temperature
        if top_k is not None:
            kth = lax.top_k(logits, top_k)[0][:, -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        return jax.random.categorical(key, logits).astype(prompt.dtype)

    # batched prefill: one forward writes all prompt K/V; its last-position
    # logits produce the first generated token
    prefill_logits, caches = _cached_forward(model, params, caches, prompt,
                                             0, last_only=True)
    # convert ONCE into the FLAT per-layer form for the decode scan
    # ([b, S, h*d] keeps the cache minor dim full-lane — PERF.md round 5)
    caches = flatten_decode_caches(caches, c.num_layers)
    first = pick_next(prefill_logits[-1], jax.random.fold_in(rng, 0))
    out = out.at[:, prompt_len].set(first)
    done0 = ((first == eos_token) if eos_token is not None
             else jnp.zeros((b,), bool))
    if max_new_tokens == 1:
        return out

    def step(carry, i):
        # i = absolute position of the token being fed (already written)
        caches, out, done = carry
        token = lax.dynamic_index_in_dim(out, i, axis=1, keepdims=False)
        logits, caches = decode_step(model, params, caches, token, i)
        nxt = pick_next(logits, jax.random.fold_in(rng, i))
        if eos_token is not None:
            nxt = jnp.where(done, eos_token, nxt)
            done = jnp.logical_or(done, nxt == eos_token)
        out = lax.dynamic_update_slice(out, nxt[:, None], (0, i + 1))
        return (caches, out, done), None

    (caches, out, _), _ = lax.scan(
        step, (caches, out, done0), jnp.arange(prompt_len, total - 1))
    return out
