"""Standalone GPT — the flagship causal LM.

Capability counterpart of the reference's test-fixture GPT
(``apex/transformer/testing/standalone_gpt.py:~40-111`` on top of
``standalone_transformer_lm.py``: ``TransformerLanguageModel`` ~:1390-1550,
``post_language_model_processing`` lm-head + vocab-parallel loss): vocab- and
tensor-sharded embedding, learned positions, parallel transformer stack,
weight-tied vocab-parallel LM head, vocab-parallel cross entropy.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from apex_tpu.models.transformer import (
    ParallelTransformer,
    TransformerConfig,
    embed_tokens,
    position_table_params,
    position_table_spec,
)
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    VocabParallelEmbedding,
    linear_with_grad_accumulation_and_async_allreduce,
)
__all__ = ["GPTModel", "lm_head_loss"]


def lm_head_loss(embedding_weight, hidden, labels, loss_mask, config):
    """Weight-tied LM head + vocab-parallel loss tail shared by
    :class:`GPTModel` and :class:`~apex_tpu.models.pipelined.PipelinedGPT`.

    Reference: ``standalone_transformer_lm.py`` ``post_language_model_
    processing`` — ColumnParallelLinear forward with the vocab-sharded
    embedding matrix (under SP this all-gathers the sequence shards back into
    the matmul), then ``vocab_parallel_cross_entropy``. Returns vocab-parallel
    logits ``[s, b, V/tp]`` when ``labels`` is None, else the scalar
    (optionally loss-masked) mean loss.
    """
    c = config

    def head(hid):
        # LM-head matmul in compute dtype (bf16 on the MXU runs ~4x fp32
        # and halves the [s, b, V] logits footprint); the CE upcasts
        # internally (vocab_parallel_cross_entropy fp32 math, Megatron
        # kernel semantics)
        return linear_with_grad_accumulation_and_async_allreduce(
            hid.astype(c.compute_dtype),
            embedding_weight,  # callee casts weight to x.dtype (amp-O2 rule)
            None,
            sequence_parallel_enabled=c.sequence_parallel,
            axis_name=c.axis_name)                          # [s, b, V/tp]

    if labels is None:
        return head(hidden)
    labels_sb = labels.transpose(1, 0)                      # [s, b]
    nc = c.loss_seq_chunks
    if nc > 1 and not c.sequence_parallel and hidden.shape[0] % nc == 0:
        # long-context memory guard: the [s, b, V] logits of a 64k sequence
        # are ~13 GB in fp32 — compute head+CE per sequence chunk under
        # remat so only one chunk's logits ever exist (the chunk re-runs
        # its matmul in backward, a cheap trade at vocab width). Skipped
        # under SP, where the head's all-gather interleaves global
        # positions across chunks.
        s = hidden.shape[0]
        hc = hidden.reshape(nc, s // nc, *hidden.shape[1:])
        lc = labels_sb.reshape(nc, s // nc, labels_sb.shape[1])

        @jax.checkpoint
        def chunk_losses(hid, lab):
            return vocab_parallel_cross_entropy(head(hid), lab,
                                                axis_name=c.axis_name)

        losses = jax.lax.map(lambda xs: chunk_losses(*xs), (hc, lc))
        losses = losses.reshape(s, -1)
    else:
        losses = vocab_parallel_cross_entropy(head(hidden), labels_sb,
                                              axis_name=c.axis_name)
    if loss_mask is None:
        return jnp.mean(losses)
    mask_sb = loss_mask.transpose(1, 0).astype(losses.dtype)
    return jnp.sum(losses * mask_sb) / jnp.maximum(jnp.sum(mask_sb), 1.0)


@dataclass
class GPTModel:
    """GPT: embeddings -> ParallelTransformer (causal) -> tied LM head."""

    config: TransformerConfig

    def __post_init__(self):
        c = self.config
        self.embedding = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, init_method=c.init_method(),
            params_dtype=c.params_dtype, axis_name=c.axis_name)
        self.transformer = ParallelTransformer(c)

    def init(self, key: jax.Array) -> Dict[str, Any]:
        c = self.config
        k_emb, k_pos, k_tr = jax.random.split(key, 3)
        return {
            "embedding": {
                "word_embeddings": self.embedding.init(k_emb),
                **position_table_params(c, k_pos),
            },
            "transformer": self.transformer.init(k_tr),
        }

    def spec(self) -> Dict[str, Any]:
        return {
            "embedding": {
                "word_embeddings": self.embedding.spec(),
                **position_table_spec(self.config),
            },
            "transformer": self.transformer.spec(),
        }

    def _embed(self, params, tokens, rng, deterministic):
        """tokens [b, s] -> hidden [s(, shard), b, h] (Megatron layout)."""
        return embed_tokens(self.embedding, params["embedding"], tokens,
                            self.config, rng=rng, deterministic=deterministic)

    def apply(
        self,
        params: Dict[str, Any],
        tokens: jax.Array,
        labels: Optional[jax.Array] = None,
        *,
        loss_mask: Optional[jax.Array] = None,
        rng: Optional[jax.Array] = None,
        deterministic: bool = True,
        moe_drop_free: Optional[bool] = None,
    ):
        """tokens/labels/loss_mask: ``[batch, seq]``.

        With ``labels`` returns the scalar masked-mean LM loss (the
        reference's loss path through ``vocab_parallel_cross_entropy``);
        otherwise returns vocab-parallel logits ``[s, b, vocab/tp]``.
        ``moe_drop_free=True`` routes MoE layers without capacity drops —
        the serving forward that matches ``generate()``'s cached logits
        exactly at ANY ``moe_capacity_factor`` (the generation path itself
        always routes drop-free); default (None) keeps the factor-based
        training routing.
        """
        rngs = (None, None) if rng is None else tuple(jax.random.split(rng))
        hidden = self._embed(params, tokens, rngs[0], deterministic)
        hidden = self.transformer.apply(
            params["transformer"], hidden, rng=rngs[1],
            deterministic=deterministic, moe_drop_free=moe_drop_free)
        moe_aux = None
        if self.config.num_moe_experts:
            hidden, moe_aux = hidden
        out = lm_head_loss(
            params["embedding"]["word_embeddings"]["weight"], hidden,
            labels, loss_mask, self.config)
        if moe_aux is not None and labels is not None:
            out = out + moe_aux        # load-balancing term, pre-scaled
        return out
