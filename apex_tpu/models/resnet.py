"""ResNet family (ResNet-18/34/50/101/152), TPU-native NHWC.

Capability counterpart of the reference's flagship example — ResNet-50
ImageNet training under amp O2 + apex DDP
(``/root/reference/examples/imagenet/main_amp.py``; the model itself comes
from torchvision there, but the *capability* — a convnet exercising amp,
SyncBN (``apex/parallel/optimized_sync_batchnorm.py``), fused optimizers and
data parallelism — is apex's headline configuration and BASELINE.json's
north-star config).

TPU design (not a port):

- NHWC layout end-to-end: the layout the MXU conv units want, which the
  reference's ``--channels-last`` / NHWC contrib kernels
  (``apex/contrib/groupbn``) fight torch to get.
- functional module protocol matching the rest of the model zoo:
  ``init(key) -> (params, state)``, ``apply(params, state, x, train=...)``
  returning ``(logits, new_state)`` — batch statistics are explicit carried
  state, never Python-side mutation, so the whole train step jits.
- BatchNorm is synchronized over the data axis when ``axis_name`` is bound
  (inside ``shard_map``): local sums are ``psum``-merged before normalizing,
  the same Welford-merge semantics as the reference's
  ``optimized_sync_batchnorm_kernel.py:7-120`` / ``csrc/welford.cu``. Under
  plain pjit/GSPMD the global batch mean is already synchronized — XLA
  inserts the collective.
- bf16 compute with fp32 BN statistics and fp32 residual accumulation is the
  amp-O2 equivalent (policy applied by the caller via
  :mod:`apex_tpu.amp`); params stay fp32.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from apex_tpu.utils.batch_norm import (bn_apply as _bn_apply,
                                       bn_from_sums as _bn_from_sums,
                                       bn_init as _bn_init,
                                       bn_sums as _bn_sums)
from apex_tpu.utils.conv import conv_nhwc as _conv, he_init as _he_init

__all__ = ["ResNetConfig", "ResNet", "resnet18", "resnet34", "resnet50",
           "resnet101", "resnet152"]

# (block type, per-stage block counts) keyed by depth — torchvision layout,
# which examples/imagenet/main_amp.py consumes via `models.__dict__[arch]`.
_DEPTHS = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
}

_STAGE_WIDTHS = (64, 128, 256, 512)


@dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    width: int = 64                    # stem width
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    # data-parallel axis to synchronize BN stats over (None = local/GSPMD)
    axis_name: Optional[str] = None
    compute_dtype: Any = jnp.float32   # bf16 = the amp-O2 cast
    # zero-init the last BN scale of each residual block (torchvision
    # `zero_init_residual`, the standard large-batch RN50 recipe)
    zero_init_residual: bool = True
    # route bottleneck 1x1 convs through the fused Pallas GEMM+BN+stats
    # kernel (ops/conv_fused.py) during training — folds the separate BN
    # statistics and normalize passes into the conv's own HBM streams.
    # Opt-in (None = off): per-op the kernels beat XLA's backward, but at
    # the whole-model level XLA's convs and Pallas disagree on activation
    # layouts, and the boundary copies outweigh the win on v5e (measured
    # analysis in PERF.md — the same reason the reference ships its fused
    # bottleneck as opt-in contrib, bottleneck.py:134).
    fused_conv: Optional[bool] = None

    @property
    def block(self) -> str:
        return _DEPTHS[self.depth][0]

    @property
    def stage_blocks(self) -> Tuple[int, ...]:
        return _DEPTHS[self.depth][1]

    @property
    def expansion(self) -> int:
        return 4 if self.block == "bottleneck" else 1


class ResNet:
    """Functional ResNet. ``init(key) -> (params, state)``;
    ``apply(params, state, x_nhwc, train) -> (logits, new_state)``."""

    def __init__(self, config: ResNetConfig):
        self.config = config

    # -- init ----------------------------------------------------------------

    def _block_init(self, key, cin, width, cout, stride):
        cfg = self.config
        ks = jax.random.split(key, 4)
        p: Dict[str, Any] = {}
        st: Dict[str, Any] = {}
        if cfg.block == "bottleneck":
            convs = [("conv1", (1, 1, cin, width), 1),
                     ("conv2", (3, 3, width, width), stride),
                     ("conv3", (1, 1, width, cout), 1)]
        else:
            convs = [("conv1", (3, 3, cin, width), stride),
                     ("conv2", (3, 3, width, cout), 1)]
        for i, (name, shape, _) in enumerate(convs):
            p[name] = _he_init(ks[i], shape)
            bnp, bns = _bn_init(shape[-1])
            p[f"bn{i + 1}"], st[f"bn{i + 1}"] = bnp, bns
        if cfg.zero_init_residual:
            last = f"bn{len(convs)}"
            p[last] = dict(p[last], scale=jnp.zeros_like(p[last]["scale"]))
        if stride != 1 or cin != cout:
            p["down_conv"] = _he_init(ks[3], (1, 1, cin, cout))
            p["down_bn"], st["down_bn"] = _bn_init(cout)
        return p, st

    def init(self, key: jax.Array):
        cfg = self.config
        keys = jax.random.split(key, 2 + len(cfg.stage_blocks))
        params: Dict[str, Any] = {
            "stem": {"conv": _he_init(keys[0], (7, 7, 3, cfg.width))}}
        state: Dict[str, Any] = {"stem": {}}
        params["stem"]["bn"], state["stem"]["bn"] = _bn_init(cfg.width)
        cin = cfg.width
        for si, nblocks in enumerate(cfg.stage_blocks):
            width = _STAGE_WIDTHS[si]
            cout = width * cfg.expansion
            bkeys = jax.random.split(keys[1 + si], nblocks)
            stage_p, stage_s = [], []
            for bi in range(nblocks):
                stride = 2 if (si > 0 and bi == 0) else 1
                bp, bs = self._block_init(bkeys[bi], cin, width, cout, stride)
                stage_p.append(bp)
                stage_s.append(bs)
                cin = cout
            params[f"layer{si + 1}"] = stage_p
            state[f"layer{si + 1}"] = stage_s
        fan_in = cin
        params["fc"] = {
            "kernel": jax.random.normal(keys[-1], (fan_in, cfg.num_classes),
                                        jnp.float32) * fan_in ** -0.5,
            "bias": jnp.zeros((cfg.num_classes,), jnp.float32),
        }
        return params, state

    def spec(self):
        """Replicated params (pure DP); shard the batch dim of inputs."""
        params, state = jax.eval_shape(self.init, jax.random.PRNGKey(0))
        rep = lambda tree: jax.tree_util.tree_map(
            lambda _: PartitionSpec(), tree)
        return rep(params), rep(state)

    # -- apply ---------------------------------------------------------------

    def _bn(self, p, s, x, train):
        cfg = self.config
        return _bn_apply(p, s, x, train=train, momentum=cfg.bn_momentum,
                         eps=cfg.bn_eps, axis_name=cfg.axis_name)

    def _use_fused(self) -> bool:
        return bool(self.config.fused_conv)

    def _block_apply_fused(self, p, s, x, stride):
        """Bottleneck block on the fused 1x1-GEMM+BN kernels (training hot
        path): each 1x1 conv reads its raw input once, applies the previous
        BN's normalize+ReLU on the fly, and emits its output's batch
        statistics from a VMEM epilogue — the TPU counterpart of the
        reference's fused bottleneck graphs
        (``apex/contrib/bottleneck/bottleneck.py:134-262``). The 3x3 conv
        stays an XLA convolution (its input normalize fuses into the conv
        read; its output statistics are one fused reduction pass)."""
        cfg = self.config
        from apex_tpu.ops.conv_fused import conv1x1_bn_act, conv3x3_bn_act
        new_s = {}

        def close(bn_name, sums, n, y=None):
            """bn_from_sums (+ optionally the normalize affine in the
            activation dtype); records the updated running stats."""
            a, b, new_s[bn_name] = _bn_from_sums(
                p[bn_name], s[bn_name], sums, n, shift=s[bn_name]["mean"],
                momentum=cfg.bn_momentum, eps=cfg.bn_eps,
                axis_name=cfg.axis_name)
            if y is None:
                return a, b
            return y * a.astype(y.dtype) + b.astype(y.dtype)

        nhw = x.shape[0] * x.shape[1] * x.shape[2]
        y1, s1 = conv1x1_bn_act(x, p["conv1"].reshape(x.shape[-1], -1),
                                stats_shift=s["bn1"]["mean"])
        a1, b1 = close("bn1", s1, nhw)
        if stride == 1:
            # fused 3x3: bn1 normalize+relu on the fly, stats epilogue
            y2, s2 = conv3x3_bn_act(y1, p["conv2"], a1, b1, relu=True,
                                    stats_shift=s["bn2"]["mean"])
            nhw2 = nhw
        else:
            # the 3 stride-2 blocks keep the XLA conv (strided slicing in
            # the shifted-GEMM kernel costs more than the boundary copy)
            z1 = jax.nn.relu(y1 * a1.astype(y1.dtype)
                             + b1.astype(y1.dtype))
            y2 = _conv(z1, p["conv2"], stride)
            s2 = _bn_sums(y2, s["bn2"]["mean"])
            nhw2 = y2.shape[0] * y2.shape[1] * y2.shape[2]
        a2, b2 = close("bn2", s2, nhw2)
        y3, s3 = conv1x1_bn_act(y2, p["conv3"].reshape(y2.shape[-1], -1),
                                a2, b2, relu=True,
                                stats_shift=s["bn3"]["mean"])
        out = close("bn3", s3, nhw2, y3)
        if "down_conv" in p:
            xd = x[:, ::stride, ::stride, :] if stride != 1 else x
            yd, sd = conv1x1_bn_act(xd,
                                    p["down_conv"].reshape(x.shape[-1], -1),
                                    stats_shift=s["down_bn"]["mean"])
            residual = close("down_bn", sd, nhw2, yd)
        else:
            residual = x
        return jax.nn.relu(out + residual), new_s

    def _block_apply(self, p, s, x, stride, train):
        cfg = self.config
        if cfg.block == "bottleneck" and train and self._use_fused():
            return self._block_apply_fused(p, s, x, stride)
        new_s = {}
        out = _conv(x, p["conv1"], stride if cfg.block == "basic" else 1)
        out, new_s["bn1"] = self._bn(p["bn1"], s["bn1"], out, train)
        out = jax.nn.relu(out)
        out = _conv(out, p["conv2"], 1 if cfg.block == "basic" else stride)
        out, new_s["bn2"] = self._bn(p["bn2"], s["bn2"], out, train)
        if cfg.block == "bottleneck":
            out = jax.nn.relu(out)
            out = _conv(out, p["conv3"])
            out, new_s["bn3"] = self._bn(p["bn3"], s["bn3"], out, train)
        if "down_conv" in p:
            residual = _conv(x, p["down_conv"], stride)
            residual, new_s["down_bn"] = self._bn(
                p["down_bn"], s["down_bn"], residual, train)
        else:
            residual = x
        return jax.nn.relu(out + residual), new_s

    def apply(self, params, state, x, *, train: bool = False):
        """x: [N, H, W, 3] NHWC, any float dtype; returns fp32 logits."""
        cfg = self.config
        x = x.astype(cfg.compute_dtype)
        new_state: Dict[str, Any] = {"stem": {}}
        out = _conv(x, params["stem"]["conv"].astype(cfg.compute_dtype),
                    stride=2)
        out, new_state["stem"]["bn"] = self._bn(
            params["stem"]["bn"], state["stem"]["bn"], out, train)
        out = jax.nn.relu(out)
        out = lax.reduce_window(
            out, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
        for si, nblocks in enumerate(cfg.stage_blocks):
            stage_p = params[f"layer{si + 1}"]
            stage_s = state[f"layer{si + 1}"]
            new_stage = []
            for bi in range(nblocks):
                stride = 2 if (si > 0 and bi == 0) else 1
                bp = jax.tree_util.tree_map(
                    lambda a: a.astype(cfg.compute_dtype)
                    if a.ndim == 4 else a, stage_p[bi])
                out, bs = self._block_apply(bp, stage_s[bi], out, stride,
                                            train)
                new_stage.append(bs)
            new_state[f"layer{si + 1}"] = new_stage
        out = jnp.mean(out.astype(jnp.float32), axis=(1, 2))
        logits = out @ params["fc"]["kernel"] + params["fc"]["bias"]
        return logits, new_state


def _make(depth):
    def ctor(**kw) -> ResNet:
        return ResNet(ResNetConfig(depth=depth, **kw))
    ctor.__name__ = f"resnet{depth}"
    ctor.__doc__ = f"ResNet-{depth} (torchvision-equivalent topology)."
    return ctor


resnet18 = _make(18)
resnet34 = _make(34)
resnet50 = _make(50)
resnet101 = _make(101)
resnet152 = _make(152)
