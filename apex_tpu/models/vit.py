"""Vision Transformer (ViT-B/L/H) on the parallel transformer toolkit.

BASELINE.json lists "ViT-L/16 SyncBatchNorm + FusedAdam across v5p-64" as a
target config; the reference itself has no ViT, but its Megatron blocks are
the obvious substrate (the same way NeMo builds ViT on apex's
``apex/transformer``). Patch embedding is a single strided conv (an MXU
matmul after im2col — XLA does this folding), then the standard
:class:`~apex_tpu.models.transformer.ParallelTransformer` encoder stack in
Megatron ``[seq, batch, hidden]`` layout with bidirectional (padding-free)
attention, CLS token, and a linear head.

Tensor parallelism, sequence parallelism, recompute, and bf16 compute all
come along for free from :class:`TransformerConfig`.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from apex_tpu.models.transformer import ParallelTransformer, TransformerConfig
from apex_tpu.transformer.enums import AttnMaskType

__all__ = ["ViTConfig", "ViTModel", "vit_b16", "vit_l16", "vit_h14"]


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    channels: int = 3
    transformer: TransformerConfig = None  # required

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def _encoder_config(num_layers, hidden, heads, **kw) -> TransformerConfig:
    return TransformerConfig(
        num_layers=num_layers, hidden_size=hidden, num_attention_heads=heads,
        attn_mask_type=AttnMaskType.padding, hidden_dropout=0.0,
        attention_dropout=0.0, **kw)


class ViTModel:
    """Functional ViT: ``init(key) -> params``;
    ``apply(params, images_nhwc) -> logits``."""

    def __init__(self, config: ViTConfig):
        self.config = config
        self.encoder = ParallelTransformer(config.transformer)

    def init(self, key: jax.Array) -> Dict[str, Any]:
        cfg = self.config
        t = cfg.transformer
        h = t.hidden_size
        k_patch, k_cls, k_pos, k_head, k_enc = jax.random.split(key, 5)
        fan_in = cfg.patch_size * cfg.patch_size * cfg.channels
        return {
            "patch_embed": jax.random.normal(
                k_patch, (cfg.patch_size, cfg.patch_size, cfg.channels, h),
                jnp.float32) * fan_in ** -0.5,
            "cls_token": jax.random.normal(k_cls, (1, 1, h)) * 0.02,
            "pos_embed": jax.random.normal(
                k_pos, (cfg.num_patches + 1, 1, h)) * 0.02,
            "encoder": self.encoder.init(k_enc),
            "head": {
                "kernel": jax.random.normal(k_head, (h, cfg.num_classes),
                                            jnp.float32) * h ** -0.5,
                "bias": jnp.zeros((cfg.num_classes,), jnp.float32),
            },
        }

    def spec(self):
        return {
            "patch_embed": PartitionSpec(),
            "cls_token": PartitionSpec(),
            "pos_embed": PartitionSpec(),
            "encoder": self.encoder.spec(),
            "head": {"kernel": PartitionSpec(), "bias": PartitionSpec()},
        }

    def apply(self, params, images, *, rng=None, deterministic=True):
        """images: [N, H, W, C] NHWC -> logits [N, num_classes] — or
        ``(logits, moe_aux_loss)`` when the transformer config enables MoE
        (``num_moe_experts``): the pre-scaled load-balancing term belongs
        in the caller's training loss (ViT computes no loss in-model)."""
        cfg = self.config
        t = cfg.transformer
        x = images.astype(t.compute_dtype)
        w = params["patch_embed"].astype(t.compute_dtype)
        patches = lax.conv_general_dilated(
            x, w, window_strides=(cfg.patch_size, cfg.patch_size),
            padding="VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        n = patches.shape[0]
        # [N, h/p, w/p, H] -> Megatron [seq, batch, hidden]
        hidden = patches.reshape(n, cfg.num_patches, t.hidden_size)
        hidden = jnp.transpose(hidden, (1, 0, 2))
        cls = jnp.broadcast_to(
            params["cls_token"].astype(t.compute_dtype),
            (1, n, t.hidden_size))
        hidden = jnp.concatenate([cls, hidden], axis=0)
        hidden = hidden + params["pos_embed"].astype(t.compute_dtype)
        hidden = self.encoder.apply(
            params["encoder"], hidden, rng=rng, deterministic=deterministic)
        moe_aux = None
        if t.num_moe_experts:
            hidden, moe_aux = hidden
        cls_out = hidden[0].astype(jnp.float32)          # [batch, hidden]
        logits = cls_out @ params["head"]["kernel"] + params["head"]["bias"]
        return logits if moe_aux is None else (logits, moe_aux)


def _make(name, layers, hidden, heads, patch):
    def ctor(image_size: int = 224, num_classes: int = 1000,
             **tkw) -> ViTModel:
        enc = _encoder_config(layers, hidden, heads, **tkw)
        return ViTModel(ViTConfig(image_size=image_size, patch_size=patch,
                                  num_classes=num_classes, transformer=enc))
    ctor.__name__ = name
    ctor.__doc__ = f"ViT {name}: {layers}L/{hidden}H/{heads}A, patch {patch}."
    return ctor


vit_b16 = _make("vit_b16", 12, 768, 12, 16)
vit_l16 = _make("vit_l16", 24, 1024, 16, 16)
vit_h14 = _make("vit_h14", 32, 1280, 16, 14)
