"""Standalone BERT — bidirectional encoder with MLM + binary heads.

Capability counterpart of ``apex/transformer/testing/standalone_bert.py``
(``BertModel`` on top of the Megatron blocks: padding-mask attention,
pooler, ``BertLMHead`` with tied embeddings, binary [NSP] head).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from apex_tpu.models.transformer import (
    ParallelTransformer,
    TransformerConfig,
    _ln,
    _ln_params,
    _ln_spec,
    embed_tokens,
    position_table_params,
    position_table_spec,
)
from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    VocabParallelEmbedding,
    linear_with_grad_accumulation_and_async_allreduce,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    gather_from_sequence_parallel_region,
)

__all__ = ["BertModel"]


@dataclass
class BertModel:
    """BERT encoder: embeddings (word+position+tokentype) -> bidirectional
    ParallelTransformer -> LM head (tied) + optional binary head."""

    config: TransformerConfig
    num_tokentypes: int = 2
    add_binary_head: bool = True

    def __post_init__(self):
        c = self.config
        if c.attn_mask_type == AttnMaskType.causal:
            self.config = c = replace(c, attn_mask_type=AttnMaskType.padding)
        self.embedding = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, init_method=c.init_method(),
            params_dtype=c.params_dtype, axis_name=c.axis_name)
        self.transformer = ParallelTransformer(c)

    def init(self, key: jax.Array) -> Dict[str, Any]:
        c = self.config
        ks = jax.random.split(key, 6)
        params = {
            "embedding": {
                "word_embeddings": self.embedding.init(ks[0]),
                **position_table_params(c, ks[1]),
                "tokentype_embeddings": c.init_method()(
                    ks[2], (self.num_tokentypes, c.hidden_size),
                    c.params_dtype),
            },
            "transformer": self.transformer.init(ks[3]),
            # BertLMHead: dense + layernorm before the tied projection
            "lm_head": {
                "dense": {
                    "weight": c.init_method()(
                        ks[4], (c.hidden_size, c.hidden_size), c.params_dtype),
                    "bias": jnp.zeros((c.hidden_size,), c.params_dtype),
                },
                "layernorm": _ln_params(c.hidden_size, c.params_dtype,
                                        c.normalization),
            },
        }
        if self.add_binary_head:
            params["binary_head"] = {
                "pooler": {
                    "weight": c.init_method()(
                        ks[5], (c.hidden_size, c.hidden_size), c.params_dtype),
                    "bias": jnp.zeros((c.hidden_size,), c.params_dtype),
                },
                "classifier": {
                    "weight": jnp.zeros((2, c.hidden_size), c.params_dtype),
                    "bias": jnp.zeros((2,), c.params_dtype),
                },
            }
        return params

    def spec(self) -> Dict[str, Any]:
        dense_spec = {"weight": PartitionSpec(), "bias": PartitionSpec()}
        spec = {
            "embedding": {
                "word_embeddings": self.embedding.spec(),
                **position_table_spec(self.config),
                "tokentype_embeddings": PartitionSpec(),
            },
            "transformer": self.transformer.spec(),
            "lm_head": {"dense": dict(dense_spec),
                        "layernorm": _ln_spec(self.config.normalization)},
        }
        if self.add_binary_head:
            spec["binary_head"] = {"pooler": dict(dense_spec),
                                   "classifier": dict(dense_spec)}
        return spec

    @staticmethod
    def build_attention_mask(padding_mask: jax.Array) -> jax.Array:
        """[b, s] bool (True = valid token) -> [b, 1, s, s] bool mask where
        True = masked out, the reference's extended attention mask
        (``standalone_bert.py`` ``bert_extended_attention_mask``)."""
        m = padding_mask.astype(bool)
        att = m[:, None, None, :] & m[:, None, :, None]
        return ~att

    def apply(
        self,
        params: Dict[str, Any],
        tokens: jax.Array,
        padding_mask: Optional[jax.Array] = None,
        tokentype_ids: Optional[jax.Array] = None,
        lm_labels: Optional[jax.Array] = None,
        *,
        rng: Optional[jax.Array] = None,
        deterministic: bool = True,
    ):
        """tokens/padding_mask/tokentype_ids/lm_labels: ``[batch, seq]``.

        Returns ``(lm_loss_or_logits, binary_logits_or_None)`` mirroring the
        reference BertModel.forward output pair.
        """
        c = self.config
        rngs = (None, None) if rng is None else tuple(jax.random.split(rng))
        hidden = embed_tokens(
            self.embedding, params["embedding"], tokens, c,
            tokentype_params=params["embedding"]["tokentype_embeddings"],
            tokentype_ids=tokentype_ids, rng=rngs[0],
            deterministic=deterministic)
        mask = (self.build_attention_mask(padding_mask)
                if padding_mask is not None else None)
        hidden = self.transformer.apply(
            params["transformer"], hidden, attention_mask=mask,
            rng=rngs[1], deterministic=deterministic)
        moe_aux = None
        if c.num_moe_experts:
            hidden, moe_aux = hidden
        if c.sequence_parallel:
            # heads (pooler/dense/layernorm) run on the full sequence; the
            # gather's backward scatters grads back to the shards
            hidden = gather_from_sequence_parallel_region(
                hidden, False, c.axis_name)

        binary_logits = None
        if self.add_binary_head:
            # pooler over the first token's hidden state ([CLS]); under SP
            # token 0 lives on rank 0's shard — gather happens in the LM head
            # matmul, so take it from the (possibly sharded) dim-0 start.
            pooled = jnp.tanh(
                hidden[0].astype(jnp.float32)
                @ params["binary_head"]["pooler"]["weight"].T.astype(jnp.float32)
                + params["binary_head"]["pooler"]["bias"])
            binary_logits = (
                pooled @ params["binary_head"]["classifier"]["weight"].T
                + params["binary_head"]["classifier"]["bias"])

        # LM head in the compute dtype (matches lm_head_loss in gpt.py:
        # bf16 on the MXU runs ~4x fp32 and halves the [s, b, V] logits
        # footprint; the CE upcasts internally). Round 5: this head ran
        # entirely in fp32 — the 8192x768x30528 GEMM pair alone was ~12 ms
        # of the 74 ms BERT step.
        h = hidden.astype(c.compute_dtype)
        h = h @ params["lm_head"]["dense"]["weight"].T.astype(
            c.compute_dtype) \
            + params["lm_head"]["dense"]["bias"].astype(c.compute_dtype)
        h = jax.nn.gelu(h, approximate=True)
        h = _ln(params["lm_head"]["layernorm"], h, c.layernorm_epsilon,
                norm=c.normalization)
        logits = linear_with_grad_accumulation_and_async_allreduce(
            h.astype(c.compute_dtype),
            params["embedding"]["word_embeddings"]["weight"],
            None,  # callee casts weight to x.dtype (amp-O2 rule)
            sequence_parallel_enabled=False,  # already gathered above
            axis_name=c.axis_name)
        if lm_labels is None:
            return logits, binary_logits
        losses = vocab_parallel_cross_entropy(
            logits, lm_labels.transpose(1, 0), axis_name=c.axis_name)
        if padding_mask is not None:
            m = padding_mask.transpose(1, 0).astype(losses.dtype)
            lm_loss = jnp.sum(losses * m) / jnp.maximum(jnp.sum(m), 1.0)
        else:
            lm_loss = jnp.mean(losses)
        if moe_aux is not None:
            lm_loss = lm_loss + moe_aux    # pre-scaled load-balancing term
        return lm_loss, binary_logits
