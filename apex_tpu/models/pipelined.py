"""Pipeline-parallel GPT.

The pipelined flagship: embedding + tied head replicated across pipeline
stages (grads psum-synced, the analog of the reference's embedding-group
all-reduce, ``parallel_state.py:347-407``), transformer layers stacked and
sharded over the ``pipeline`` mesh axis, driven by the ``ppermute`` schedules
in :mod:`apex_tpu.transformer.pipeline_parallel.schedules`.

Capability counterpart of the reference's pipelined GPT test fixture
(``apex/transformer/testing/standalone_gpt.py`` under
``test_pipeline_parallel_fwd_bwd.py``): same TP/SP layers inside each stage,
1F1B or interleaved schedule outside, vocab-parallel loss on the last stage.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from apex_tpu.models.transformer import (
    ParallelTransformerLayer,
    TransformerConfig,
    embed_tokens,
    position_table_params,
    position_table_spec,
)
from apex_tpu.models.transformer import _ln, _ln_params, _ln_spec
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    arrange_layers_for_pipeline,
    mark_pipeline_replicated,
    pipeline_stage_spec,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (
    make_interleaved_pipelined_loss_fn,
    make_pipelined_loss_fn,
)
from apex_tpu.models.gpt import lm_head_loss
from apex_tpu.transformer.tensor_parallel.layers import VocabParallelEmbedding

__all__ = ["PipelinedGPT", "PipelinedEncoderDecoder"]


def _pipeline_stage_rng(rng, tick):
    """Per-tick dropout stream, decorrelated across pipeline stages (the
    Megatron RNG-tracker role, ``tensor_parallel/random.py:90-240``).
    Shared by both pipelined models."""
    if rng is None:
        return None
    from apex_tpu.transformer.parallel_state import (
        get_pipeline_model_parallel_rank,
    )
    rng = jax.random.fold_in(rng, tick)
    return jax.random.fold_in(rng, get_pipeline_model_parallel_rank())


def _tied_head_loss(config, emb, fln, hidden, mb):
    """Final norm + weight-tied head + vocab-parallel loss for one
    microbatch — the last-stage tail both pipelined models stream.
    ``emb``/``fln`` must already carry the pipeline-replication mark."""
    hidden = _ln(fln, hidden, config.layernorm_epsilon,
                 config.sequence_parallel, config.axis_name,
                 config.normalization)
    return lm_head_loss(emb["word_embeddings"]["weight"], hidden,
                        mb["labels"], mb.get("loss_mask"), config)


@dataclass
class PipelinedGPT:
    """GPT with its layer stack split over the pipeline mesh axis.

    ``num_microbatches`` sizes the schedule scan; ``virtual_pipeline_size``
    switches to the interleaved schedule. The loss fn returned by
    :meth:`make_loss_fn` runs per-rank inside ``shard_map`` (compose with
    ``apex_tpu.training.make_train_step``).
    """

    config: TransformerConfig
    pipeline_size: int
    num_microbatches: int
    virtual_pipeline_size: Optional[int] = None

    def __post_init__(self):
        c = self.config
        self.embedding = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, init_method=c.init_method(),
            params_dtype=c.params_dtype, axis_name=c.axis_name)
        self.layer = ParallelTransformerLayer(c)
        V = self.pipeline_size * (self.virtual_pipeline_size or 1)
        if c.num_layers % V:
            raise ValueError(
                f"num_layers ({c.num_layers}) must divide evenly into "
                f"{V} (virtual) pipeline stages")
        self.layers_per_chunk = c.num_layers // V

    # -- parameters ---------------------------------------------------------

    def init(self, key: jax.Array) -> Dict[str, Any]:
        c = self.config
        k_emb, k_pos, k_tr = jax.random.split(key, 3)
        keys = jax.random.split(k_tr, c.num_layers)
        stacked = jax.vmap(self.layer.init)(keys)
        stages = arrange_layers_for_pipeline(
            stacked, self.pipeline_size, self.virtual_pipeline_size)
        return {
            "embedding": {
                "word_embeddings": self.embedding.init(k_emb),
                **position_table_params(c, k_pos),
            },
            "stages": stages,
            "final_layernorm": _ln_params(c.hidden_size, c.params_dtype,
                                          c.normalization),
        }

    def spec(self) -> Dict[str, Any]:
        return {
            "embedding": {
                "word_embeddings": self.embedding.spec(),
                **position_table_spec(self.config),
            },
            "stages": pipeline_stage_spec(self.layer.spec(),
                                          self.virtual_pipeline_size),
            "final_layernorm": _ln_spec(self.config.normalization),
        }

    # -- stage functions ----------------------------------------------------

    def _run_chunk(self, chunk_params, hidden, rng):
        """Apply this rank's layer chunk; with MoE the per-layer (pre-
        scaled) load-balancing losses are summed and returned alongside —
        ``(hidden, aux)`` — which the schedules consume via ``stage_aux``."""
        deterministic = rng is None
        moe = bool(self.config.num_moe_experts)

        def one_layer(carry, layer_params):
            h, aux, idx = carry
            layer_rng = None if rng is None else jax.random.fold_in(rng, idx)
            out = self.layer.apply(layer_params, h, rng=layer_rng,
                                   deterministic=deterministic)
            if moe:
                h, a = out
                aux = aux + a
            else:
                h = out
            return (h, aux, idx + 1), None

        (hidden, aux, _), _ = lax.scan(
            one_layer, (hidden, jnp.zeros((), jnp.float32), 0), chunk_params)
        return (hidden, aux) if moe else hidden

    def _stage_rng(self, rng, tick):
        return _pipeline_stage_rng(rng, tick)

    def _postprocess(self, params, hidden, mb):
        return _tied_head_loss(
            self.config, mark_pipeline_replicated(params["embedding"]),
            mark_pipeline_replicated(params["final_layernorm"]), hidden, mb)

    # -- schedule -----------------------------------------------------------

    def make_loss_fn(self, *, remat: bool = True):
        """Build ``loss_fn(params, microbatched_batch, rng=None) -> scalar``.

        Batch leaves are ``[M, micro_b, ...]`` (see
        ``split_batch_into_microbatches``). ``rng`` enables dropout with
        per-microbatch embedding streams and per-tick/stage layer streams.
        """
        M = self.num_microbatches

        def loss_fn(params, batch, rng=None):
            deterministic = rng is None

            def preprocess(p, mb):
                emb = mark_pipeline_replicated(p["embedding"])
                r = (None if deterministic
                     else jax.random.fold_in(rng, mb["_mb"]))
                return embed_tokens(self.embedding, emb, mb["tokens"],
                                    self.config, rng=r,
                                    deterministic=deterministic)

            def stage(p, h, tick):
                local = jax.tree.map(lambda x: x[0], p["stages"])
                return self._run_chunk(local, h, self._stage_rng(rng, tick))

            def stage_interleaved(p, h, chunk, tick):
                local = jax.tree.map(
                    lambda x: lax.dynamic_index_in_dim(x[0], chunk, 0,
                                                       keepdims=False),
                    p["stages"])
                r = self._stage_rng(rng, tick)
                r = None if r is None else jax.random.fold_in(r, chunk)
                return self._run_chunk(local, h, r)

            batch = dict(batch)
            batch["_mb"] = jnp.arange(M)
            moe = bool(self.config.num_moe_experts)
            if self.virtual_pipeline_size is not None:
                inner = make_interleaved_pipelined_loss_fn(
                    preprocess, stage_interleaved, self._postprocess,
                    M, self.virtual_pipeline_size, remat=remat,
                    stage_aux=moe)
            else:
                inner = make_pipelined_loss_fn(
                    preprocess, stage, self._postprocess, M, remat=remat,
                    stage_aux=moe)
            return inner(params, batch)

        return loss_fn


def _pad_stage_rows(stages, total_rows: int, *, front: bool):
    """Pad a ``[rows, ...]`` stage pytree with zero rows up to ``total_rows``.

    The two-section pipeline shards BOTH section's stage arrays over the
    full pipeline axis (a sharded leading dim must equal the axis size), so
    each section is zero-padded over the ranks the other section owns. The
    padding rows are dead weight by construction: ``lax.cond`` routes each
    rank to its own section, so padded rows never see compute and their
    grads are exactly zero.
    """
    def one(x):
        pad = total_rows - x.shape[0]
        if pad == 0:
            return x
        z = jnp.zeros((pad,) + x.shape[1:], x.dtype)
        return jnp.concatenate([z, x] if front else [x, z], axis=0)
    return jax.tree.map(one, stages)


@dataclass
class PipelinedEncoderDecoder:
    """T5-style encoder-decoder split over a two-section pipeline.

    Capability counterpart of the reference's ``ModelType.encoder_and_decoder``
    pipeline: ``pipeline_model_parallel_split_rank`` cuts the pipeline axis
    into an encoder section (ranks ``< split``) and a decoder section (ranks
    ``>= split``) — reference ``apex/transformer/parallel_state.py:155-247``
    (split-rank group construction) and ``pipeline_parallel/schedules/
    fwd_bwd_pipelining_without_interleaving.py:241-400`` (the enc-dec tensor
    routing in 1F1B).

    TPU design — a two-stream lock-step carry instead of heterogeneous p2p:
    the reference sends *different* tensors across the split boundary
    (encoder hidden inside the encoder section; ``(decoder hidden, encoder
    output)`` tuples inside the decoder section) with shape-polymorphic p2p.
    Under the single ``lax.scan`` + ``ppermute`` schedule every inter-stage
    payload must be one fixed pytree, so the carry is the pair ``(enc_stream,
    dec_stream)`` from tick 0: encoder ranks advance ``enc_stream`` and pass
    ``dec_stream`` through untouched; decoder ranks cross-attend the (by
    then final) ``enc_stream`` and advance ``dec_stream``. ``lax.cond`` on
    the pipeline rank picks the section, so each rank *computes* only its
    own section's layers. The extra ppermute payload (the idle stream) is
    the price of lock-step homogeneity; it buys the same
    O(pipeline-depth) 1F1B memory bound and XLA-scheduled comms as
    :class:`PipelinedGPT`, with no shape-polymorphic protocol.

    ``split_rank`` defaults to the value installed by
    ``initialize_model_parallel(pipeline_model_parallel_split_rank=...)`` —
    the consumer of ``--pipeline-model-parallel-split-rank``.

    Same restrictions as :class:`~apex_tpu.models.encoder_decoder.
    EncoderDecoderModel` (no MoE, no context parallelism) plus: no
    interleaved schedule (the reference's interleaved schedule rejects
    enc-dec too) and no encoder padding masks (full-length microbatches,
    as the reference pipeline tests use).
    """

    config: TransformerConfig
    pipeline_size: int
    num_microbatches: int
    split_rank: Optional[int] = None
    num_encoder_layers: Optional[int] = None

    def __post_init__(self):
        c = self.config
        if c.num_moe_experts:
            raise NotImplementedError(
                "MoE (num_moe_experts) is currently wired into GPT models "
                "only")
        if c.context_parallel_method:
            raise NotImplementedError(
                "context parallelism is decoder-self-attention only; the "
                "cross-attended encoder output is not sequence-sharded")
        if self.split_rank is None:
            from apex_tpu.transformer.parallel_state import (
                get_pipeline_model_parallel_split_rank,
            )
            self.split_rank = get_pipeline_model_parallel_split_rank()
        if self.split_rank is None:
            raise ValueError(
                "PipelinedEncoderDecoder needs a split rank: pass "
                "split_rank= or initialize_model_parallel("
                "pipeline_model_parallel_split_rank=...)")
        S, split = self.pipeline_size, self.split_rank
        if not 0 < split < S:
            raise ValueError(
                f"split_rank ({split}) must leave at least one encoder and "
                f"one decoder stage: need 0 < split < pipeline_size ({S})")
        from apex_tpu.transformer.enums import AttnMaskType, LayerType
        n_enc = (c.num_layers if self.num_encoder_layers is None
                 else self.num_encoder_layers)
        n_dec = c.num_layers
        if n_enc % split:
            raise ValueError(
                f"encoder depth ({n_enc}) must divide evenly into the "
                f"{split} encoder stages")
        if n_dec % (S - split):
            raise ValueError(
                f"decoder depth ({n_dec}) must divide evenly into the "
                f"{S - split} decoder stages")
        self._n_enc = n_enc
        self._enc_cfg = replace(
            c, attn_mask_type=AttnMaskType.padding, num_layers=n_enc)
        self._dec_cfg = replace(c, attn_mask_type=AttnMaskType.causal)
        self.embedding = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, init_method=c.init_method(),
            params_dtype=c.params_dtype, axis_name=c.axis_name)
        self.enc_layer = ParallelTransformerLayer(self._enc_cfg)
        self.dec_layer = ParallelTransformerLayer(self._dec_cfg,
                                                  LayerType.decoder)

    # -- parameters ---------------------------------------------------------

    def init(self, key: jax.Array) -> Dict[str, Any]:
        c = self.config
        S, split = self.pipeline_size, self.split_rank
        k_emb, k_pos, k_enc, k_dec = jax.random.split(key, 4)
        enc_stacked = jax.vmap(self.enc_layer.init)(
            jax.random.split(k_enc, self._n_enc))
        dec_stacked = jax.vmap(self.dec_layer.init)(
            jax.random.split(k_dec, c.num_layers))
        enc_stages = _pad_stage_rows(
            arrange_layers_for_pipeline(enc_stacked, split), S, front=False)
        dec_stages = _pad_stage_rows(
            arrange_layers_for_pipeline(dec_stacked, S - split), S,
            front=True)
        return {
            "embedding": {
                "word_embeddings": self.embedding.init(k_emb),
                **position_table_params(c, k_pos),
            },
            "enc_stages": enc_stages,
            "dec_stages": dec_stages,
            "enc_final_layernorm": _ln_params(c.hidden_size, c.params_dtype,
                                              c.normalization),
            "dec_final_layernorm": _ln_params(c.hidden_size, c.params_dtype,
                                              c.normalization),
        }

    def spec(self) -> Dict[str, Any]:
        return {
            "embedding": {
                "word_embeddings": self.embedding.spec(),
                **position_table_spec(self.config),
            },
            "enc_stages": pipeline_stage_spec(self.enc_layer.spec()),
            "dec_stages": pipeline_stage_spec(self.dec_layer.spec()),
            "enc_final_layernorm": _ln_spec(self.config.normalization),
            "dec_final_layernorm": _ln_spec(self.config.normalization),
        }

    # -- stage pieces -------------------------------------------------------

    def _run_section(self, layer, chunk_params, hidden, rng, enc_out=None):
        def one_layer(carry, layer_params):
            h, idx = carry
            layer_rng = None if rng is None else jax.random.fold_in(rng, idx)
            h = layer.apply(layer_params, h, encoder_output=enc_out,
                            rng=layer_rng, deterministic=rng is None)
            return (h, idx + 1), None
        (hidden, _), _ = lax.scan(one_layer, (hidden, 0), chunk_params)
        return hidden

    def _enc_final_ln(self, fln, enc_h):
        """``fln`` must already carry the pipeline-replication mark, applied
        OUTSIDE any rank-routed ``lax.cond``: the mark's backward is a psum
        over the pipeline axis, and a collective inside a branch only some
        pipeline ranks take deadlocks the group (the SPMD invariant the
        reference keeps implicitly by doing its embedding all-reduce outside
        the schedule, ``parallel_state.py:347-407``)."""
        c = self.config
        return _ln(fln, enc_h, c.layernorm_epsilon, c.sequence_parallel,
                   c.axis_name, c.normalization)

    def _gathered(self, enc_h):
        """Cross-attention wants the FULL encoder sequence; under SP the
        carry stays sequence-sharded (fixed shapes) and each decoder stage
        re-gathers — the standard SP gather-at-consumer pattern."""
        c = self.config
        if not c.sequence_parallel:
            return enc_h
        from apex_tpu.transformer.tensor_parallel.mappings import (
            axis_bound,
            gather_from_sequence_parallel_region,
        )
        if not axis_bound(c.axis_name):
            return enc_h
        return gather_from_sequence_parallel_region(enc_h, False, c.axis_name)

    def _stage_rng(self, rng, tick, section: int):
        rng = _pipeline_stage_rng(rng, tick)
        return None if rng is None else jax.random.fold_in(rng, section)

    def _postprocess(self, params, h, mb):
        _, dec_h = h
        return _tied_head_loss(
            self.config, mark_pipeline_replicated(params["embedding"]),
            mark_pipeline_replicated(params["dec_final_layernorm"]),
            dec_h, mb)

    # -- schedule -----------------------------------------------------------

    def make_loss_fn(self, *, remat: bool = True):
        """Build ``loss_fn(params, microbatched_batch, rng=None) -> scalar``.

        Batch leaves are ``[M, micro_b, ...]`` with keys ``enc_tokens``,
        ``dec_tokens``, ``labels`` (+ optional ``loss_mask``). Runs inside
        ``shard_map`` with the pipeline axis bound; with the axis unbound
        (single device) the two sections run back-to-back per microbatch —
        numerically the unpipelined :class:`~apex_tpu.models.
        encoder_decoder.EncoderDecoderModel`.
        """
        from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
        from apex_tpu.transformer.tensor_parallel.mappings import axis_bound

        M = self.num_microbatches
        split = self.split_rank

        def loss_fn(params, batch, rng=None):
            deterministic = rng is None
            pipelined = axis_bound(PIPELINE_AXIS)

            def preprocess(p, mb):
                emb = mark_pipeline_replicated(p["embedding"])
                r_e = r_d = None
                if not deterministic:
                    r = jax.random.fold_in(rng, mb["_mb"])
                    r_e, r_d = jax.random.split(r)
                enc_h = embed_tokens(self.embedding, emb, mb["enc_tokens"],
                                     self._enc_cfg, rng=r_e,
                                     deterministic=deterministic)
                dec_h = embed_tokens(self.embedding, emb, mb["dec_tokens"],
                                     self._dec_cfg, rng=r_d,
                                     deterministic=deterministic)
                return (enc_h, dec_h)

            def stage(p, h, tick):
                # replication mark hoisted out of the rank-routed branches —
                # its backward psums over the pipeline axis (see
                # _enc_final_ln)
                fln = mark_pipeline_replicated(p["enc_final_layernorm"])
                enc_h, dec_h = h
                r_enc = self._stage_rng(rng, tick, 0)
                r_dec = self._stage_rng(rng, tick, 1)
                if not pipelined:
                    # degenerate single-rank path: the full (unsharded)
                    # [S, ...] stage arrays are visible, so flatten each
                    # section's REAL rows (row 0 of dec_stages is padding)
                    # and run whole encoder, boundary norm, whole decoder
                    # in one stage
                    enc_local = jax.tree.map(
                        lambda x: x[:split].reshape((-1,) + x.shape[2:]),
                        p["enc_stages"])
                    dec_local = jax.tree.map(
                        lambda x: x[split:].reshape((-1,) + x.shape[2:]),
                        p["dec_stages"])
                    enc_h = self._run_section(self.enc_layer, enc_local,
                                              enc_h, r_enc)
                    enc_h = self._enc_final_ln(fln, enc_h)
                    dec_h = self._run_section(self.dec_layer, dec_local,
                                              dec_h, r_dec,
                                              enc_out=self._gathered(enc_h))
                    return (enc_h, dec_h)
                enc_local = jax.tree.map(lambda x: x[0], p["enc_stages"])
                dec_local = jax.tree.map(lambda x: x[0], p["dec_stages"])
                i = lax.axis_index(PIPELINE_AXIS)

                def enc_branch(h):
                    enc_h, dec_h = h
                    enc_h = self._run_section(self.enc_layer, enc_local,
                                              enc_h, r_enc)
                    enc_h = lax.cond(i == split - 1,
                                     lambda e: self._enc_final_ln(fln, e),
                                     lambda e: e, enc_h)
                    return (enc_h, dec_h)

                def dec_branch(h):
                    enc_h, dec_h = h
                    dec_h = self._run_section(self.dec_layer, dec_local,
                                              dec_h, r_dec,
                                              enc_out=self._gathered(enc_h))
                    return (enc_h, dec_h)

                return lax.cond(i < split, enc_branch, dec_branch, h)

            batch = dict(batch)
            batch["_mb"] = jnp.arange(M)
            inner = make_pipelined_loss_fn(
                preprocess, stage, self._postprocess, M, remat=remat)
            return inner(params, batch)

        return loss_fn
