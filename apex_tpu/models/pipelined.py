"""Pipeline-parallel GPT.

The pipelined flagship: embedding + tied head replicated across pipeline
stages (grads psum-synced, the analog of the reference's embedding-group
all-reduce, ``parallel_state.py:347-407``), transformer layers stacked and
sharded over the ``pipeline`` mesh axis, driven by the ``ppermute`` schedules
in :mod:`apex_tpu.transformer.pipeline_parallel.schedules`.

Capability counterpart of the reference's pipelined GPT test fixture
(``apex/transformer/testing/standalone_gpt.py`` under
``test_pipeline_parallel_fwd_bwd.py``): same TP/SP layers inside each stage,
1F1B or interleaved schedule outside, vocab-parallel loss on the last stage.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from apex_tpu.models.transformer import (
    ParallelTransformerLayer,
    TransformerConfig,
    embed_tokens,
    position_table_params,
    position_table_spec,
)
from apex_tpu.models.transformer import _ln, _ln_params, _ln_spec
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    arrange_layers_for_pipeline,
    mark_pipeline_replicated,
    pipeline_stage_spec,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (
    make_interleaved_pipelined_loss_fn,
    make_pipelined_loss_fn,
)
from apex_tpu.models.gpt import lm_head_loss
from apex_tpu.transformer.tensor_parallel.layers import VocabParallelEmbedding

__all__ = ["PipelinedGPT"]


@dataclass
class PipelinedGPT:
    """GPT with its layer stack split over the pipeline mesh axis.

    ``num_microbatches`` sizes the schedule scan; ``virtual_pipeline_size``
    switches to the interleaved schedule. The loss fn returned by
    :meth:`make_loss_fn` runs per-rank inside ``shard_map`` (compose with
    ``apex_tpu.training.make_train_step``).
    """

    config: TransformerConfig
    pipeline_size: int
    num_microbatches: int
    virtual_pipeline_size: Optional[int] = None

    def __post_init__(self):
        c = self.config
        self.embedding = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, init_method=c.init_method(),
            params_dtype=c.params_dtype, axis_name=c.axis_name)
        self.layer = ParallelTransformerLayer(c)
        V = self.pipeline_size * (self.virtual_pipeline_size or 1)
        if c.num_layers % V:
            raise ValueError(
                f"num_layers ({c.num_layers}) must divide evenly into "
                f"{V} (virtual) pipeline stages")
        self.layers_per_chunk = c.num_layers // V

    # -- parameters ---------------------------------------------------------

    def init(self, key: jax.Array) -> Dict[str, Any]:
        c = self.config
        k_emb, k_pos, k_tr = jax.random.split(key, 3)
        keys = jax.random.split(k_tr, c.num_layers)
        stacked = jax.vmap(self.layer.init)(keys)
        stages = arrange_layers_for_pipeline(
            stacked, self.pipeline_size, self.virtual_pipeline_size)
        return {
            "embedding": {
                "word_embeddings": self.embedding.init(k_emb),
                **position_table_params(c, k_pos),
            },
            "stages": stages,
            "final_layernorm": _ln_params(c.hidden_size, c.params_dtype,
                                          c.normalization),
        }

    def spec(self) -> Dict[str, Any]:
        return {
            "embedding": {
                "word_embeddings": self.embedding.spec(),
                **position_table_spec(self.config),
            },
            "stages": pipeline_stage_spec(self.layer.spec(),
                                          self.virtual_pipeline_size),
            "final_layernorm": _ln_spec(self.config.normalization),
        }

    # -- stage functions ----------------------------------------------------

    def _run_chunk(self, chunk_params, hidden, rng):
        """Apply this rank's layer chunk; with MoE the per-layer (pre-
        scaled) load-balancing losses are summed and returned alongside —
        ``(hidden, aux)`` — which the schedules consume via ``stage_aux``."""
        deterministic = rng is None
        moe = bool(self.config.num_moe_experts)

        def one_layer(carry, layer_params):
            h, aux, idx = carry
            layer_rng = None if rng is None else jax.random.fold_in(rng, idx)
            out = self.layer.apply(layer_params, h, rng=layer_rng,
                                   deterministic=deterministic)
            if moe:
                h, a = out
                aux = aux + a
            else:
                h = out
            return (h, aux, idx + 1), None

        (hidden, aux, _), _ = lax.scan(
            one_layer, (hidden, jnp.zeros((), jnp.float32), 0), chunk_params)
        return (hidden, aux) if moe else hidden

    def _stage_rng(self, rng, tick):
        """Per-tick dropout stream, decorrelated across pipeline stages (the
        Megatron RNG-tracker role, ``tensor_parallel/random.py:90-240``)."""
        if rng is None:
            return None
        from apex_tpu.transformer.parallel_state import (
            get_pipeline_model_parallel_rank,
        )
        rng = jax.random.fold_in(rng, tick)
        return jax.random.fold_in(rng, get_pipeline_model_parallel_rank())

    def _postprocess(self, params, hidden, mb):
        c = self.config
        emb = mark_pipeline_replicated(params["embedding"])
        fln = mark_pipeline_replicated(params["final_layernorm"])
        hidden = _ln(fln, hidden, c.layernorm_epsilon,
                     c.sequence_parallel, c.axis_name, c.normalization)
        return lm_head_loss(emb["word_embeddings"]["weight"], hidden,
                            mb["labels"], mb.get("loss_mask"), c)

    # -- schedule -----------------------------------------------------------

    def make_loss_fn(self, *, remat: bool = True):
        """Build ``loss_fn(params, microbatched_batch, rng=None) -> scalar``.

        Batch leaves are ``[M, micro_b, ...]`` (see
        ``split_batch_into_microbatches``). ``rng`` enables dropout with
        per-microbatch embedding streams and per-tick/stage layer streams.
        """
        M = self.num_microbatches

        def loss_fn(params, batch, rng=None):
            deterministic = rng is None

            def preprocess(p, mb):
                emb = mark_pipeline_replicated(p["embedding"])
                r = (None if deterministic
                     else jax.random.fold_in(rng, mb["_mb"]))
                return embed_tokens(self.embedding, emb, mb["tokens"],
                                    self.config, rng=r,
                                    deterministic=deterministic)

            def stage(p, h, tick):
                local = jax.tree.map(lambda x: x[0], p["stages"])
                return self._run_chunk(local, h, self._stage_rng(rng, tick))

            def stage_interleaved(p, h, chunk, tick):
                local = jax.tree.map(
                    lambda x: lax.dynamic_index_in_dim(x[0], chunk, 0,
                                                       keepdims=False),
                    p["stages"])
                r = self._stage_rng(rng, tick)
                r = None if r is None else jax.random.fold_in(r, chunk)
                return self._run_chunk(local, h, r)

            batch = dict(batch)
            batch["_mb"] = jnp.arange(M)
            moe = bool(self.config.num_moe_experts)
            if self.virtual_pipeline_size is not None:
                inner = make_interleaved_pipelined_loss_fn(
                    preprocess, stage_interleaved, self._postprocess,
                    M, self.virtual_pipeline_size, remat=remat,
                    stage_aux=moe)
            else:
                inner = make_pipelined_loss_fn(
                    preprocess, stage, self._postprocess, M, remat=remat,
                    stage_aux=moe)
            return inner(params, batch)

        return loss_fn
