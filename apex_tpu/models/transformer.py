"""Megatron-style parallel transformer blocks, TPU-native.

Capability counterpart of the reference's Megatron LM building blocks
(``apex/transformer/testing/standalone_transformer_lm.py``: ``ParallelMLP``
~:610-672, ``ParallelAttention`` ~:675-884, ``ParallelTransformerLayer``
~:1033-1148, ``ParallelTransformer`` ~:1151-1380), built on the
tensor/sequence-parallel layers of :mod:`apex_tpu.transformer.tensor_parallel`.

Design (not a port):

- modules are functional: ``init(key) -> params`` (global shapes),
  ``spec() -> PartitionSpec`` pytree, ``apply(params, ...)`` written against
  the local-shard view inside ``shard_map`` (identical code runs unsharded).
- layout is Megatron's ``[seq, batch, hidden]``; under sequence parallelism
  dim 0 holds the local sequence shard between matmul regions.
- core attention is the Pallas flash kernel (``apex_tpu.ops.flash_attention``)
  when the mask is causal/lengths-shaped and attention dropout is off;
  otherwise the :class:`FusedScaleMaskSoftmax` path with dropout, matching
  the reference's kernel-availability dispatch
  (``functional/fused_softmax.py:222-248``).
- layer stacking is ``lax.scan`` over stacked per-layer params — one trace,
  one compile, regardless of depth; optional ``jax.checkpoint`` per layer is
  the activation-recompute story (reference
  ``tensor_parallel/random.py:~240-311``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from apex_tpu.ops import (
    flash_attention,
    flash_attention_packed,
    packed_attention_supported,
    fused_layer_norm_affine,
    fused_rms_norm_affine,
)
from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType
from apex_tpu.transformer.functional import FusedScaleMaskSoftmax
from apex_tpu.transformer.parallel_state import CONTEXT_AXIS, TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    axis_size,
    mark_sequence_parallel_parameter,
)
from apex_tpu.transformer.tensor_parallel.random import model_parallel_rng_key
from apex_tpu.transformer.tensor_parallel.utils import divide
from apex_tpu.utils.activations import (
    apply_activation,
    is_gated,
    validate_activation,
)

__all__ = [
    "TransformerConfig",
    "ParallelMLP",
    "ParallelAttention",
    "ParallelTransformerLayer",
    "ParallelTransformer",
]


@dataclass(frozen=True)
class TransformerConfig:
    """Hyperparameters (subset of the reference's Megatron global args,
    ``apex/transformer/testing/arguments.py``, that shape the model)."""

    num_layers: int
    hidden_size: int
    num_attention_heads: int
    # GQA/MQA (exceeds reference): number of K/V head groups; None = MHA.
    # Query heads are split evenly over the groups; the flash kernel reads
    # shared K/V blocks per group with no HBM broadcast.
    num_query_groups: Optional[int] = None
    ffn_hidden_size: Optional[int] = None
    vocab_size: int = 32000
    max_position_embeddings: int = 2048
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layernorm_epsilon: float = 1e-5
    # "learned" (reference GPT/BERT fixtures), "rope" (rotary via the fused
    # rope op applied to q/k inside attention, the NeMo/Megatron fused_rope
    # capability in real use), or "none"
    position_embedding_type: str = "learned"
    rotary_percent: float = 1.0        # fraction of head_dim rotated
    rope_theta: float = 10000.0
    # MLP activation: "gelu" (reference ParallelMLP), "relu", or the gated
    # pairs "swiglu"/"geglu" (LLaMA/PaLM-class; one fused bias-free 2*ffn
    # column projection, gate/up unit-interleaved — utils/activations.py)
    activation: str = "gelu"
    # "layernorm" (reference) or "rmsnorm" (LLaMA-class; bias-free, RMS
    # statistics via the fused Pallas RMSNorm kernel)
    normalization: str = "layernorm"
    attn_mask_type: AttnMaskType = AttnMaskType.causal
    # Mistral-class local attention: keep only the last sliding_window keys
    # per query (causal only); far-past flash blocks are skipped, cost
    # O(seq * window). None = full attention.
    sliding_window: Optional[int] = None
    sequence_parallel: bool = False
    # context parallelism (long-context; the reference has none, SURVEY.md §5):
    # None | "ring" (ppermute KV rotation) | "ulysses" (all-to-all head swap)
    context_parallel_method: Optional[str] = None
    context_axis: str = CONTEXT_AXIS
    # MoE (exceeds reference, SURVEY.md §2.2 EP: absent): when set, every
    # layer's MLP becomes a SwitchMLP with this many experts; apply() then
    # returns (hidden, aux_loss)
    num_moe_experts: Optional[int] = None
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_loss_weight: float = 1e-2
    moe_router_jitter: float = 0.0
    moe_expert_axis: Optional[str] = None   # e.g. "data" for EP over DP
    # activation recompute: False = save everything; True/'full' = full
    # per-layer recompute (reference `tensor_parallel.random.checkpoint`
    # semantics); 'selective' = save matmul outputs, recompute elementwise
    # (Megatron's selective activation recompute, expressed as a
    # jax.checkpoint dot-saveable policy instead of hand-split forward)
    recompute: Any = False
    # lax.scan unroll factor for the layer stack: >1 trades compile time
    # for fewer while-loop iterations and cross-layer fusion of the
    # activation-save writes (the dynamic-update-slice traffic)
    scan_unroll: int = 1
    # compute the LM-head loss in this many sequence chunks (remat'd scan)
    # so only one chunk's [s/nc, b, V] logits ever materialize — the
    # long-context memory guard for the vocab head (no-op at 1, under SP,
    # or when the sequence does not divide evenly)
    loss_seq_chunks: int = 1
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32  # activations cast at block entry
    init_method_std: float = 0.02
    axis_name: str = TENSOR_AXIS

    def __post_init__(self):
        if self.position_embedding_type not in ("learned", "rope", "none"):
            raise ValueError(
                f"position_embedding_type must be 'learned', 'rope', or "
                f"'none', got {self.position_embedding_type!r}")
        if not 0.0 < self.rotary_percent <= 1.0:
            raise ValueError(
                f"rotary_percent must be in (0, 1], got "
                f"{self.rotary_percent}")
        validate_activation(self.activation)
        if self.normalization not in ("layernorm", "rmsnorm"):
            raise ValueError(
                f"normalization must be 'layernorm' or 'rmsnorm', got "
                f"{self.normalization!r}")
        if self.sliding_window is not None:
            if self.sliding_window < 1:
                raise ValueError(
                    f"sliding_window must be >= 1, got "
                    f"{self.sliding_window}")
            if self.attn_mask_type != AttnMaskType.causal:
                raise ValueError("sliding_window requires causal attention")
            # under context parallelism the window is exact across chunk
            # boundaries: ring masks with global positions, ulysses windows
            # the gathered full sequence

    @property
    def ffn_size(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return divide(self.hidden_size, self.num_attention_heads)

    @property
    def kv_heads(self) -> int:
        """K/V heads per replica (== query heads unless GQA/MQA)."""
        if self.num_query_groups is None:
            return self.num_attention_heads
        divide(self.num_attention_heads, self.num_query_groups)  # validates
        return self.num_query_groups

    @property
    def rotary_dim(self) -> int:
        """Even number of head-dim channels rotated by RoPE (≥ 2; a
        rotary_percent low enough to round below 2 is rejected)."""
        rot = int(self.head_dim * self.rotary_percent)
        rot -= rot % 2
        if rot < 2:
            raise ValueError(
                f"rotary_percent ({self.rotary_percent}) rotates fewer than "
                f"2 of {self.head_dim} head-dim channels; use "
                f"position_embedding_type='none' to disable rotation")
        return rot

    def init_method(self) -> Callable:
        std = self.init_method_std
        return jax.nn.initializers.normal(stddev=std)

    def output_init_method(self) -> Callable:
        # Megatron scales residual-output layer init by 1/sqrt(2*L)
        # (standalone_transformer_lm.py `scaled_init_method_normal`).
        std = self.init_method_std / (2.0 * self.num_layers) ** 0.5
        return jax.nn.initializers.normal(stddev=std)


def position_table_params(config: "TransformerConfig", key) -> dict:
    """Learned-position table params, or ``{}`` under rope/none — the one
    shared guard every model's ``init`` uses so param trees stay consistent
    across GPT/BERT/encoder-decoder/pipelined for the same config."""
    if config.position_embedding_type != "learned":
        return {}
    return {"position_embeddings": config.init_method()(
        key, (config.max_position_embeddings, config.hidden_size),
        config.params_dtype)}


def position_table_spec(config: "TransformerConfig") -> dict:
    if config.position_embedding_type != "learned":
        return {}
    return {"position_embeddings": PartitionSpec()}


def rope_freqs(start, length: int, rot_dim: int, theta: float) -> jax.Array:
    """RoPE angles for positions ``[start, start+length)`` in the layout
    :func:`apex_tpu.ops.fused_rope` expects: ``[s, 1, 1, rot_dim]`` with the
    Megatron ``concat(f, f)`` convention (reference
    ``apex/transformer/functional/fused_rope.py`` pairs with
    ``RotaryEmbedding`` in NeMo producing exactly this). ``start`` may be a
    traced value (decode offset, context-parallel shard offset), or a
    ``[batch]`` VECTOR of per-row offsets (the serving engine's
    continuous-batching decode, where every cache slot sits at its own
    position) — then the return is ``[s, batch, 1, rot_dim]``, which
    broadcasts against ``[s, b, h, d]`` q/k exactly like the scalar form."""
    inv = 1.0 / theta ** (jnp.arange(0, rot_dim, 2, dtype=jnp.float32)
                          / rot_dim)
    if getattr(start, "ndim", 0) == 1:
        pos = (jnp.asarray(start, jnp.float32)[None, :]
               + jnp.arange(length, dtype=jnp.float32)[:, None])  # [s, b]
        f = pos[:, :, None] * inv[None, None, :]      # [s, b, rot_dim/2]
        return jnp.concatenate([f, f], axis=-1)[:, :, None, :]
    pos = start + jnp.arange(length, dtype=jnp.float32)
    f = pos[:, None] * inv[None, :]                   # [s, rot_dim/2]
    return jnp.concatenate([f, f], axis=-1)[:, None, None, :]


def _dropout(x, rate, key, deterministic, model_parallel_region, axis_name):
    """Dropout with Megatron RNG semantics: inside model-parallel regions
    each TP rank draws a distinct mask (reference
    ``tensor_parallel/random.py:90-240``); in replicated regions all ranks
    draw the same mask."""
    if deterministic or rate == 0.0 or key is None:
        return x
    if model_parallel_region:
        key = model_parallel_rng_key(key, axis_name)
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


def embed_tokens(embedding, emb_params, tokens, config, *, tokentype_params=None,
                 tokentype_ids=None, rng=None, deterministic=True):
    """Shared embedding pipeline: word + position (+ tokentype) lookups,
    [b,s,h] -> [s,b,h] transpose, SP scatter, embedding dropout (reference
    ``standalone_transformer_lm.py`` ``Embedding.forward``)."""
    c = config
    from apex_tpu.transformer.tensor_parallel.mappings import axis_bound

    emb = embedding.apply(emb_params["word_embeddings"], tokens)
    s_local = tokens.shape[1]
    if c.position_embedding_type == "learned":
        if c.context_parallel_method and axis_bound(c.context_axis):
            # tokens are this context rank's contiguous sequence chunk:
            # position ids start at rank * s_local. dynamic_slice clamps
            # out-of-range starts, so overlong sequences must be rejected
            # loudly here (the unsharded path fails with a shape error
            # instead).
            cp = axis_size(c.context_axis)
            if cp * s_local > c.max_position_embeddings:
                raise ValueError(
                    f"global sequence length ({cp} context shards x "
                    f"{s_local}) exceeds max_position_embeddings "
                    f"({c.max_position_embeddings})")
            offset = lax.axis_index(c.context_axis) * s_local
            pos = lax.dynamic_slice_in_dim(
                emb_params["position_embeddings"], offset, s_local, axis=0)
        else:
            pos = emb_params["position_embeddings"][:s_local]
        emb = emb + pos[None, :, :]
    if tokentype_ids is not None:
        emb = emb + jnp.take(tokentype_params, tokentype_ids, axis=0)
    hidden = emb.transpose(1, 0, 2).astype(c.compute_dtype)
    if c.sequence_parallel:
        from apex_tpu.transformer.tensor_parallel.mappings import (
            scatter_to_sequence_parallel_region,
        )
        hidden = scatter_to_sequence_parallel_region(hidden, c.axis_name)
    return _dropout(hidden, c.hidden_dropout, rng, deterministic,
                    model_parallel_region=c.sequence_parallel,
                    axis_name=c.axis_name)


def _ln_params(hidden_size, dtype, norm: str = "layernorm"):
    p = {"weight": jnp.ones((hidden_size,), dtype)}
    if norm == "layernorm":
        p["bias"] = jnp.zeros((hidden_size,), dtype)
    return p


def _ln_spec(norm: str = "layernorm"):
    s = {"weight": PartitionSpec()}
    if norm == "layernorm":
        s["bias"] = PartitionSpec()
    return s


def _ln(params, x, eps, sequence_parallel=False, axis_name=TENSOR_AXIS,
        norm: str = "layernorm"):
    w = params["weight"]
    if sequence_parallel:
        # norm runs on sequence shards; psum the param grads (reference
        # layer_norm.py:26-99 ``sequence_parallel_enabled`` marking)
        w = mark_sequence_parallel_parameter(w, axis_name)
    # out_dtype=x.dtype: the consumer (QKV/MLP GEMM, residual add) runs in
    # the compute dtype, so promote-to-fp32 output (bf16 x, fp32 norm
    # params) would write 2x the bytes only for a convert to follow —
    # measured ~3 ms/step of fp32 LN writes + converts on BERT (round 5)
    if norm == "rmsnorm":
        return fused_rms_norm_affine(x, w, (x.shape[-1],), eps,
                                     out_dtype=x.dtype)
    b = params["bias"]
    if sequence_parallel:
        b = mark_sequence_parallel_parameter(b, axis_name)
    return fused_layer_norm_affine(x, w, b, (x.shape[-1],), eps,
                                   out_dtype=x.dtype)


def _lora_delta(x, lora):
    """Per-slot low-rank delta for one target projection: ``(x @ A) @ B``
    with PER-BATCH-ELEMENT factors — ``x [s, b, h]``, ``A [b, h, r]``,
    ``B [b, r, out(_local)]`` -> ``[s, b, out]``. The serving engine
    gathers each slot's factors from the adapter bank by adapter index
    (apex_tpu.lora; the null row is all-zeros, so base-traffic slots add
    an exact 0). Math in fp32 — the factors train in fp32 and rank is
    tiny, so the two skinny GEMMs round once at the final cast."""
    xf = x.astype(jnp.float32)
    d = jnp.einsum("sbh,bhr->sbr", xf, lora["A"].astype(jnp.float32))
    return jnp.einsum("sbr,bro->sbo", d, lora["B"].astype(jnp.float32))


@dataclass
class ParallelMLP:
    """h -> ffn (column) -> act -> h (row).

    Reference: ``standalone_transformer_lm.py`` ``ParallelMLP`` (~:610-672):
    ColumnParallelLinear with ``gather_output=False``, fused bias-gelu,
    RowParallelLinear with ``input_is_parallel=True``. Gated activations
    (``config.activation = "swiglu"/"geglu"``, LLaMA/PaLM-class — exceeds
    the gelu-only reference) widen the column projection to ``2*ffn`` with
    gate/up **unit-interleaved** along the output dim (column ``2i`` =
    gate_i, ``2i+1`` = up_i), so one matmul + one input-grad collective
    serves both halves and every TP slice holds matched pairs.
    """

    config: TransformerConfig

    def __post_init__(self):
        c = self.config
        self.gated = is_gated(c.activation)
        # gated projections are bias-free (LLaMA convention; the pre-fusion
        # gate_proj had bias=False — the fused layout keeps that invariant
        # for both halves)
        self.dense_h_to_4h = ColumnParallelLinear(
            c.hidden_size, (2 if self.gated else 1) * c.ffn_size,
            gather_output=False, bias=not self.gated,
            init_method=c.init_method(),
            sequence_parallel_enabled=c.sequence_parallel,
            params_dtype=c.params_dtype, axis_name=c.axis_name)
        self.dense_4h_to_h = RowParallelLinear(
            c.ffn_size, c.hidden_size, input_is_parallel=True,
            init_method=c.output_init_method(),
            sequence_parallel_enabled=c.sequence_parallel,
            params_dtype=c.params_dtype, axis_name=c.axis_name)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {"dense_h_to_4h": self.dense_h_to_4h.init(k1),
                "dense_4h_to_h": self.dense_4h_to_h.init(k2)}

    def spec(self):
        return {"dense_h_to_4h": self.dense_h_to_4h.spec(),
                "dense_4h_to_h": self.dense_4h_to_h.spec()}

    def apply(self, params, hidden, *, lora=None):
        c = self.config
        x = self.dense_h_to_4h.apply(params["dense_h_to_4h"], hidden)
        if lora is not None:
            x = x + _lora_delta(hidden, lora).astype(x.dtype)
        x = apply_activation(x, c.activation)
        return self.dense_4h_to_h.apply(params["dense_4h_to_h"], x)


@dataclass
class ParallelAttention:
    """Self- or cross-attention with TP-sharded heads.

    Reference: ``standalone_transformer_lm.py`` ``ParallelAttention``
    (~:675-884): fused QKV ColumnParallelLinear (``gather_output=False``) for
    self-attention, separate Q and fused KV projections for cross-attention
    (``attention_type == AttnType.cross_attn`` branch), per-rank head slice,
    core attention (fused softmax + dropout + BMMs or flash),
    RowParallelLinear output projection.
    """

    config: TransformerConfig
    attn_type: Any = AttnType.self_attn

    def __post_init__(self):
        c = self.config
        if self.attn_type == AttnType.self_attn:
            # fused QKV, grouped layout [g0: qpg·dh + k·dh + v·dh | g1: ...]
            # so a TP slice holds whole K/V groups (Megatron fuses the same
            # way for plain MHA; the grouped layout generalizes it to GQA)
            qpg = c.num_attention_heads // c.kv_heads
            qkv_size = c.kv_heads * (qpg + 2) * c.head_dim
            self.query_key_value = ColumnParallelLinear(
                c.hidden_size, qkv_size, gather_output=False,
                init_method=c.init_method(),
                sequence_parallel_enabled=c.sequence_parallel,
                params_dtype=c.params_dtype, axis_name=c.axis_name)
        else:
            self.query = ColumnParallelLinear(
                c.hidden_size, c.hidden_size, gather_output=False,
                init_method=c.init_method(),
                sequence_parallel_enabled=c.sequence_parallel,
                params_dtype=c.params_dtype, axis_name=c.axis_name)
            # CONTRACT: encoder_output is the full (gathered) sequence; the
            # KV projection runs without SP, so a sequence-sharded input
            # would silently attend over one shard — callers under SP must
            # gather first (see ParallelTransformerLayer.apply docstring)
            self.key_value = ColumnParallelLinear(
                c.hidden_size, 2 * c.hidden_size, gather_output=False,
                init_method=c.init_method(),
                sequence_parallel_enabled=False,
                params_dtype=c.params_dtype, axis_name=c.axis_name)
        self.dense = RowParallelLinear(
            c.hidden_size, c.hidden_size, input_is_parallel=True,
            init_method=c.output_init_method(),
            sequence_parallel_enabled=c.sequence_parallel,
            params_dtype=c.params_dtype, axis_name=c.axis_name)
        self.scale_mask_softmax = FusedScaleMaskSoftmax(
            attn_mask_type=(AttnMaskType.padding
                            if self.attn_type == AttnType.cross_attn
                            else c.attn_mask_type),
            scaled_masked_softmax_fusion=True,
            softmax_in_fp32=True)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        if self.attn_type == AttnType.self_attn:
            return {"query_key_value": self.query_key_value.init(k1),
                    "dense": self.dense.init(k2)}
        k1a, k1b = jax.random.split(k1)
        return {"query": self.query.init(k1a),
                "key_value": self.key_value.init(k1b),
                "dense": self.dense.init(k2)}

    def spec(self):
        if self.attn_type == AttnType.self_attn:
            return {"query_key_value": self.query_key_value.spec(),
                    "dense": self.dense.spec()}
        return {"query": self.query.spec(),
                "key_value": self.key_value.spec(),
                "dense": self.dense.spec()}

    def _core_attention(self, q, k, v, attention_mask, kv_lengths,
                        rng, deterministic, window=None):
        """q/k/v: [b, local_heads, s, dh]. ``window``: sliding-window span
        for THIS call — the caller zeroes it on the cache-decode path, where
        the window is already folded into ``attention_mask`` at the correct
        cache offsets (the generic row/col clause below assumes queries sit
        at the sequence end, which padded caches violate)."""
        c = self.config
        causal = (self.attn_type == AttnType.self_attn
                  and c.attn_mask_type == AttnMaskType.causal)
        if c.context_parallel_method and self.attn_type != AttnType.self_attn:
            raise NotImplementedError(
                "context parallelism shards the self-attention sequence; "
                "cross-attention K/V come from the (unsharded) encoder")
        if (k.shape[1] != q.shape[1]
                and c.context_parallel_method == "ulysses"):
            from apex_tpu.transformer.tensor_parallel.mappings import (
                axis_bound,
            )
            cp_sz = (axis_size(c.context_axis)
                     if axis_bound(c.context_axis) else 1)
            if k.shape[1] % cp_sz:
                # GQA under Ulysses needs kv_heads divisible by cp for the
                # head all-to-all (grouped reads stay aligned after the
                # swap); broadcast K/V heads only up to the SMALLEST such
                # multiple — the repeat factor must also divide the query
                # group so each repeated head serves a whole subgroup. The
                # ring path reads shared K/V natively (small chunks rotate).
                group = q.shape[1] // k.shape[1]
                rep = next((r for r in range(1, group + 1)
                            if group % r == 0
                            and (k.shape[1] * r) % cp_sz == 0),
                           group)   # fallback: ulysses raises its own error
                k = jnp.repeat(k, rep, axis=1)
                v = jnp.repeat(v, rep, axis=1)
        if c.context_parallel_method:
            from apex_tpu.ops.ring_attention import (
                ring_attention,
                ulysses_attention,
            )
            if attention_mask is not None or (
                    not deterministic and c.attention_dropout > 0.0):
                raise NotImplementedError(
                    "context parallelism supports causal/full attention "
                    "without attention dropout or explicit masks")
            fn = {"ring": ring_attention,
                  "ulysses": ulysses_attention}[c.context_parallel_method]
            # kv_lengths are GLOBAL valid lengths for both CP methods
            return fn(q, k, v, causal=causal, axis_name=c.context_axis,
                      kv_lengths=kv_lengths, sliding_window=window)
        use_flash = attention_mask is None and (
            deterministic or c.attention_dropout == 0.0)
        if use_flash:
            return flash_attention(q, k, v, causal=causal,
                                   kv_lengths=kv_lengths,
                                   sliding_window=window)
        if kv_lengths is not None:
            # fold varlen lengths into the boolean mask (True = masked out)
            # so the unfused path matches flash semantics
            invalid = jnp.arange(k.shape[2])[None, None, None, :] >= \
                kv_lengths[:, None, None, None]
            attention_mask = invalid if attention_mask is None else (
                jnp.logical_or(attention_mask, invalid))
        if window is not None and causal:
            # window clause for the unfused path (the causal clause rides
            # the mask-type dispatcher / explicit mask)
            row = jnp.arange(q.shape[2])[None, None, :, None]
            col = jnp.arange(k.shape[2])[None, None, None, :]
            far = col <= row + (k.shape[2] - q.shape[2]) - window
            attention_mask = far if attention_mask is None else (
                jnp.logical_or(attention_mask, far))
        inv_scale = jnp.sqrt(
            jnp.asarray(c.head_dim, jnp.float32)).astype(q.dtype)
        if k.shape[1] != q.shape[1]:
            # grouped einsum: q heads fold into [kv_heads, group] so K/V are
            # contracted once per group with no HBM broadcast copy
            g = q.shape[1] // k.shape[1]
            qg = q.reshape(q.shape[0], k.shape[1], g, *q.shape[2:])
            scores = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k) / inv_scale
            scores = scores.reshape(q.shape[0], q.shape[1], *scores.shape[3:])
            probs = self.scale_mask_softmax(scores, attention_mask)
            probs = _dropout(probs, c.attention_dropout, rng, deterministic,
                             model_parallel_region=True, axis_name=c.axis_name)
            pg = probs.reshape(q.shape[0], k.shape[1], g, *probs.shape[2:])
            ctx = jnp.einsum("bhgqk,bhkd->bhgqd", pg.astype(v.dtype), v)
            return ctx.reshape(q.shape[0], q.shape[1], *ctx.shape[3:])
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / inv_scale
        probs = self.scale_mask_softmax(scores, attention_mask)
        probs = _dropout(probs, c.attention_dropout, rng, deterministic,
                         model_parallel_region=True, axis_name=c.axis_name)
        return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)

    def _flat_cache_attention(self, params, q, k, v, ck, cv, cache_index,
                              attention_mask, kv_lengths, rng,
                              deterministic):
        """Incremental decode over a FLAT ``[b, S, kvh*dh]`` cache pair.

        Same semantics as the 4D cached path (causal/prefix mask over the
        padded cache, sliding window, ``kv_lengths``, GQA grouping,
        dropout) but the cache keeps heads*head_dim fused as the minor
        dimension so reads and the one-row write stay full-lane, and the
        single-token path reads both cache streams through MXU GEMMs so
        XLA's layout assignment has no reason to re-lay the carry (see
        the in-branch comments; the per-head view is a bitcast —
        ``reshape`` splitting the minor dim).
        ``q``/``k``/``v`` arrive as ``[b, local_heads, s, dh]``.

        ``cache_index`` may be a ``[b]`` VECTOR of per-row offsets
        (continuous batching: each cache row is an independent request at
        its own position) — the write becomes a per-row scatter and the
        causal mask is taken per row, so one batched decode step serves
        rows at arbitrary, unequal positions.
        """
        c = self.config
        dh = c.head_dim
        b, hl, s, _ = q.shape
        kvh = k.shape[1]
        kf = k.transpose(0, 2, 1, 3).reshape(b, s, kvh * dh)
        vf = v.transpose(0, 2, 1, 3).reshape(b, s, kvh * dh)
        if getattr(cache_index, "ndim", 0) == 1:
            # per-row offsets: each row r writes its s tokens at
            # [cache_index[r], cache_index[r]+s) in its own cache row
            row_update = jax.vmap(
                lambda cache, update, idx: lax.dynamic_update_slice(
                    cache, update, (idx, 0)))
            ck = row_update(ck, kf.astype(ck.dtype), cache_index)
            cv = row_update(cv, vf.astype(cv.dtype), cache_index)
            ci = cache_index[:, None, None, None]         # [b, 1, 1, 1]
        else:
            ck = lax.dynamic_update_slice(ck, kf.astype(ck.dtype),
                                          (0, cache_index, 0))
            cv = lax.dynamic_update_slice(cv, vf.astype(cv.dtype),
                                          (0, cache_index, 0))
            ci = cache_index
        S = ck.shape[1]
        # identical mask to the 4D cached branch: query i of the slice may
        # see slots j <= cache_index + i, within the window and (varlen)
        # below the row's valid length
        slots = jnp.arange(S)[None, None, None, :]
        allowed_up_to = ci + jnp.arange(s)[None, None, :, None]
        invalid = slots > allowed_up_to
        if c.sliding_window is not None:
            invalid = jnp.logical_or(
                invalid, slots <= allowed_up_to - c.sliding_window)
        if kv_lengths is not None:
            invalid = jnp.logical_or(
                invalid, slots >= kv_lengths[:, None, None, None])
        mask = (invalid if attention_mask is None
                else jnp.logical_or(attention_mask, invalid))
        inv_scale = jnp.sqrt(
            jnp.asarray(c.head_dim, jnp.float32)).astype(q.dtype)
        g = hl // kvh
        if s == 1:
            # single-token fast path. The per-head einsum formulation lets
            # XLA's layout assignment put the SEQUENCE dim minor on the
            # cache carry (the softmax's preference propagates backward),
            # which turns the one-row cache write into a full-cache copy
            # every step (measured 0.5 ms/step at 124M bs8). Instead BOTH
            # cache streams go through MXU GEMMs:
            #   scores = K_flat @ Qblock  — one GEMM per batch, where
            #     Qblock [kvh*dh, hl] holds each query head's vector in its
            #     K/V head's row block and zeros elsewhere, so the cache is
            #     read as contiguous full-lane [S, kvh*dh] rows (the 12x
            #     redundant MACs are free — decode is bandwidth-bound);
            #   ctx = probs @ V_flat — every (head, V column) pair, each
            #     head's own dh block kept by a static selector.
            # Neither expression gives XLA a reason to re-lay the carry.
            q2 = q[:, :, 0, :]                            # [b, hl, dh]
            q_tiled = jnp.tile(q2.transpose(0, 2, 1), (1, kvh, 1))
            frow = jnp.arange(kvh * dh)[:, None]
            jcol = jnp.arange(hl)[None, :]
            blockmask = (frow // dh == jcol // g).astype(q.dtype)
            qblock = q_tiled * blockmask                  # [b, kvh*dh, hl]
            scores = jnp.einsum("bsf,bfh->bsh", ck.astype(q.dtype),
                                qblock) / inv_scale       # [b, S, hl]
            neg = jnp.asarray(-1e30, jnp.float32)
            invalid1 = jnp.swapaxes(mask[:, 0], 1, 2)     # [b|1, S, 1]
            sf = jnp.where(invalid1, neg, scores.astype(jnp.float32))
            sf = sf - jnp.max(sf, axis=1, keepdims=True)
            e = jnp.exp(sf)
            probs = (e / jnp.sum(e, axis=1, keepdims=True)).astype(q.dtype)
            probs = _dropout(probs, c.attention_dropout, rng, deterministic,
                             model_parallel_region=True,
                             axis_name=c.axis_name)
            # context as a second MXU GEMM over the flat V (an elementwise
            # broadcast-multiply-reduce here makes XLA lay the V carry
            # S-minor, reintroducing the full-cache-copy write): compute
            # every (query head, V column) pair, then keep each head's own
            # dh block — kvh x redundant MACs, still free on the MXU
            ctx_big = jnp.einsum("bsh,bsf->bhf", probs,
                                 cv.astype(q.dtype))      # [b, hl, kvh*dh]
            sel = (jnp.arange(kvh)[None, :]
                   == (jnp.arange(hl) // g)[:, None]).astype(q.dtype)
            ctx = jnp.einsum("bjkd,jk->bjd",
                             ctx_big.reshape(b, hl, kvh, dh), sel)
            ctx = ctx.reshape(b, hl * dh)[None]           # [1, b, hl*dh]
            out = self.dense.apply(params["dense"], ctx)
            return out, (ck, cv)
        K4 = ck.reshape(b, S, kvh, dh).astype(q.dtype)
        V4 = cv.reshape(b, S, kvh, dh).astype(q.dtype)
        qg = q.reshape(b, kvh, g, s, dh)
        scores = jnp.einsum("bhgqd,bkhd->bhgqk", qg, K4) / inv_scale
        scores = scores.reshape(b, hl, s, S)
        probs = self.scale_mask_softmax(scores, mask)
        probs = _dropout(probs, c.attention_dropout, rng, deterministic,
                         model_parallel_region=True, axis_name=c.axis_name)
        pg = probs.astype(V4.dtype).reshape(b, kvh, g, s, S)
        ctx = jnp.einsum("bhgqk,bkhd->bhgqd", pg, V4).reshape(b, hl, s, dh)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, hl * dh)
        out = self.dense.apply(params["dense"], ctx)
        return out, (ck, cv)

    def apply(self, params, hidden, *, encoder_output=None,
              attention_mask=None, kv_lengths=None, kv_cache=None,
              cache_index=None, rng=None, deterministic=True,
              dropout_seed=None, paged_state=None, lora=None):
        """hidden: [s(, shard), b, h] -> [s(, shard), b, h]; cross-attention
        reads K/V from ``encoder_output`` [s_enc, b, h].

        ``dropout_seed`` (scalar/``(1,)`` i32) overrides the packed path's
        in-kernel attention-dropout hash seed — the transformer stack
        passes a per-layer offset of ONE base draw so masks are
        structurally distinct across layers (independent 32-bit draws per
        layer collide at ~L^2/2^33 per step and would then share a mask).
        The XLA/bernoulli dropout paths key on ``rng`` and ignore it.

        Incremental decoding: pass ``kv_cache=(k, v)`` (``[b, local_kv_heads,
        S_max, dh]`` each — K/V heads, i.e. ``num_query_groups`` under
        GQA/MQA) and ``cache_index`` (tokens already cached); the
        current K/V are written at that offset, attention runs over the
        cache, and the return becomes ``(out, new_cache)``. On the FLAT
        cache form ``cache_index`` may be a ``[b]`` vector of per-row
        offsets (continuous batching; rope rotates each row at its own
        position).

        ``paged_state`` (a ``[b, pages_per_slot]`` int32 page table)
        switches the cache interpretation to the PAGED pool form: the
        ``kv_cache`` pair is ``[n_pages, page_size, kv_heads*head_dim]``
        pools shared by all slots and ``cache_index`` must be the ``[b]``
        per-row position vector — single-token decode only, served by the
        fused append+attend op (:mod:`apex_tpu.ops.decode_attention`).
        """
        c = self.config
        dh = c.head_dim
        if self.attn_type == AttnType.self_attn:
            qkv = self.query_key_value.apply(params["query_key_value"],
                                             hidden)
            if lora is not None:
                # per-slot low-rank QKV delta (B pre-sliced to the local
                # out-dim under TP, so the delta matches the qkv slice)
                qkv = qkv + _lora_delta(hidden, lora).astype(qkv.dtype)
            s, b = qkv.shape[0], qkv.shape[1]
            qpg = c.num_attention_heads // c.kv_heads
            block = (qpg + 2) * dh
            if qkv.shape[-1] % block:
                raise ValueError(
                    f"tensor-parallel slice of the fused QKV projection "
                    f"({qkv.shape[-1]}) cuts through a K/V group (group "
                    f"block = {block}); num_query_groups ({c.kv_heads}) "
                    f"must be divisible by the tensor-parallel size")
            local_groups = qkv.shape[-1] // block
            # layout-native fast path: feed the packed projection straight
            # to the attention kernel and get ctx back in [s, b, h*dh] —
            # no [b,h,s,dh] transposes in either direction, and the VJP
            # emits the packed dqkv cotangent the wgrad GEMM wants (at
            # 355M the transposes + cotangent reassembly were ~18 ms of a
            # 202 ms step — PERF.md round 5)
            drop_active = (not deterministic
                           and c.attention_dropout > 0.0)
            if (kv_cache is None and cache_index is None
                    and attention_mask is None
                    and not c.context_parallel_method
                    and (not drop_active or rng is not None)
                    and packed_attention_supported(s, local_groups, qpg,
                                                   dh)):
                freqs = None
                if c.position_embedding_type == "rope":
                    # positions start at 0: no cache offset (cache_index
                    # gated above) and no bound context axis (CP gated
                    # above)
                    freqs = rope_freqs(0, s, c.rotary_dim, c.rope_theta)
                seed = None
                if drop_active:
                    if dropout_seed is not None:
                        seed = jnp.asarray(dropout_seed,
                                           jnp.int32).reshape(1)
                    else:
                        # Megatron RNG semantics: attention dropout lives
                        # in a model-parallel region — each TP rank draws
                        # its own mask (same convention as _dropout)
                        dkey = model_parallel_rng_key(rng, c.axis_name)
                        seed = jax.random.randint(
                            dkey, (1,), -2**31, 2**31 - 1, jnp.int32)
                ctx = flash_attention_packed(
                    qkv, queries_per_group=qpg, head_dim=dh,
                    causal=c.attn_mask_type == AttnMaskType.causal,
                    kv_lengths=kv_lengths,
                    sliding_window=c.sliding_window,
                    rope_freqs=freqs,
                    dropout_rate=(c.attention_dropout if drop_active
                                  else 0.0),
                    dropout_seed=seed)
                return self.dense.apply(params["dense"], ctx)
            qkv = qkv.reshape(s, b, local_groups, qpg + 2, dh)
            q = qkv[:, :, :, :qpg].reshape(s, b, local_groups * qpg, dh)
            k = qkv[:, :, :, qpg]
            v = qkv[:, :, :, qpg + 1]
            local_heads = local_groups * qpg
            if c.position_embedding_type == "rope":
                from apex_tpu.ops import fused_rope
                from apex_tpu.transformer.tensor_parallel.mappings import (
                    axis_bound,
                )

                start = 0 if cache_index is None else cache_index
                if c.context_parallel_method and axis_bound(c.context_axis):
                    if cache_index is not None:
                        raise NotImplementedError(
                            "incremental decode (kv_cache) with a bound "
                            "context-parallel axis and rope positions: the "
                            "per-rank rope offset for a sharded cache is "
                            "not wired up — decode without the context "
                            "axis")
                    start = lax.axis_index(c.context_axis) * s
                freqs = rope_freqs(start, s, c.rotary_dim, c.rope_theta)
                q = fused_rope(q, freqs)
                k = fused_rope(k, freqs)
        else:
            if encoder_output is None:
                raise ValueError("cross-attention needs encoder_output")
            q = self.query.apply(params["query"], hidden)
            kv = self.key_value.apply(params["key_value"], encoder_output)
            s, b = q.shape[0], q.shape[1]
            local_heads = q.shape[-1] // dh
            q = q.reshape(s, b, local_heads, dh)
            kv = kv.reshape(kv.shape[0], b, local_heads, 2 * dh)
            k, v = jnp.split(kv, 2, axis=-1)
        # [s, b, hl, dh] -> [b, hl, s, dh]
        q, k, v = (t.transpose(1, 2, 0, 3) for t in (q, k, v))
        new_cache = None
        if kv_cache is not None:
            if self.attn_type != AttnType.self_attn:
                raise NotImplementedError(
                    "kv_cache is for self-attention decode; cross-attention "
                    "K/V are static — precompute them once instead")
            ck, cv = kv_cache
            if paged_state is not None:
                # PAGED decode: the cache pair is the global page pool and
                # ``paged_state`` maps this batch's slots onto it. The
                # fused op appends each row's K/V at its own position and
                # attends over its mapped pages in one pass (one HBM read
                # of the KV stream per step); its reference path replays
                # the flat s==1 formulation below bit-for-bit on the
                # gathered logical view, so paged serving stays
                # token-exact against the flat engine. ``s > 1`` is the
                # speculative verify window: each slot appends/attends a
                # window of ``s`` rows starting at its own cache_index
                # (window query t masks to rows <= index + t). With an
                # int8 pool each of ck/cv is a ``(pages, scales)`` pair
                # and the op carries the per-page scales through.
                if attention_mask is not None or kv_lengths is not None:
                    raise NotImplementedError(
                        "paged decode derives validity from cache_index; "
                        "attention_mask/kv_lengths are not supported")
                from apex_tpu.ops import fused_paged_decode_attention
                k_scales = v_scales = None
                if isinstance(ck, (tuple, list)):
                    ck, k_scales = ck
                    cv, v_scales = cv
                kvh_l = k.shape[1]
                # [b, hl, s, dh] -> windowed [b, s, hl, dh] / [b, s, f]
                qw = q.transpose(0, 2, 1, 3)
                kw = k.transpose(0, 2, 1, 3).reshape(b, s, kvh_l * dh)
                vw = v.transpose(0, 2, 1, 3).reshape(b, s, kvh_l * dh)
                res = fused_paged_decode_attention(
                    qw, kw, vw, ck, cv, paged_state, cache_index,
                    queries_per_group=local_heads // kvh_l,
                    sliding_window=c.sliding_window,
                    k_scales=k_scales, v_scales=v_scales)
                if k_scales is not None:
                    ctx, ck, cv, k_scales, v_scales = res
                    new = ((ck, k_scales), (cv, v_scales))
                else:
                    ctx, ck, cv = res
                    new = (ck, cv)
                # ctx [b, s, hl*dh] -> [s, b, hl*dh] for the dense proj
                out = self.dense.apply(params["dense"],
                                       ctx.transpose(1, 0, 2))
                return out, new
            if ck.ndim == 3:
                # FLAT decode cache [b, S, local_kv_heads*dh]: with the 4D
                # [b, h, S, d] carry XLA picks a layout whose minor dim is
                # head_dim (64) — half a 128-lane tile — so the cache is
                # physically padded 2x and every decode-attention read runs
                # at ~50% HBM bandwidth; the flat form keeps the minor dim
                # at h*d (>= 128) and the whole cache stream full-lane
                # (PERF.md round 5: bs8 decode 10.4k -> 13.8k tok/s)
                out, new_cache = self._flat_cache_attention(
                    params, q, k, v, ck, cv, cache_index, attention_mask,
                    kv_lengths, rng, deterministic)
                return out, new_cache
            if getattr(cache_index, "ndim", 0) == 1:
                raise NotImplementedError(
                    "per-row cache_index (continuous-batching decode) "
                    "needs the FLAT cache form — "
                    "init_kv_caches(stacked=False, flat=True)")
            ck = lax.dynamic_update_slice(
                ck, k.astype(ck.dtype), (0, 0, cache_index, 0))
            cv = lax.dynamic_update_slice(
                cv, v.astype(cv.dtype), (0, 0, cache_index, 0))
            new_cache = (ck, cv)
            if (isinstance(cache_index, int) and cache_index == 0
                    and attention_mask is None and kv_lengths is None
                    and (deterministic or c.attention_dropout == 0.0)):
                # PREFILL fast path (statically at slot 0): queries occupy
                # cache slots [0, s), so attention over the populated
                # prefix is plain causal flash — the empty tail slots
                # never enter the kernel, and the [s, S]-mask einsum path
                # below (built for mid-cache offsets) is skipped entirely
                ctx = flash_attention(
                    q, ck[:, :, :s].astype(q.dtype),
                    cv[:, :, :s].astype(q.dtype), causal=True,
                    sliding_window=c.sliding_window)
                ctx = ctx.transpose(2, 0, 1, 3).reshape(
                    s, b, local_heads * dh)
                return self.dense.apply(params["dense"], ctx), new_cache
            k, v = ck.astype(q.dtype), cv.astype(q.dtype)
            # per-query causal+prefix mask over the padded cache: query i of
            # the slice may see slots j <= cache_index + i (the dispatcher's
            # offset-causal tril assumes queries sit at the cache END, which
            # padded caches violate — so encode causality explicitly)
            slots = jnp.arange(k.shape[2])[None, None, None, :]
            allowed_up_to = cache_index + jnp.arange(s)[None, None, :, None]
            invalid = slots > allowed_up_to
            if c.sliding_window is not None:
                invalid = jnp.logical_or(
                    invalid, slots <= allowed_up_to - c.sliding_window)
            attention_mask = (invalid if attention_mask is None
                              else jnp.logical_or(attention_mask, invalid))
        window = (c.sliding_window
                  if (self.attn_type == AttnType.self_attn
                      and kv_cache is None) else None)
        ctx = self._core_attention(q, k, v, attention_mask, kv_lengths,
                                   rng, deterministic, window=window)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, local_heads * dh)
        out = self.dense.apply(params["dense"], ctx)
        return out if new_cache is None else (out, new_cache)


@dataclass
class ParallelTransformerLayer:
    """Pre-LN block: ln -> attn -> add -> ln -> mlp -> add.

    Reference: ``standalone_transformer_lm.py`` ``ParallelTransformerLayer``
    (~:1033-1148). Under sequence parallelism the norms and dropouts run on
    sequence shards (``transformer/layers/layer_norm.py:26-99`` marks those
    params ``sequence_parallel_enabled`` for grad sync; here that sync is the
    train step's psum of replicated-param grads).
    """

    config: TransformerConfig
    layer_type: Any = LayerType.encoder

    def __post_init__(self):
        c = self.config
        self.attention = ParallelAttention(c)
        if self.layer_type == LayerType.decoder:
            # decoder blocks add cross-attention over the encoder output
            # (reference ParallelTransformerLayer inter_attention branch,
            # standalone_transformer_lm.py ~:1090-1115)
            self.inter_attention = ParallelAttention(
                c, attn_type=AttnType.cross_attn)
        if c.num_moe_experts:
            from apex_tpu.transformer.moe import MoEConfig, SwitchMLP
            self.mlp = SwitchMLP(MoEConfig(
                hidden_size=c.hidden_size,
                ffn_hidden_size=c.ffn_size,
                num_experts=c.num_moe_experts,
                top_k=c.moe_top_k,
                capacity_factor=c.moe_capacity_factor,
                aux_loss_weight=c.moe_aux_loss_weight,
                router_jitter=c.moe_router_jitter,
                expert_axis=c.moe_expert_axis,
                activation=c.activation,
                params_dtype=c.params_dtype,
                compute_dtype=c.compute_dtype,
                init_method_std=c.init_method_std))
        else:
            self.mlp = ParallelMLP(c)

    def init(self, key):
        c = self.config
        k1, k2, k3 = jax.random.split(key, 3)
        p = {
            "input_layernorm": _ln_params(c.hidden_size, c.params_dtype,
                                          c.normalization),
            "self_attention": self.attention.init(k1),
            "post_attention_layernorm": _ln_params(
                c.hidden_size, c.params_dtype, c.normalization),
            "mlp": self.mlp.init(k2),
        }
        if self.layer_type == LayerType.decoder:
            p["inter_attention"] = self.inter_attention.init(k3)
            p["post_inter_attention_layernorm"] = _ln_params(
                c.hidden_size, c.params_dtype, c.normalization)
        return p

    def spec(self):
        norm = self.config.normalization
        s = {
            "input_layernorm": _ln_spec(norm),
            "self_attention": self.attention.spec(),
            "post_attention_layernorm": _ln_spec(norm),
            "mlp": self.mlp.spec(),
        }
        if self.layer_type == LayerType.decoder:
            s["inter_attention"] = self.inter_attention.spec()
            s["post_inter_attention_layernorm"] = _ln_spec(norm)
        return s

    def apply(self, params, hidden, *, encoder_output=None,
              enc_dec_attn_mask=None, enc_kv_lengths=None,
              attention_mask=None, kv_lengths=None, kv_cache=None,
              cache_index=None, rng=None, deterministic=True,
              moe_drop_free=None, attention_seed=None, paged_state=None,
              lora=None):
        """``encoder_output`` (decoder layers) must be the FULL encoder
        sequence ``[s_enc, b, h]`` — under sequence parallelism gather it
        first (``gather_from_sequence_parallel_region``), as
        :class:`~apex_tpu.models.bert.BertModel` does for its heads.
        ``enc_kv_lengths`` ([batch] valid encoder lengths) keeps padded
        cross-attention on the varlen flash path instead of a boolean
        ``enc_dec_attn_mask``. With ``kv_cache`` (incremental decoding) the
        return becomes ``(out, new_cache)``."""
        c = self.config
        decoder = self.layer_type == LayerType.decoder
        # decoder layers draw a 4th key; encoder layers keep the historical
        # 3-way split so fixed-seed dropout streams stay reproducible
        n_keys = 4 if decoder else 3
        rngs = ((None,) * n_keys if rng is None
                else tuple(jax.random.split(rng, n_keys)))
        x = _ln(params["input_layernorm"], hidden, c.layernorm_epsilon,
                c.sequence_parallel, c.axis_name, c.normalization)
        attn_out = self.attention.apply(
            params["self_attention"], x.astype(c.compute_dtype),
            attention_mask=attention_mask, kv_lengths=kv_lengths,
            kv_cache=kv_cache, cache_index=cache_index,
            rng=rngs[2], deterministic=deterministic,
            dropout_seed=attention_seed, paged_state=paged_state,
            lora=None if lora is None else lora.get("query_key_value"))
        new_cache = None
        if kv_cache is not None:
            attn_out, new_cache = attn_out
        attn_out = _dropout(attn_out, c.hidden_dropout, rngs[0], deterministic,
                            model_parallel_region=c.sequence_parallel,
                            axis_name=c.axis_name)
        hidden = hidden + attn_out
        if decoder:
            x = _ln(params["post_attention_layernorm"], hidden,
                    c.layernorm_epsilon, c.sequence_parallel, c.axis_name,
                    c.normalization)
            r_attn = None if rngs[3] is None else jax.random.fold_in(rngs[3], 0)
            r_drop = None if rngs[3] is None else jax.random.fold_in(rngs[3], 1)
            inter_out = self.inter_attention.apply(
                params["inter_attention"], x.astype(c.compute_dtype),
                encoder_output=encoder_output,
                attention_mask=enc_dec_attn_mask,
                kv_lengths=enc_kv_lengths,
                rng=r_attn, deterministic=deterministic)
            inter_out = _dropout(
                inter_out, c.hidden_dropout, r_drop, deterministic,
                model_parallel_region=c.sequence_parallel,
                axis_name=c.axis_name)
            hidden = hidden + inter_out
            norm_name = "post_inter_attention_layernorm"
        else:
            norm_name = "post_attention_layernorm"
        x = _ln(params[norm_name], hidden,
                c.layernorm_epsilon, c.sequence_parallel, c.axis_name,
                c.normalization)
        if c.num_moe_experts:
            moe_rng = (None if rngs[1] is None
                       else jax.random.fold_in(rngs[1], 1))
            # drop-free routing on the whole generation path (prefill AND
            # single-token decode) and wherever the caller asks
            # (moe_drop_free=True = the serving forward): factor-based
            # capacity drops are a TRAINING load-balancing trade, and a
            # capacity prefill would disagree with the drop-free decode
            # steps it seeds (round 5; the round-4 caveat in generate()).
            # Cost model: E/top_k x the routed FLOPs either way; above 512
            # tokens SwitchMLP switches to its dense per-expert scan
            # (O(T*ffn) memory — the cap=T one-hot machinery is quadratic
            # in T), below it the one-shot capacity dispatch.
            if moe_drop_free is None:
                moe_drop_free = kv_cache is not None
            mlp_out, aux = self.mlp.apply(
                params["mlp"], x.astype(c.compute_dtype),
                rng=moe_rng, deterministic=deterministic,
                drop_free=moe_drop_free)
        else:
            mlp_out = self.mlp.apply(
                params["mlp"], x.astype(c.compute_dtype),
                lora=None if lora is None else lora.get("dense_h_to_4h"))
            aux = None
        mlp_out = _dropout(mlp_out, c.hidden_dropout, rngs[1], deterministic,
                           model_parallel_region=c.sequence_parallel,
                           axis_name=c.axis_name)
        out = hidden + mlp_out
        if new_cache is not None:
            # decode is inference: the MoE load-balancing aux loss is a
            # training signal, so it is dropped on the cache path (expert
            # dispatch itself runs normally inside the decode scan)
            return out, new_cache
        return (out, aux) if c.num_moe_experts else out


@dataclass
class ParallelTransformer:
    """Stack of :class:`ParallelTransformerLayer` via ``lax.scan``.

    Reference: ``standalone_transformer_lm.py`` ``ParallelTransformer``
    (~:1151-1380). ``num_layers`` here is the *local* (per-pipeline-stage)
    depth; pipeline schedules stack these per stage.
    """

    config: TransformerConfig
    layer_type: Any = LayerType.encoder

    def __post_init__(self):
        self.layer = ParallelTransformerLayer(self.config, self.layer_type)

    def init(self, key):
        keys = jax.random.split(key, self.config.num_layers)
        stacked = jax.vmap(self.layer.init)(keys)
        return {"layers": stacked,
                "final_layernorm": _ln_params(
                    self.config.hidden_size, self.config.params_dtype,
                    self.config.normalization)}

    def spec(self):
        layer_spec = self.layer.spec()
        stacked = jax.tree.map(
            lambda s: PartitionSpec(None, *s), layer_spec,
            is_leaf=lambda x: isinstance(x, PartitionSpec))
        return {"layers": stacked,
                "final_layernorm": _ln_spec(self.config.normalization)}

    def apply(self, params, hidden, *, encoder_output=None,
              enc_dec_attn_mask=None, enc_kv_lengths=None,
              attention_mask=None, kv_lengths=None, kv_caches=None,
              cache_index=None, rng=None, deterministic=True,
              final_norm=True, moe_drop_free=None, paged_state=None,
              lora=None):
        """Returns ``hidden`` — or ``(hidden, moe_aux_loss)`` (aux summed
        over layers) when the config enables MoE, or ``(hidden, new_caches)``
        when decoding with ``kv_caches`` — either ``(k, v)`` stacked
        ``[L, ...]`` (scan form) or a LIST of per-layer ``(k, v)`` pairs
        (unrolled form; ``init_kv_caches(stacked=False)``). The list form
        is the fast decode path: scanning over a stacked cache pays
        full-cache slice/restack copies every step (measured 2.4x slower
        at bs8 — PERF.md round 4), while per-layer buffers update in
        place."""
        c = self.config
        moe = bool(c.num_moe_experts)

        # attention-dropout seeds: ONE base draw per step, offset per layer
        # by an odd constant (injective mod 2^32) — masks are structurally
        # distinct across layers, where independent per-layer 32-bit draws
        # collide (and then share a mask) at ~L^2/2^33 per step. The base
        # key folds num_layers so it never collides with the per-layer
        # fold_in(rng, idx) stream below; model_parallel_rng_key keeps the
        # per-TP-rank distinctness of the in-attention derivation.
        attn_seed_base = None
        if (rng is not None and not deterministic
                and c.attention_dropout > 0.0):
            skey = model_parallel_rng_key(
                jax.random.fold_in(rng, c.num_layers), c.axis_name)
            attn_seed_base = jax.random.randint(
                skey, (1,), -2 ** 31, 2 ** 31 - 1, jnp.int32)

        def _attn_seed(idx):
            if attn_seed_base is None:
                return None
            golden = jnp.int32(-1640531527)  # 0x9E3779B9, odd
            return attn_seed_base + jnp.int32(idx) * golden

        if lora is not None and not (
                kv_caches is not None and isinstance(kv_caches, list)):
            # per-slot adapters exist for the serving step programs, which
            # all decode over the per-layer LIST cache form; training and
            # merged-reference paths fold adapters into the weights instead
            # (apex_tpu.lora.merge_adapter)
            raise NotImplementedError(
                "lora (per-slot adapter factors) needs the per-layer LIST "
                "kv_caches form — merge adapters into the weights for "
                "cache-free or scan-form forwards")
        if lora is not None and c.sequence_parallel:
            raise NotImplementedError(
                "lora deltas read the layer input pre-gather; sequence "
                "parallelism is not supported on the adapter path")
        if paged_state is not None and not (
                kv_caches is not None and isinstance(kv_caches, list)):
            raise NotImplementedError(
                "paged decode needs the per-layer LIST cache form (each "
                "entry one layer's page pool pair) — the stacked scan "
                "form re-slices the whole pool every layer")
        # a LIST means per-layer (k, v) pairs (the stacked scan form is a
        # 2-TUPLE of [L, ...] arrays — do not widen this check to tuple)
        if kv_caches is not None and isinstance(kv_caches, list):
            # quantized paged entries nest one level deeper: each of
            # k/v is a (pages, scales) pair — validate on the pages
            k0 = kv_caches[0][0] if (
                isinstance(kv_caches[0], (tuple, list))
                and len(kv_caches[0]) == 2) else None
            if (isinstance(k0, (tuple, list)) and len(k0) == 2
                    and paged_state is not None):
                k0 = k0[0]
            if (len(kv_caches) != c.num_layers
                    # entries must be (k, v) PAIRS: a stacked (k, v) pair
                    # that became a [k, v] list in a serialization
                    # round-trip would otherwise run SILENTLY wrong on
                    # 2-layer models — each [2, ...] ARRAY entry unpacks
                    # into two per-layer slices of valid shape, so the
                    # entry type check (not just the lengths) is what
                    # actually catches it
                    or not isinstance(kv_caches[0], (tuple, list))
                    or len(kv_caches[0]) != 2
                    or getattr(k0, "ndim", 0) not in (3, 4)):
                raise ValueError(
                    f"list-form kv_caches must hold num_layers "
                    f"({c.num_layers}) per-layer (k, v) pairs of "
                    f"[batch, heads, S, head_dim] (or flat "
                    f"[batch, S, heads*head_dim]) arrays; got a "
                    f"{len(kv_caches)}-element list — a stacked cache is "
                    f"a (k, v) TUPLE of [L, ...] arrays")
            # unrolled per-layer cache loop (no remat: decode is inference)
            h = hidden
            new_caches = []
            layers_p = params["layers"]
            for idx, layer_cache in enumerate(kv_caches):
                # a list/tuple of per-layer pytrees skips the in-loop slice
                # of the stacked params: inside a decode scan XLA re-slices
                # (and lays out copies of) the stacked weights EVERY step
                # (~115 us/step at GPT-2 124M bs8 — PERF.md round 5);
                # generate() pre-slices once outside the scan
                layer_params = (layers_p[idx]
                                if isinstance(layers_p, (list, tuple))
                                else jax.tree.map(lambda x: x[idx],
                                                  layers_p))
                layer_rng = (None if rng is None
                             else jax.random.fold_in(rng, idx))
                # adapter-bank leaves are [L, b, ...] (gathered per slot
                # by the caller); slice this layer's factors
                layer_lora = (None if lora is None
                              else jax.tree.map(lambda x: x[idx], lora))
                h, new_cache = self.layer.apply(
                    layer_params, h, encoder_output=encoder_output,
                    enc_dec_attn_mask=enc_dec_attn_mask,
                    enc_kv_lengths=enc_kv_lengths,
                    attention_mask=attention_mask,
                    kv_lengths=kv_lengths, kv_cache=layer_cache,
                    cache_index=cache_index, rng=layer_rng,
                    deterministic=deterministic,
                    moe_drop_free=moe_drop_free,
                    attention_seed=_attn_seed(idx),
                    paged_state=paged_state, lora=layer_lora)
                new_caches.append(new_cache)
            if final_norm:
                h = _ln(params["final_layernorm"], h, c.layernorm_epsilon,
                        c.sequence_parallel, c.axis_name, c.normalization)
            return h, new_caches

        def one_layer(carry, xs):
            h, aux_sum, idx = carry
            if kv_caches is not None:
                layer_params, layer_cache = xs
            else:
                layer_params, layer_cache = xs, None
            layer_rng = None if rng is None else jax.random.fold_in(rng, idx)

            def run(h):
                out = self.layer.apply(
                    layer_params, h, encoder_output=encoder_output,
                    enc_dec_attn_mask=enc_dec_attn_mask,
                    enc_kv_lengths=enc_kv_lengths,
                    attention_mask=attention_mask,
                    kv_lengths=kv_lengths, kv_cache=layer_cache,
                    cache_index=cache_index, rng=layer_rng,
                    deterministic=deterministic,
                    moe_drop_free=moe_drop_free,
                    attention_seed=_attn_seed(idx))
                if layer_cache is not None:
                    return out        # (h, new_cache)
                return out if moe else (out, jnp.zeros((), jnp.float32))

            if c.recompute == "selective":
                run = jax.checkpoint(
                    run,
                    policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)
            elif c.recompute:
                run = jax.checkpoint(run)
            h, extra = run(h)
            if layer_cache is not None:
                return (h, aux_sum, idx + 1), extra
            return (h, aux_sum + extra, idx + 1), None

        xs = (params["layers"] if kv_caches is None
              else (params["layers"], kv_caches))
        (hidden, aux_sum, _), new_caches = lax.scan(
            one_layer, (hidden, jnp.zeros((), jnp.float32), 0), xs,
            unroll=min(c.scan_unroll, c.num_layers))
        if final_norm:
            hidden = _ln(params["final_layernorm"], hidden,
                         c.layernorm_epsilon, c.sequence_parallel,
                         c.axis_name, c.normalization)
        if kv_caches is not None:
            return hidden, new_caches
        return (hidden, aux_sum) if moe else hidden
