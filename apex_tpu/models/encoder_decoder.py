"""Encoder-decoder LM (T5-style) on the parallel transformer toolkit.

Capability counterpart of the reference's ``ModelType.encoder_and_decoder``
path through the Megatron testing LM (``standalone_transformer_lm.py``
builds encoder+decoder ``ParallelTransformer`` stacks with cross-attention
decoder layers; exercised by the pipeline-parallel tests with
encoder_and_decoder model type). Here: a bidirectional encoder stack, a
causal decoder stack whose layers cross-attend the gathered encoder output,
tied input embeddings, and the vocab-parallel LM loss tail.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from apex_tpu.models.gpt import lm_head_loss
from apex_tpu.models.transformer import (
    ParallelTransformer,
    TransformerConfig,
    embed_tokens,
    position_table_params,
    position_table_spec,
)
from apex_tpu.transformer.enums import AttnMaskType, LayerType
from apex_tpu.transformer.tensor_parallel.layers import VocabParallelEmbedding
from apex_tpu.transformer.tensor_parallel.mappings import (
    gather_from_sequence_parallel_region,
)

__all__ = ["EncoderDecoderModel"]


@dataclass
class EncoderDecoderModel:
    """``apply(params, enc_tokens, dec_tokens, labels=None)``.

    ``config`` describes the decoder; the encoder uses the same sizes with
    bidirectional (padding) attention and ``num_encoder_layers`` depth
    (default: ``config.num_layers``).
    """

    config: TransformerConfig
    num_encoder_layers: Optional[int] = None

    def __post_init__(self):
        c = self.config
        if c.num_moe_experts:
            raise NotImplementedError(
                "MoE (num_moe_experts) is currently wired into GPTModel only")
        if c.context_parallel_method:
            raise NotImplementedError(
                "context parallelism is decoder-self-attention only; the "
                "cross-attended encoder output is not sequence-sharded")
        n_enc = (c.num_layers if self.num_encoder_layers is None
                 else self.num_encoder_layers)
        if n_enc < 1:
            raise ValueError(f"num_encoder_layers must be >= 1, got {n_enc}")
        self._enc_cfg = replace(
            c, attn_mask_type=AttnMaskType.padding, num_layers=n_enc)
        self._dec_cfg = replace(c, attn_mask_type=AttnMaskType.causal)
        self.embedding = VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, init_method=c.init_method(),
            params_dtype=c.params_dtype, axis_name=c.axis_name)
        self.encoder = ParallelTransformer(self._enc_cfg)
        self.decoder = ParallelTransformer(self._dec_cfg, LayerType.decoder)

    def init(self, key: jax.Array) -> Dict[str, Any]:
        c = self.config
        k_emb, k_pos, k_enc, k_dec = jax.random.split(key, 4)
        return {
            "embedding": {
                "word_embeddings": self.embedding.init(k_emb),
                **position_table_params(c, k_pos),
            },
            "encoder": self.encoder.init(k_enc),
            "decoder": self.decoder.init(k_dec),
        }

    def spec(self) -> Dict[str, Any]:
        return {
            "embedding": {
                "word_embeddings": self.embedding.spec(),
                **position_table_spec(self.config),
            },
            "encoder": self.encoder.spec(),
            "decoder": self.decoder.spec(),
        }

    def apply(self, params, enc_tokens, dec_tokens, labels=None, *,
              enc_padding_mask=None, enc_lengths=None, loss_mask=None,
              rng=None, deterministic: bool = True):
        """enc/dec tokens, labels: ``[batch, seq]``.

        Right-padded batches should pass ``enc_lengths`` ([batch] valid
        lengths) — that keeps both encoder self-attention and decoder
        cross-attention on the Pallas varlen flash path. The general boolean
        ``enc_padding_mask`` ([batch, s_enc], True = pad) takes the fused
        masked-softmax fallback. Returns the scalar LM loss with ``labels``,
        else vocab-parallel decoder logits."""
        c = self.config
        if enc_padding_mask is not None and enc_lengths is not None:
            raise ValueError("pass enc_padding_mask or enc_lengths, not both")
        rngs = ((None,) * 4 if rng is None
                else tuple(jax.random.split(rng, 4)))
        enc_hidden = embed_tokens(
            self.embedding, params["embedding"], enc_tokens, self._enc_cfg,
            rng=rngs[0], deterministic=deterministic)
        enc_mask = (None if enc_padding_mask is None
                    else enc_padding_mask[:, None, None, :])
        enc_out = self.encoder.apply(
            params["encoder"], enc_hidden, attention_mask=enc_mask,
            kv_lengths=enc_lengths, rng=rngs[1],
            deterministic=deterministic)
        if c.sequence_parallel:
            # decoder cross-attention wants the full encoder sequence
            enc_out = gather_from_sequence_parallel_region(
                enc_out, False, c.axis_name)
        dec_hidden = embed_tokens(
            self.embedding, params["embedding"], dec_tokens, self._dec_cfg,
            rng=rngs[2], deterministic=deterministic)
        dec_out = self.decoder.apply(
            params["decoder"], dec_hidden, encoder_output=enc_out,
            enc_dec_attn_mask=enc_mask, enc_kv_lengths=enc_lengths,
            rng=rngs[3], deterministic=deterministic)
        return lm_head_loss(
            params["embedding"]["word_embeddings"]["weight"], dec_out,
            labels, loss_mask, c)
