from apex_tpu.utils.logging import get_logger, RankInfoFormatter
from apex_tpu.utils.deprecation import deprecated_warning
from apex_tpu.utils.flops import (
    peak_flops_per_chip,
    resnet50_train_flops,
    transformer_train_flops,
)
from apex_tpu.utils.profiling import (
    annotate_fn,
    device_memory_stats,
    nvtx_range,
    profiler_start,
    profiler_stop,
    trace,
)
from apex_tpu.utils.tree import (
    tree_cast,
    tree_size,
    tree_zeros_like,
    global_norm,
)

__all__ = [
    "get_logger",
    "RankInfoFormatter",
    "deprecated_warning",
    "tree_cast",
    "tree_size",
    "tree_zeros_like",
    "global_norm",
    "nvtx_range",
    "annotate_fn",
    "profiler_start",
    "profiler_stop",
    "trace",
    "device_memory_stats",
    "peak_flops_per_chip",
    "resnet50_train_flops",
    "transformer_train_flops",
]
