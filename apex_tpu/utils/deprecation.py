"""Deprecation machinery (capability of ``apex/__init__.py:46-67``)."""

from __future__ import annotations

import warnings


class DeprecatedFeatureWarning(FutureWarning):
    pass


_seen: set = set()


def deprecated_warning(msg: str) -> None:
    """Warn once per unique message, on process 0 only."""
    import jax

    if msg in _seen:
        return
    _seen.add(msg)
    if jax.process_index() == 0:
        warnings.warn(msg, DeprecatedFeatureWarning, stacklevel=2)
