"""Library logging with parallel-rank context.

TPU-native counterpart of the reference's ``RankInfoFormatter``
(``apex/__init__.py:31-44``), which prefixes every record with the
``(dp, tp, pp, vpp)`` rank tuple from ``parallel_state.get_rank_info``
(``apex/transformer/parallel_state.py:421-430``). Here ranks come from the
process index and the active mesh registry instead of torch.distributed.
"""

from __future__ import annotations

import itertools
import logging
import os
import time


def _rank_info() -> str:
    """Return a compact rank string: process index plus mesh axis coordinates."""
    parts = [f"proc={os.environ.get('JAX_PROCESS_INDEX', '0')}"]
    try:
        from apex_tpu.transformer import parallel_state

        if parallel_state.model_parallel_is_initialized():
            parts.append(parallel_state.get_rank_info())
    except Exception:
        pass
    return " ".join(parts)


class RankInfoFormatter(logging.Formatter):
    """Formatter injecting ``%(rank_info)s`` into every record."""

    def format(self, record: logging.LogRecord) -> str:
        record.rank_info = _rank_info()
        return super().format(record)


_LOGGER_NAME = "apex_tpu"

#: process-wide event ordering.  ``next()`` on a count is atomic under
#: the GIL, so concurrent emitters (watchdog thread + step loop) get
#: strictly increasing, gap-free sequence numbers.
_EVENT_SEQ = itertools.count()


def log_event(logger: logging.Logger, event: str, *, level: str = "warning",
              **fields) -> str:
    """Structured failure/recovery telemetry: one ``logfmt``-style line
    (``event=<name> seq=<n> ts=<monotonic> wall=<epoch> key=value ...``)
    per incident, machine-greppable by event name. The resilience layer
    routes every skip/rollback/retry/preemption/retrace incident through
    here (the counters in ``TrainingResult.telemetry`` aggregate the same
    incidents), the way the reference's RankInfoFormatter gives every
    record a parseable rank prefix. ``seq`` is a process-wide strictly
    increasing counter and ``ts`` a monotonic-clock stamp, so events can
    be totally ordered and rate-measured (retraces/min, skips/min) even
    when the logging backend reorders or batches lines; ``wall`` is epoch
    seconds (``time.time()``), the only stamp comparable *across*
    processes/hosts — use it to correlate events from different workers,
    and ``ts`` (immune to clock steps) for intervals and rates. Returns
    the formatted line (callers embed it in exceptions).
    """
    parts = [f"event={event}", f"seq={next(_EVENT_SEQ)}",
             f"ts={time.monotonic():.6f}", f"wall={time.time():.6f}"]
    for k in sorted(fields):
        v = fields[k]
        v = f"{v:.6g}" if isinstance(v, float) else str(v)
        if any(c.isspace() for c in v):
            v = '"' + v.replace('"', "'") + '"'
        parts.append(f"{k}={v}")
    line = " ".join(parts)
    logger.log(getattr(logging, level.upper(), logging.WARNING), "%s", line)
    return line


def get_logger(name: str = _LOGGER_NAME) -> logging.Logger:
    logger = logging.getLogger(name)
    if not getattr(logger, "_apex_tpu_configured", False):
        handler = logging.StreamHandler()
        handler.setFormatter(
            RankInfoFormatter(
                "%(asctime)s [%(levelname)s] [%(rank_info)s] %(name)s: %(message)s"
            )
        )
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("APEX_TPU_LOG_LEVEL", "WARNING"))
        logger.propagate = False
        logger._apex_tpu_configured = True  # type: ignore[attr-defined]
    return logger
