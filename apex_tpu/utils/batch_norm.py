"""Shared functional batch-norm primitives for the vision models.

Used by :mod:`apex_tpu.models.resnet` and :mod:`apex_tpu.models.dcgan`.
Statistics are always fp32 regardless of activation dtype (the reference's
``keep_batchnorm_fp32`` amp rule, ``fp16_utils/fp16util.py:60``), and the
training-mode reduction optionally ``psum``s over a named mesh axis — the
SyncBN merge of ``apex/parallel/optimized_sync_batchnorm_kernel.py:7-120``.

The moments are one fused pass of **shifted** sums ``(sum(x - c),
sum((x - c)^2))`` with ``c`` the running mean: one reduction (one ``psum``
under SyncBN) like the naive ``E[x^2] - E[x]^2`` form, but centered so it
does not catastrophically cancel for channels whose mean is large relative
to their std — the numerical property the reference's Welford kernels
(``csrc/welford.cu``) exist to provide, recovered here without the
sequential update Welford needs.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["bn_init", "bn_apply", "bn_sums", "bn_from_sums"]


def bn_init(c: int):
    """Returns ``(params, state)`` for a ``c``-channel batch norm."""
    return ({"scale": jnp.ones((c,), jnp.float32),
             "bias": jnp.zeros((c,), jnp.float32)},
            {"mean": jnp.zeros((c,), jnp.float32),
             "var": jnp.ones((c,), jnp.float32)})


def bn_sums(x, shift, sample_mask=None):
    """Per-channel fp32 ``[2, C]`` shifted sums of NHWC ``x`` over (N, H, W):
    row 0 = ``sum(x - shift)``, row 1 = ``sum((x - shift)^2)``. The cast and
    subtract fuse into the reduction read — one pass over ``x``.
    ``sample_mask`` (``[N]`` bool) excludes padded batch rows from both sums
    (pair with the matching ``n`` — see :func:`bn_apply`)."""
    xc = x.astype(jnp.float32) - lax.stop_gradient(
        shift.astype(jnp.float32))
    if sample_mask is not None:
        # where, not multiply: 0 * NaN/Inf in a padded row would poison
        # both sums
        xc = jnp.where((sample_mask != 0)[:, None, None, None], xc, 0.0)
    return jnp.stack([jnp.sum(xc, axis=(0, 1, 2)),
                      jnp.sum(jnp.square(xc), axis=(0, 1, 2))])


def bn_from_sums(p, s, sums, n, *, shift, momentum: float, eps: float,
                 axis_name: Optional[str]):
    """Close a batch norm from shifted sums: ``shift`` must be the same
    per-channel shift the sums were built with (see :func:`bn_sums` /
    ``conv1x1_bn_act(stats_shift=...)``). Returns ``(a, b, new_state)``
    where the normalize is the per-channel affine ``y = x * a + b``. With
    ``axis_name`` bound the sums are ``psum``-merged first (SyncBN)."""
    n = jnp.asarray(n, jnp.float32)
    if axis_name is not None:
        sums = lax.psum(sums, axis_name)
        n = lax.psum(n, axis_name)
    shift = lax.stop_gradient(shift)
    # guard the 0/0 of an all-padded (global) batch: stats degrade to the
    # shift/zeros instead of NaN-poisoning the running state
    n_safe = jnp.maximum(n, 1.0)
    d = sums[0] / n_safe
    mean = shift + d
    var = jnp.maximum(sums[1] / n_safe - jnp.square(d), 0.0)
    new_s = {
        "mean": (1 - momentum) * s["mean"] + momentum * mean,
        # running var uses the unbiased estimate, torch BN semantics
        "var": (1 - momentum) * s["var"]
               + momentum * var * n / jnp.maximum(n - 1, 1.0),
    }
    inv = lax.rsqrt(var + eps)
    a = inv * p["scale"]
    b = p["bias"] - mean * a
    return a, b, new_s


def bn_apply(p, s, x, *, train: bool, momentum: float, eps: float,
             axis_name: Optional[str], sample_mask=None):
    """NHWC batch norm; returns ``(y, new_state)``. With ``axis_name`` bound
    the batch statistics are synchronized across that mesh axis.

    ``sample_mask`` (``[N]`` bool) marks real batch rows: padded rows drop
    out of the statistics and the count, making the cross-rank merge
    count-weighted — the SPMD form of the reference's unequal per-rank
    batches (``csrc/welford.cu`` ``welford_parallel``;
    ``tests/distributed/synced_batchnorm/two_gpu_test_different_batch_size
    .py``). Masked rows still get normalized outputs; mask them downstream.

    Performance shape (v5e, RN50-sized activations): statistics are ONE
    fused fp32 pass (shifted sum + sum-of-squares reduced together, one
    ``psum`` for both under SyncBN), and the normalize itself is a
    per-channel affine ``x * a + b`` applied in the activation dtype — the
    big elementwise op stays bf16 and fuses into the surrounding conv, only
    the tiny [C] vectors are fp32. This is the same split the reference's
    Welford CUDA kernels make (fp32 stats, fp16 apply; ``csrc/welford.cu``).
    """
    if train:
        if sample_mask is None:
            n = x.shape[0] * x.shape[1] * x.shape[2]
        else:
            n = (jnp.sum(sample_mask.astype(jnp.float32))
                 * x.shape[1] * x.shape[2])
        a, b, new_s = bn_from_sums(p, s, bn_sums(x, s["mean"], sample_mask),
                                   n, shift=s["mean"], momentum=momentum,
                                   eps=eps, axis_name=axis_name)
    else:
        mean, var, new_s = s["mean"], s["var"], s
        inv = lax.rsqrt(var + eps)
        a = inv * p["scale"]
        b = p["bias"] - mean * a
    return x * a.astype(x.dtype) + b.astype(x.dtype), new_s
