"""Shared functional batch-norm primitives for the vision models.

Used by :mod:`apex_tpu.models.resnet` and :mod:`apex_tpu.models.dcgan`.
Statistics are always fp32 regardless of activation dtype (the reference's
``keep_batchnorm_fp32`` amp rule, ``fp16_utils/fp16util.py:60``), and the
training-mode reduction optionally ``psum``s over a named mesh axis — the
SyncBN merge of ``apex/parallel/optimized_sync_batchnorm_kernel.py:7-120``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

__all__ = ["bn_init", "bn_apply"]


def bn_init(c: int):
    """Returns ``(params, state)`` for a ``c``-channel batch norm."""
    return ({"scale": jnp.ones((c,), jnp.float32),
             "bias": jnp.zeros((c,), jnp.float32)},
            {"mean": jnp.zeros((c,), jnp.float32),
             "var": jnp.ones((c,), jnp.float32)})


def bn_apply(p, s, x, *, train: bool, momentum: float, eps: float,
             axis_name: Optional[str]):
    """NHWC batch norm; returns ``(y, new_state)``. With ``axis_name`` bound
    the batch statistics are synchronized across that mesh axis.

    Performance shape (v5e, RN50-sized activations): statistics are ONE
    fused fp32 pass (sum + sum-of-squares reduced together, one ``psum``
    for both under SyncBN) instead of the textbook two-pass
    ``E[(x-mean)^2]``, and the normalize itself is a per-channel affine
    ``x * a + b`` applied in the activation dtype — the big elementwise op
    stays bf16 and fuses into the surrounding conv, only the tiny [C]
    vectors are fp32. This is the same split the reference's Welford CUDA
    kernels make (fp32 stats, fp16 apply; ``csrc/welford.cu``).
    """
    if train:
        x32 = x.astype(jnp.float32)      # fused into the reduction by XLA
        n = jnp.asarray(x.shape[0] * x.shape[1] * x.shape[2], jnp.float32)
        stats = jnp.stack([jnp.sum(x32, axis=(0, 1, 2)),
                           jnp.sum(jnp.square(x32), axis=(0, 1, 2))])
        if axis_name is not None:
            stats = lax.psum(stats, axis_name)
            n = lax.psum(n, axis_name)
        mean = stats[0] / n
        var = jnp.maximum(stats[1] / n - jnp.square(mean), 0.0)
        new_s = {
            "mean": (1 - momentum) * s["mean"] + momentum * mean,
            # running var uses the unbiased estimate, torch BN semantics
            "var": (1 - momentum) * s["var"]
                   + momentum * var * n / jnp.maximum(n - 1, 1.0),
        }
    else:
        mean, var, new_s = s["mean"], s["var"], s
    inv = lax.rsqrt(var + eps)
    a = (inv * p["scale"]).astype(x.dtype)
    b = (p["bias"] - mean * inv * p["scale"]).astype(x.dtype)
    return x * a + b, new_s
