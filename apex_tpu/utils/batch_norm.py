"""Shared functional batch-norm primitives for the vision models.

Used by :mod:`apex_tpu.models.resnet` and :mod:`apex_tpu.models.dcgan`.
Statistics are always fp32 regardless of activation dtype (the reference's
``keep_batchnorm_fp32`` amp rule, ``fp16_utils/fp16util.py:60``), and the
training-mode reduction optionally ``psum``s over a named mesh axis — the
SyncBN merge of ``apex/parallel/optimized_sync_batchnorm_kernel.py:7-120``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

__all__ = ["bn_init", "bn_apply"]


def bn_init(c: int):
    """Returns ``(params, state)`` for a ``c``-channel batch norm."""
    return ({"scale": jnp.ones((c,), jnp.float32),
             "bias": jnp.zeros((c,), jnp.float32)},
            {"mean": jnp.zeros((c,), jnp.float32),
             "var": jnp.ones((c,), jnp.float32)})


def bn_apply(p, s, x, *, train: bool, momentum: float, eps: float,
             axis_name: Optional[str]):
    """NHWC batch norm; returns ``(y, new_state)``. With ``axis_name`` bound
    the batch statistics are synchronized across that mesh axis."""
    x32 = x.astype(jnp.float32)
    if train:
        n = jnp.asarray(x32.shape[0] * x32.shape[1] * x32.shape[2],
                        jnp.float32)
        total = jnp.sum(x32, axis=(0, 1, 2))
        if axis_name is not None:
            total = lax.psum(total, axis_name)
            n = lax.psum(n, axis_name)
        mean = total / n
        sq = jnp.sum(jnp.square(x32 - mean), axis=(0, 1, 2))
        if axis_name is not None:
            sq = lax.psum(sq, axis_name)
        var = sq / n
        new_s = {
            "mean": (1 - momentum) * s["mean"] + momentum * mean,
            # running var uses the unbiased estimate, torch BN semantics
            "var": (1 - momentum) * s["var"]
                   + momentum * var * n / jnp.maximum(n - 1, 1.0),
        }
    else:
        mean, var, new_s = s["mean"], s["var"], s
    inv = lax.rsqrt(var + eps)
    y = (x32 - mean) * (inv * p["scale"]) + p["bias"]
    return y.astype(x.dtype), new_s
