"""Small shared PartitionSpec / mesh-axis helpers.

One home for the two questions several modules kept re-answering locally:
which mesh axes does a PartitionSpec leaf bind, and which of a set of axis
names are bound in the current trace (inside ``shard_map``).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
from jax import lax

__all__ = ["spec_axis_names", "bound_axes", "broadcast_spec"]


def spec_axis_names(spec) -> set:
    """Mesh axis names a PartitionSpec binds across all its dims (empty for
    ``None``/replicated)."""
    used = set()
    if spec is None:
        return used
    for entry in tuple(spec):
        for ax in (entry if isinstance(entry, (tuple, list)) else (entry,)):
            if ax is not None:
                used.add(ax)
    return used


def bound_axes(axis_names: Sequence[str]) -> Tuple[str, ...]:
    """The subset of ``axis_names`` bound as collective axes in this trace."""
    out = []
    for a in axis_names:
        try:
            lax.axis_index(a)
            out.append(a)
        except NameError:
            pass
    return tuple(out)


def broadcast_spec(spec_prefix_tree, full_tree) -> list:
    """Expand a (possibly prefix) PartitionSpec pytree to one spec per leaf
    of ``full_tree`` — the same prefix semantics ``shard_map``'s in_specs
    accept, so spec trees valid there stay valid for per-leaf walks."""
    result: list = []
    num_leaves = lambda t: jax.tree_util.tree_structure(t).num_leaves

    def add(spec_leaf, subtree):
        result.extend([spec_leaf] * num_leaves(subtree))

    jax.tree_util.tree_map(add, spec_prefix_tree, full_tree,
                           is_leaf=lambda t: t is None)
    return result
