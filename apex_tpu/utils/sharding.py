"""Small shared PartitionSpec / mesh-axis helpers.

One home for the two questions several modules kept re-answering locally:
which mesh axes does a PartitionSpec leaf bind, and which of a set of axis
names are bound in the current trace (inside ``shard_map``).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
from jax import lax

__all__ = ["spec_axis_names", "bound_axes", "broadcast_spec", "shard_map",
           "axis_size"]


def axis_size(axis_name) -> int:
    """``lax.axis_size`` across jax versions: 0.4.x lacks it, but a psum of
    the literal ``1`` over a bound axis is evaluated statically at trace
    time, so this returns a plain Python int either way (callers build
    static grid/schedule structure from it)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def shard_map(f=None, *, mesh, in_specs, out_specs,
              check_vma: bool = False):
    """``jax.shard_map`` across jax versions: newer jax exposes it at the
    top level with the replication check spelled ``check_vma``; 0.4.x only
    has ``jax.experimental.shard_map`` with ``check_rep``. Every shard_map
    in the package routes through here so a jax upgrade is one-file.
    Without ``f`` returns a decorator (the new-API partial form)."""
    if f is None:
        return lambda g: shard_map(g, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, check_vma=check_vma)
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


def spec_axis_names(spec) -> set:
    """Mesh axis names a PartitionSpec binds across all its dims (empty for
    ``None``/replicated)."""
    used = set()
    if spec is None:
        return used
    for entry in tuple(spec):
        for ax in (entry if isinstance(entry, (tuple, list)) else (entry,)):
            if ax is not None:
                used.add(ax)
    return used


def bound_axes(axis_names: Sequence[str]) -> Tuple[str, ...]:
    """The subset of ``axis_names`` bound as collective axes in this trace."""
    out = []
    for a in axis_names:
        try:
            lax.axis_index(a)
            out.append(a)
        except NameError:
            pass
    return tuple(out)


def broadcast_spec(spec_prefix_tree, full_tree) -> list:
    """Expand a (possibly prefix) PartitionSpec pytree to one spec per leaf
    of ``full_tree`` — the same prefix semantics ``shard_map``'s in_specs
    accept, so spec trees valid there stay valid for per-leaf walks."""
    result: list = []
    num_leaves = lambda t: jax.tree_util.tree_structure(t).num_leaves

    def add(spec_leaf, subtree):
        result.extend([spec_leaf] * num_leaves(subtree))

    jax.tree_util.tree_map(add, spec_prefix_tree, full_tree,
                           is_leaf=lambda t: t is None)
    return result
