"""MLP activation helpers shared by the dense and MoE FFN paths.

One home for the activation whitelist and the gated unit-interleaved layout
convention (output column ``2i`` = gate_i, ``2i+1`` = up_i) so
``ParallelMLP`` and ``SwitchMLP`` cannot drift apart.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

ACTIVATIONS = ("gelu", "relu", "swiglu", "geglu")
GATED = ("swiglu", "geglu")

__all__ = ["ACTIVATIONS", "GATED", "is_gated", "validate_activation",
           "apply_activation"]


def is_gated(activation: str) -> bool:
    return activation in GATED


def validate_activation(activation: str) -> None:
    if activation not in ACTIVATIONS:
        raise ValueError(
            f"activation must be one of {ACTIVATIONS}, got {activation!r}")


def apply_activation(x: jax.Array, activation: str) -> jax.Array:
    """Apply ``activation`` to an FFN pre-activation.

    Gated variants expect the unit-interleaved ``2*ffn`` layout
    (``x[..., 2i]`` = gate_i, ``x[..., 2i+1]`` = up_i; any TP slice of even
    width holds matched pairs) and halve the last dim:
    ``act(gate) * up``. Gated projections are bias-free by convention
    (LLaMA-style) — callers construct their linears accordingly.
    """
    if is_gated(activation):
        x = x.reshape(*x.shape[:-1], x.shape[-1] // 2, 2)
        gate, up = x[..., 0], x[..., 1]
        act = (jax.nn.silu if activation == "swiglu"
               else lambda t: jax.nn.gelu(t, approximate=True))
        return act(gate) * up
    if activation == "relu":
        return jax.nn.relu(x)
    return jax.nn.gelu(x, approximate=True)
