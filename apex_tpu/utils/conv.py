"""Shared NHWC convolution helpers for the vision stack.

NHWC + HWIO is the TPU-native layout (what the reference's channels-last /
NHWC contrib kernels emulate on GPU); every conv in the framework routes
through here so layout and initializer conventions stay in one place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["conv_nhwc", "he_init"]


def conv_nhwc(x, w, stride: int = 1, padding="SAME"):
    """``x``: [N, H, W, Cin]; ``w``: [kh, kw, Cin, Cout]."""
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def he_init(key, shape, dtype=jnp.float32):
    """Kaiming-normal for HWIO conv weights (fan_in = kh*kw*Cin)."""
    fan_in = shape[0] * shape[1] * shape[2]
    return jax.random.normal(key, shape, dtype) * (2.0 / fan_in) ** 0.5
