"""Pytree utilities shared across the framework."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def tree_cast(tree: Any, dtype) -> Any:
    """Cast every floating-point leaf of ``tree`` to ``dtype``."""
    if dtype is None:
        return tree

    def _cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(_cast, tree)


def tree_size(tree: Any) -> int:
    """Total number of elements across all leaves."""
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def tree_zeros_like(tree: Any, dtype=None) -> Any:
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape, dtype or x.dtype), tree
    )


def global_norm(tree: Any) -> jax.Array:
    """L2 norm over all leaves, accumulated in fp32.

    Capability parity with ``amp_C.multi_tensor_l2norm``
    (``csrc/multi_tensor_l2norm_kernel.cu``): one fused reduction over the
    whole parameter set (XLA fuses the per-leaf partial sums).
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    return jnp.sqrt(sq)
