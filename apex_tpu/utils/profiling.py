"""Tracing / profiling helpers.

Counterpart of the reference's NVTX plumbing (SURVEY.md §5: DDP's ``prof``
flag wraps hooks/comm in ``torch.cuda.nvtx`` ranges,
``apex/parallel/distributed.py:361-364``; the imagenet example calls
``cudaProfilerStart`` at a chosen iteration). TPU-native equivalents:

- :func:`nvtx_range` — ``jax.named_scope`` context manager (the name lands
  in XLA HLO metadata and shows up in the profiler timeline exactly like an
  NVTX range does in Nsight); pass a
  :class:`~apex_tpu.observability.registry.MetricsRegistry` and the scope
  also records its host-side wall duration into the ``span/<name>_s``
  histogram — one annotation, visible both in the trace and in the run's
  own metrics;
- :func:`profiler_start` / :func:`profiler_stop` — ``jax.profiler`` trace
  capture to a TensorBoard-readable directory;
- :func:`annotate_fn` — decorator form of :func:`nvtx_range`;
- :func:`device_memory_stats` — per-device live-bytes summary (role of
  ``report_memory``, ``pipeline_parallel/utils.py:253-263``, which also
  re-exports this).
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Callable, Dict, Optional

import jax

__all__ = ["nvtx_range", "annotate_fn", "profiler_start", "profiler_stop",
           "trace", "device_memory_stats"]


@contextlib.contextmanager
def _timed_scope(name: str, registry):
    t0 = time.perf_counter()
    try:
        with jax.named_scope(name):
            yield
    finally:
        # host-side wall duration: dispatch time, not device time — in a
        # saturated pipeline they converge; either way it is free (no sync)
        registry.observe(f"span/{name}_s", time.perf_counter() - t0)


def nvtx_range(name: str, registry=None):
    """``with nvtx_range("fwd"):`` — names the enclosed computation in the
    profiler timeline (``jax.named_scope``). With ``registry`` (a
    ``MetricsRegistry``), the scope's host-side wall duration is also
    observed into the ``span/<name>_s`` histogram."""
    if registry is None:
        return jax.named_scope(name)
    return _timed_scope(name, registry)


def annotate_fn(name: Optional[str] = None, registry=None) -> Callable:
    """Decorator: run the function under a named scope (optionally timed
    into ``registry``, as :func:`nvtx_range`)."""

    def deco(fn: Callable) -> Callable:
        scope = name or fn.__name__

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with nvtx_range(scope, registry=registry):
                return fn(*a, **kw)

        return wrapped

    return deco


def profiler_start(log_dir: str) -> None:
    """Begin a profiler trace (role of ``cudaProfilerStart`` at iteration N,
    reference ``examples/imagenet/main_amp.py:335-339``)."""
    jax.profiler.start_trace(log_dir)


def profiler_stop() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: str):
    """Context-manager form: profile exactly the enclosed iterations."""
    profiler_start(log_dir)
    try:
        yield
    finally:
        profiler_stop()


def device_memory_stats(device=None) -> Dict[str, Any]:
    """Live/peak byte counts for one device (empty dict when the backend
    doesn't expose stats, e.g. CPU)."""
    dev = device or jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    return dict(stats) if stats else {}
