"""Tracing / profiling helpers.

Counterpart of the reference's NVTX plumbing (SURVEY.md §5: DDP's ``prof``
flag wraps hooks/comm in ``torch.cuda.nvtx`` ranges,
``apex/parallel/distributed.py:361-364``; the imagenet example calls
``cudaProfilerStart`` at a chosen iteration). TPU-native equivalents:

- :func:`nvtx_range` — ``jax.named_scope`` context manager (the name lands
  in XLA HLO metadata and shows up in the profiler timeline exactly like an
  NVTX range does in Nsight);
- :func:`profiler_start` / :func:`profiler_stop` — ``jax.profiler`` trace
  capture to a TensorBoard-readable directory;
- :func:`annotate_fn` — decorator form of :func:`nvtx_range`;
- :func:`device_memory_stats` — per-device live-bytes summary (role of
  ``report_memory``, ``pipeline_parallel/utils.py:253-263``, which also
  re-exports this).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Dict, Optional

import jax

__all__ = ["nvtx_range", "annotate_fn", "profiler_start", "profiler_stop",
           "trace", "device_memory_stats"]


def nvtx_range(name: str):
    """``with nvtx_range("fwd"):`` — names the enclosed computation in the
    profiler timeline (``jax.named_scope``)."""
    return jax.named_scope(name)


def annotate_fn(name: Optional[str] = None) -> Callable:
    """Decorator: run the function under a named scope."""

    def deco(fn: Callable) -> Callable:
        scope = name or fn.__name__

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with jax.named_scope(scope):
                return fn(*a, **kw)

        return wrapped

    return deco


def profiler_start(log_dir: str) -> None:
    """Begin a profiler trace (role of ``cudaProfilerStart`` at iteration N,
    reference ``examples/imagenet/main_amp.py:335-339``)."""
    jax.profiler.start_trace(log_dir)


def profiler_stop() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: str):
    """Context-manager form: profile exactly the enclosed iterations."""
    profiler_start(log_dir)
    try:
        yield
    finally:
        profiler_stop()


def device_memory_stats(device=None) -> Dict[str, Any]:
    """Live/peak byte counts for one device (empty dict when the backend
    doesn't expose stats, e.g. CPU)."""
    dev = device or jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    return dict(stats) if stats else {}
