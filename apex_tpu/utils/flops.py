"""FLOP accounting: chip peak FLOP/s table + model-FLOP estimators.

One source of truth for MFU math, shared by the library's observability
layer (:mod:`apex_tpu.observability` — per-step MFU against the chip's
bf16 peak) and the benchmark harness (``benchmarks/_harness.py``), which
previously each would have had to carry their own copy of the peak table.
MFU here is *model*-FLOPs utilization (PaLM-style: the FLOPs the math
requires, not the FLOPs the compiler executes), so numbers are comparable
across implementations.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["peak_flops_per_chip", "transformer_train_flops",
           "resnet50_train_flops"]

# bf16 peak TFLOP/s per chip by device kind (public Cloud TPU specs); MFU is
# model-FLOPs utilization against this number
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops_per_chip(device=None) -> Optional[float]:
    """bf16 peak FLOP/s of ``device`` (default: the first visible device),
    or None when the device kind is not in the table (CPU, unknown TPU)."""
    import jax

    kind = (device or jax.devices()[0]).device_kind
    for name, peak in _PEAK_FLOPS.items():
        if kind.startswith(name):
            return peak
    return None


def transformer_train_flops(n_params: int, tokens: int, num_layers: int,
                            hidden: int, seq: int, causal: bool) -> float:
    """Model FLOPs for one training step over ``tokens`` tokens: the
    standard ``6N`` matmul term plus the attention score/value term
    ``12 * L * s * d`` per token (halved for causal masking)."""
    attn = 12 * num_layers * seq * hidden * (0.5 if causal else 1.0)
    return float(tokens) * (6.0 * n_params + attn)


def resnet50_train_flops(images: int, image_size: int) -> float:
    """Model FLOPs for one RN50 training step: 4.09 GFLOP forward per
    224px image (torchvision profile), scaled by area, x3 for fwd+bwd."""
    return images * 3.0 * 4.09e9 * (image_size / 224.0) ** 2
