"""Batched multi-job LoRA fine-tuning against one frozen base model.

LoRAFusion's training shape (PAPERS.md 2510.00206): several adapters
fine-tune against ONE base model in one step loop — the base forward is
shared work, only the rank-r factors train. Here ``n_jobs`` adapters are
stacked on a leading axis and the whole cohort steps as one jitted
program: the per-job loss is ``vmap`` of the base model applied to
``merge_adapter(stop_gradient(base), factors_j)``, so gradients flow
ONLY into the factors, and the update routes the factor leaves through
the existing fused-optimizer machinery — flattened into one contiguous
buffer per dtype (``multi_tensor_apply.flatten_by_dtype``, the
flat-bucket path the Pallas fused optimizers dispatch on) and stepped by
:class:`~apex_tpu.optimizers.FusedAdam`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from apex_tpu.lora.adapter import init_adapter, merge_adapter
from apex_tpu.multi_tensor_apply import flatten_by_dtype, unflatten_by_dtype
from apex_tpu.optimizers import FusedAdam

__all__ = ["lora_finetune"]


def lora_finetune(model, params, tokens, labels, *, rank: int = 4,
                  steps: int = 10, lr: float = 1e-2,
                  optimizer=None, rng: Optional[jax.Array] = None,
                  factors=None):
    """Fine-tune ``n_jobs`` adapters in one batched step loop.

    ``tokens``/``labels``: ``[n_jobs, batch, seq]`` int arrays — each
    job's own data stream. Returns ``(factors, losses)`` where
    ``factors`` is the STACKED adapter pytree (leaves ``[n_jobs, L,
    ...]``; slice job ``j`` with ``jax.tree.map(lambda x: x[j], factors)``
    and hand it to :meth:`AdapterStore.load`) and ``losses`` is the
    ``[steps, n_jobs]`` per-job loss history.

    ``optimizer`` defaults to ``FusedAdam(lr)``; pass ``factors`` (same
    stacked shape) to resume. The base ``params`` are frozen — they sit
    behind ``stop_gradient`` inside the merged forward and are never
    touched by the optimizer.
    """
    n_jobs = tokens.shape[0]
    if labels.shape != tokens.shape:
        raise ValueError(
            f"labels shape {labels.shape} != tokens shape {tokens.shape}")
    if factors is None:
        rng = jax.random.PRNGKey(0) if rng is None else rng
        keys = jax.random.split(rng, n_jobs)
        factors = jax.vmap(
            lambda k: init_adapter(model.config, rank, k))(keys)
    opt = optimizer or FusedAdam(lr=lr)

    frozen = jax.lax.stop_gradient(params)

    def job_loss(f, tok, lab):
        return model.apply(merge_adapter(frozen, f), tok, lab)

    def cohort_loss(stacked):
        losses = jax.vmap(job_loss)(stacked, tokens, labels)  # [n_jobs]
        return jnp.mean(losses), losses

    # the factor leaves ride the flat-bucket path: one contiguous buffer
    # per dtype, the layout multi_tensor_apply hands the fused kernels
    buffers, metas, aux = flatten_by_dtype(factors)
    state = opt.init(buffers)

    @jax.jit
    def train_step(buffers, state):
        stacked = unflatten_by_dtype(buffers, metas, aux)
        grads, losses = jax.grad(cohort_loss, has_aux=True)(stacked)
        gbufs, _, _ = flatten_by_dtype(grads)
        new_buffers, state = opt.step(gbufs, buffers, state, lr=lr)
        return new_buffers, state, losses

    history = []
    for _ in range(steps):
        buffers, state, losses = train_step(buffers, state)
        history.append(losses)
    factors = unflatten_by_dtype(buffers, metas, aux)
    return factors, jnp.stack(history) if history else jnp.zeros((0, n_jobs))
