"""Adapter format + device-resident adapter bank for Multi-LoRA.

An *adapter* is a set of low-rank factor pairs, one per target matrix:
for a target weight ``W [out, in]`` the factors are ``A [in, r]`` and
``B [r, out]`` and the adapted projection is ``y = x @ W.T + (x @ A) @ B``
— LoRA with the delta kept factored (LoRAFusion, PAPERS.md 2510.00206).
Targets are the two ColumnParallelLinear projections every layer owns:
the fused QKV (``query_key_value``) and the MLP up-projection
(``dense_h_to_4h``); both shard their OUTPUT dim over the tensor axis, so
the ``B`` factor shards with the heads while ``A`` stays replicated.

:class:`AdapterStore` registers adapters host-side and materializes them
as a stacked device bank ``[num_layers, max_adapters + 1, ...]`` per
factor. Index ``max_adapters`` is the reserved all-zeros NULL adapter:
requests without an ``adapter_id`` gather it and their delta is exactly
zero, so base traffic shares the one batched program with tenant traffic.
``load``/``unload`` rewrite one bank row in place (same shapes, no
retrace) — the hot-load hook the ROADMAP's live-update item needs.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.utils.activations import is_gated

__all__ = [
    "LORA_TARGETS",
    "AdapterStore",
    "UnknownAdapterError",
    "init_adapter",
    "random_adapter",
    "merge_adapter",
    "target_dims",
]

#: layer-local projections that take a low-rank delta, in bank order
LORA_TARGETS = ("query_key_value", "dense_h_to_4h")


class UnknownAdapterError(KeyError):
    """``adapter_id`` is not (or no longer) loaded in the AdapterStore."""


def target_dims(config) -> Dict[str, Tuple[int, int]]:
    """``{target: (in_dim, out_dim)}`` — FULL (unsharded) dims; the sharded
    engine's shard_map slices the ``B`` bank with the heads. MoE models
    carry no ``dense_h_to_4h`` (expert weights are routed, not adapted)."""
    c = config
    qpg = c.num_attention_heads // c.kv_heads
    dims = {"query_key_value": (c.hidden_size,
                                c.kv_heads * (qpg + 2) * c.head_dim)}
    if not c.num_moe_experts:
        gated = 2 if is_gated(c.activation) else 1
        dims["dense_h_to_4h"] = (c.hidden_size, gated * c.ffn_size)
    return dims


def init_adapter(config, rank: int, key) -> Dict[str, Dict[str, jax.Array]]:
    """Fresh trainable factors ``{target: {"A": [L, in, r], "B": [L, r,
    out]}}`` — A gaussian, B zeros, so the initial delta is exactly zero
    (the standard LoRA init: fine-tuning starts from the base model)."""
    c = config
    factors = {}
    for t, (din, dout) in target_dims(config).items():
        key, ka = jax.random.split(key)
        factors[t] = {
            "A": (0.02 * jax.random.normal(
                ka, (c.num_layers, din, rank))).astype(jnp.float32),
            "B": jnp.zeros((c.num_layers, rank, dout), jnp.float32),
        }
    return factors


def random_adapter(config, rank: int, key,
                   scale: float = 0.02) -> Dict[str, Dict[str, jax.Array]]:
    """Factors with BOTH halves nonzero (delta != 0) — the shape traffic
    generators and parity tests want; ``init_adapter`` is a zero delta."""
    c = config
    factors = {}
    for t, (din, dout) in target_dims(config).items():
        key, ka, kb = jax.random.split(key, 3)
        factors[t] = {
            "A": (scale * jax.random.normal(
                ka, (c.num_layers, din, rank))).astype(jnp.float32),
            "B": (scale * jax.random.normal(
                kb, (c.num_layers, rank, dout))).astype(jnp.float32),
        }
    return factors


def _check_factors(config, rank: int, factors) -> None:
    dims = target_dims(config)
    if set(factors) != set(dims):
        raise ValueError(
            f"adapter targets {sorted(factors)} != expected "
            f"{sorted(dims)} for this config")
    L = config.num_layers
    for t, (din, dout) in dims.items():
        a = factors[t]["A"]
        b = factors[t]["B"]
        if tuple(a.shape) != (L, din, rank):
            raise ValueError(
                f"{t}.A shape {tuple(a.shape)} != {(L, din, rank)}")
        if tuple(b.shape) != (L, rank, dout):
            raise ValueError(
                f"{t}.B shape {tuple(b.shape)} != {(L, rank, dout)}")


def merge_adapter(params, factors):
    """Fold an adapter into full weights: per layer/target
    ``W' = W + (A @ B).T`` (``W`` is ``[out, in]``, Megatron layout). The
    merged-reference engine the parity tests compare against runs these
    params with NO lora arguments — the ground truth for token-exactness.
    Handles both the stacked ``[L, ...]`` layer leaves and the per-layer
    list form; returns a new params pytree (input untouched)."""
    params = dict(params)
    params["transformer"] = dict(params["transformer"])
    layers = params["transformer"]["layers"]
    paths = {"query_key_value": ("self_attention", "query_key_value"),
             "dense_h_to_4h": ("mlp", "dense_h_to_4h")}

    def folded(w, a, b):
        # delta in fp32, cast back: params may be bf16
        delta = jnp.einsum("...ir,...ro->...oi", a.astype(jnp.float32),
                           b.astype(jnp.float32))
        return (w.astype(jnp.float32) + delta).astype(w.dtype)

    def set_weight(layer_params, sub, name, w):
        lp = dict(layer_params)
        lp[sub] = dict(lp[sub])
        lp[sub][name] = dict(lp[sub][name])
        lp[sub][name]["weight"] = w
        return lp

    if isinstance(layers, (list, tuple)):
        layers_new: Any = list(layers)
        for t, f in factors.items():
            sub, name = paths[t]
            for idx in range(len(layers_new)):
                w = layers_new[idx][sub][name]["weight"]
                layers_new[idx] = set_weight(
                    layers_new[idx], sub, name,
                    folded(w, f["A"][idx], f["B"][idx]))
    else:
        layers_new = layers
        for t, f in factors.items():
            sub, name = paths[t]
            w = layers_new[sub][name]["weight"]          # [L, out, in]
            layers_new = set_weight(layers_new, sub, name,
                                    folded(w, f["A"], f["B"]))
    params["transformer"]["layers"] = layers_new
    return params


class AdapterStore:
    """Host-side registry + device-resident stacked adapter bank.

    The bank is a pytree ``{target: {"A": [L, n+1, in, r], "B":
    [L, n+1, r, out]}}`` (``n = max_adapters``); engine step programs
    close over nothing — the bank is a runtime argument, gathered per
    slot in-jit, so ``load``/``unload`` between ticks never retrace.
    """

    def __init__(self, config, rank: int, max_adapters: int = 8):
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        if max_adapters < 1:
            raise ValueError(
                f"max_adapters must be >= 1, got {max_adapters}")
        self.config = config
        self.rank = int(rank)
        self.max_adapters = int(max_adapters)
        self._ids: Dict[str, int] = {}
        self._free = list(range(max_adapters))
        L = config.num_layers
        self._bank = {
            t: {"A": jnp.zeros((L, max_adapters + 1, din, rank),
                               jnp.float32),
                "B": jnp.zeros((L, max_adapters + 1, rank, dout),
                               jnp.float32)}
            for t, (din, dout) in target_dims(config).items()
        }

    # -- identity ---------------------------------------------------------
    @property
    def null_index(self) -> int:
        """Bank row of the reserved all-zeros adapter (base traffic)."""
        return self.max_adapters

    @property
    def bank(self):
        """The device bank pytree — pass straight into the step programs."""
        return self._bank

    def ids(self):
        return sorted(self._ids)

    def __contains__(self, adapter_id: str) -> bool:
        return adapter_id in self._ids

    def __len__(self) -> int:
        return len(self._ids)

    def index_of(self, adapter_id: Optional[str]) -> int:
        """Bank row for a request's ``adapter_id`` (None -> null row)."""
        if adapter_id is None:
            return self.null_index
        try:
            return self._ids[adapter_id]
        except KeyError:
            raise UnknownAdapterError(
                f"adapter {adapter_id!r} is not loaded "
                f"(loaded: {self.ids()})") from None

    # -- lifecycle --------------------------------------------------------
    def load(self, adapter_id: str, factors) -> int:
        """Register ``factors`` under ``adapter_id`` and write its bank
        row (re-loading an existing id overwrites in place). Returns the
        bank index. Raises when full or on a shape/target mismatch."""
        if not isinstance(adapter_id, str) or not adapter_id:
            raise ValueError("adapter_id must be a non-empty string")
        _check_factors(self.config, self.rank, factors)
        if adapter_id in self._ids:
            ix = self._ids[adapter_id]
        else:
            if not self._free:
                raise ValueError(
                    f"adapter bank full ({self.max_adapters} slots); "
                    f"unload one of {self.ids()}")
            ix = self._free.pop(0)
            self._ids[adapter_id] = ix
        for t, f in factors.items():
            self._bank[t] = {
                "A": self._bank[t]["A"].at[:, ix].set(
                    jnp.asarray(f["A"], jnp.float32)),
                "B": self._bank[t]["B"].at[:, ix].set(
                    jnp.asarray(f["B"], jnp.float32)),
            }
        return ix

    def unload(self, adapter_id: str) -> None:
        """Drop an adapter: zero its bank row and free the index. Requests
        already decoding against the row keep running — against a zero
        delta from the next step on (they degrade to base-model output);
        NEW submits with this id fail :class:`UnknownAdapterError`."""
        if adapter_id not in self._ids:
            raise UnknownAdapterError(
                f"adapter {adapter_id!r} is not loaded "
                f"(loaded: {self.ids()})")
        ix = self._ids.pop(adapter_id)
        for t in list(self._bank):
            self._bank[t] = {
                "A": self._bank[t]["A"].at[:, ix].set(0.0),
                "B": self._bank[t]["B"].at[:, ix].set(0.0),
            }
        self._free.append(ix)
        self._free.sort()
