"""Multi-LoRA: adapter fine-tuning + per-request multi-tenant serving.

Train side: :func:`lora_finetune` — batched multi-job fine-tuning of
several rank-r adapters against one frozen base model, factor updates on
the multi_tensor_apply flat-bucket path through FusedAdam. Serve side:
:class:`AdapterStore` — a device-resident stacked adapter bank the
serving engine gathers per slot in-jit, so one batched decode program
serves every tenant (docs/lora.md has the walkthrough).
"""

from apex_tpu.lora.adapter import (
    LORA_TARGETS,
    AdapterStore,
    UnknownAdapterError,
    init_adapter,
    merge_adapter,
    random_adapter,
    target_dims,
)
from apex_tpu.lora.finetune import lora_finetune

__all__ = [
    "LORA_TARGETS",
    "AdapterStore",
    "UnknownAdapterError",
    "init_adapter",
    "merge_adapter",
    "random_adapter",
    "target_dims",
    "lora_finetune",
]
