"""``python -m apex_tpu.analysis [paths ...]`` — run the hazard linter."""

import sys

from apex_tpu.analysis.engine import main

if __name__ == "__main__":
    sys.exit(main())
