"""``python -m apex_tpu.analysis [paths ...]`` — run the hazard linter;
``python -m apex_tpu.analysis mc [...]`` — run the fleet model checker
(imported lazily: the linter stays stdlib-only, the checker needs the
serving stack)."""

import sys


def _dispatch(argv):
    if argv and argv[0] == "mc":
        from apex_tpu.analysis.mc.cli import main as mc_main
        return mc_main(argv[1:])
    from apex_tpu.analysis.engine import main
    return main(argv)


if __name__ == "__main__":
    sys.exit(_dispatch(sys.argv[1:]))
