"""apex_tpu.analysis — JAX/TPU hazard tooling.

Two halves:

- a **static lint engine** (:mod:`~apex_tpu.analysis.engine` + the APX
  rule pack in :mod:`~apex_tpu.analysis.rules`) that machine-checks the
  JAX-specific invariants this repo has paid postmortems for — PRNG key
  reuse, concretization inside jit, host sync in step bodies, recompile
  hazards, unbound collective axes, bf16 dtype drift, interpret-mode
  pallas in scans, trace-time state mutation.  Run it as
  ``python -m apex_tpu.analysis`` (configured via
  ``[tool.apex_tpu.analysis]`` in pyproject.toml); the tier-1 gate test
  keeps the tree clean.
- a **retrace watchdog** (:mod:`~apex_tpu.analysis.retrace`) that counts
  jit cache misses at run time and raises after a configurable budget —
  wired into :func:`apex_tpu.resilience.run_training`.

See ``docs/analysis.md`` for the rule catalog and suppression/baseline
workflow.
"""

from apex_tpu.analysis.engine import (
    AnalysisConfig,
    Baseline,
    Finding,
    ModuleContext,
    Rule,
    RuleVisitor,
    analyze_file,
    analyze_paths,
    analyze_source,
    load_config,
    main,
)
from apex_tpu.analysis.retrace import RetraceBudgetExceeded, RetraceWatchdog

__all__ = [
    "AnalysisConfig",
    "Baseline",
    "Finding",
    "ModuleContext",
    "RetraceBudgetExceeded",
    "RetraceWatchdog",
    "Rule",
    "RuleVisitor",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "load_config",
    "main",
]
