"""The machine-checked invariant catalog (docs/analysis.md#mc-invariants).

Checked by :class:`InvariantChecker` after EVERY schedule event, against
the live fleet plus the full telemetry stream (an
:class:`~apex_tpu.observability.sinks.InMemorySink` attached to the
fleet's shared registry — every counter increment, incident event,
typed record, and terminal request record flows through it):

- ``exactly_once`` — every request id has at most one terminal
  ``kind="request"`` record, ever; at quiescence, ``requests_submitted``
  equals the sum of the ``requests_<reason>`` terminal counters.
- ``token_conservation`` — every harness-submitted request's final
  token stream is a prefix of its canonical
  :func:`~apex_tpu.analysis.mc.sim.sim_stream` (token-exact across any
  drain / restart / migration stitching), and a ``length`` finish
  carries exactly its full budget.
- ``page_balance`` — each sim engine's page pool balances: pages in use
  equal the recomputed sum over its live requests, allocs minus frees
  equal usage, and a closed engine holds zero pages.
- ``replica_id_reuse`` — a replica id that left the fleet never
  reappears, and new ids are strictly increasing.
- ``deploy_monotonic`` — within one deployment generation (a
  ``kind="deploy"`` ``action="start"`` record), no ``canary_pass`` or
  ``complete`` may follow a ``rollback``/``rejected``, and the terminal
  actions are mutually exclusive.
- ``drain_liveness`` — a replica entering ``draining``/``probing``
  leaves that state within a bounded horizon of tick events.
- ``counter_reconcile`` — every fleet lifecycle counter
  (``replica_drains``, ``replica_scale_*``, ``deploys_*``,
  ``canary_promotions``, ``requests_preempted``, ``requests_resumed``,
  ...) equals, key for key, the count of its same-named incident
  events; the ``deploys_*`` family additionally equals the count of
  typed ``kind="deploy"`` records claiming each action,
  ``requests_shed_quota`` equals the count of ``request_shed`` events
  claiming ``reason="quota"``, and applied autoscale decisions never
  exceed the scale counters they summarize.
- ``no_starvation`` — at quiescence every harness-submitted request id
  (including preempted-then-resumed and quota-deferred ones) appears in
  ``fleet.completed``: lower classes may wait arbitrarily under load,
  but a bounded settle horizon always retires them terminally.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from apex_tpu.analysis.mc.sim import SimEngine, sim_stream
from apex_tpu.serving.fleet.router import (
    REPLICA_DRAINING,
    REPLICA_PROBING,
)
from apex_tpu.serving.request import FINISH_REASONS

__all__ = ["Violation", "InvariantChecker"]

#: fleet lifecycle counter -> the same-named incident event it must
#: reconcile with, key for key (the serving telemetry contract)
COUNTER_EVENTS = {
    "replica_drains": "replica_drain",
    "replica_rebuilds": "replica_rebuild",
    "requests_migrated": "request_migrated",
    "replica_scale_ups": "replica_scale_up",
    "replica_scale_downs": "replica_scale_down",
    "deploys_started": "deploy_start",
    "deploys_completed": "deploy_complete",
    "deploys_rolled_back": "deploy_rollback",
    "deploys_rejected": "deploy_rejected",
    "canary_promotions": "canary_promoted",
    "requests_preempted": "request_preempted",
    "requests_resumed": "request_resumed",
    "requests_deferred_quota": "request_quota_deferred",
    "brownouts_escalated": "brownout_escalate",
    "brownouts_recovered": "brownout_recover",
}

#: deploys_* counter -> the typed kind="deploy" record action it counts
COUNTER_DEPLOY_ACTIONS = {
    "deploys_started": "start",
    "deploys_completed": "complete",
    "deploys_rolled_back": "rollback",
    "deploys_rejected": "rejected",
    "canary_promotions": "canary_pass",
}


@dataclass(frozen=True)
class Violation:
    """One invariant breach at one schedule step (``step`` is the event
    index, or -1 for the post-schedule settle/final checks)."""

    invariant: str
    detail: str
    step: int = -1

    def render(self) -> str:
        where = "final" if self.step < 0 else f"event {self.step}"
        return f"{self.invariant} @ {where}: {self.detail}"


class InvariantChecker:
    """Stateful checker over one harness run; see the module docstring.
    ``check(step)`` returns the NEW violations found at that step (each
    breach is reported once, not re-reported every following step)."""

    def __init__(self, harness):
        self.h = harness
        self.violations: List[Violation] = []
        self._reported = set()
        self._seen_replica_ids = set(
            r.replica_id for r in harness.fleet.replicas)
        self._live_replica_ids = set(self._seen_replica_ids)
        self._busy_since: Dict[int, int] = {}   # rid -> tick count at entry

    # -- plumbing ----------------------------------------------------------

    def _report(self, invariant: str, detail: str, step: int,
                dedup_key=None) -> None:
        key = (invariant, dedup_key if dedup_key is not None else detail)
        if key in self._reported:
            return
        self._reported.add(key)
        self.violations.append(Violation(invariant, detail, step))

    def _records(self, kind: str) -> List[dict]:
        return [r for r in self.h.sink.records if r.get("kind") == kind]

    def _events(self, name: str) -> List[dict]:
        return [r for r in self.h.sink.records
                if r.get("kind") == "event" and r.get("event") == name]

    # -- the catalog -------------------------------------------------------

    def check(self, step: int) -> List[Violation]:
        before = len(self.violations)
        self._check_exactly_once(step)
        self._check_token_conservation(step)
        self._check_page_balance(step)
        self._check_replica_ids(step)
        self._check_deploy_monotonic(step)
        self._check_drain_liveness(step)
        self._check_counter_reconcile(step)
        return self.violations[before:]

    def final(self) -> List[Violation]:
        before = len(self.violations)
        self.check(-1)
        counters = self.h.registry.counters()
        submitted = counters.get("requests_submitted", 0)
        terminal = sum(counters.get(f"requests_{r}", 0)
                       for r in FINISH_REASONS)
        if submitted != terminal:
            self._report(
                "exactly_once",
                f"requests_submitted={submitted} but terminal counters "
                f"sum to {terminal}", -1, dedup_key="counter-sum")
        missing = [rid for rid in sorted(self.h.expected)
                   if rid not in self.h.fleet.completed]
        if missing:
            self._report(
                "no_starvation",
                f"{len(missing)} request(s) never reached a terminal "
                f"result by quiescence: {missing[:8]}", -1,
                dedup_key="starved")
        return self.violations[before:]

    def _check_exactly_once(self, step: int) -> None:
        counts: Dict[int, int] = {}
        for rec in self._records("request"):
            rid = rec.get("request_id")
            counts[rid] = counts.get(rid, 0) + 1
        for rid, n in sorted(counts.items()):
            if n > 1:
                self._report(
                    "exactly_once",
                    f"request {rid} has {n} terminal kind=\"request\" "
                    f"records", step, dedup_key=rid)

    def _check_token_conservation(self, step: int) -> None:
        for rid, (prompt, max_new) in sorted(self.h.expected.items()):
            res = self.h.fleet.completed.get(rid)
            if res is None:
                continue
            canon = sim_stream(prompt, max_new)
            toks = list(res.tokens)
            if toks != canon[:len(toks)]:
                self._report(
                    "token_conservation",
                    f"request {rid} stream diverges from its canonical "
                    f"prefix (got {toks[:6]}..., want {canon[:6]}...)",
                    step, dedup_key=rid)
            elif res.finish_reason == "length" and len(toks) != max_new:
                self._report(
                    "token_conservation",
                    f"request {rid} finished 'length' with {len(toks)} "
                    f"of {max_new} budgeted tokens", step, dedup_key=rid)

    def _check_page_balance(self, step: int) -> None:
        for i, eng in enumerate(self.h.engines):
            if not isinstance(eng, SimEngine):
                continue
            pool = eng.pool
            if eng._closed:
                if pool.used != 0:
                    self._report(
                        "page_balance",
                        f"engine {i} (replica {eng.replica_id}) closed "
                        f"with {pool.used} pages still held", step,
                        dedup_key=("closed", i))
                continue
            want = sum(pool.pages_for(rec.request)
                       for rec in eng._active.values())
            if pool.used != want \
                    or pool.total_allocs - pool.total_frees != pool.used:
                self._report(
                    "page_balance",
                    f"engine {i} (replica {eng.replica_id}) holds "
                    f"{pool.used} pages; live requests account for "
                    f"{want} (allocs={pool.total_allocs}, "
                    f"frees={pool.total_frees})", step, dedup_key=i)

    def _check_replica_ids(self, step: int) -> None:
        current = set(r.replica_id for r in self.h.fleet.replicas)
        returned = (current - self._live_replica_ids) \
            & self._seen_replica_ids
        for rid in sorted(returned):
            self._report(
                "replica_id_reuse",
                f"replica id {rid} re-entered the fleet after leaving",
                step, dedup_key=rid)
        fresh = current - self._seen_replica_ids
        if fresh and self._seen_replica_ids:
            floor = max(self._seen_replica_ids)
            for rid in sorted(fresh):
                if rid <= floor:
                    self._report(
                        "replica_id_reuse",
                        f"new replica id {rid} is not monotonic "
                        f"(ids up to {floor} already used)", step,
                        dedup_key=("monotonic", rid))
        self._seen_replica_ids |= current
        self._live_replica_ids = current

    def _check_deploy_monotonic(self, step: int) -> None:
        generation = -1
        closed_by: Optional[str] = None
        for i, rec in enumerate(self._records("deploy")):
            action = rec.get("action")
            if action == "start":
                generation += 1
                closed_by = None
                continue
            if action == "rejected" and closed_by is None \
                    and generation < 0:
                # a rejected deploy never started rolling: its own
                # one-record generation
                generation += 1
                closed_by = "rejected"
                continue
            if closed_by is not None:
                self._report(
                    "deploy_monotonic",
                    f"deploy record #{i} action={action!r} after the "
                    f"generation was closed by {closed_by!r}", step,
                    dedup_key=(generation, i))
                continue
            if action in ("rollback", "rejected", "complete"):
                closed_by = action

    def _check_drain_liveness(self, step: int) -> None:
        ticks = self.h.ticks
        busy_now = {r.replica_id for r in self.h.fleet.replicas
                    if r.state in (REPLICA_DRAINING, REPLICA_PROBING)}
        for rid in list(self._busy_since):
            if rid not in busy_now:
                del self._busy_since[rid]
        for rid in busy_now:
            since = self._busy_since.setdefault(rid, ticks)
            if ticks - since > self.h.cfg.liveness_ticks:
                self._report(
                    "drain_liveness",
                    f"replica {rid} stuck draining/probing for "
                    f"{ticks - since} ticks "
                    f"(horizon {self.h.cfg.liveness_ticks})", step,
                    dedup_key=rid)

    def _check_counter_reconcile(self, step: int) -> None:
        counters = self.h.registry.counters()
        for counter, event in COUNTER_EVENTS.items():
            have = counters.get(counter, 0)
            want = len(self._events(event))
            if have != want:
                self._report(
                    "counter_reconcile",
                    f"counter {counter}={have} but {want} "
                    f"'{event}' events", step, dedup_key=counter)
        deploy_records = self._records("deploy")
        for counter, action in COUNTER_DEPLOY_ACTIONS.items():
            have = counters.get(counter, 0)
            want = sum(1 for r in deploy_records
                       if r.get("action") == action)
            if have != want:
                self._report(
                    "counter_reconcile",
                    f"counter {counter}={have} but {want} typed "
                    f"kind=\"deploy\" action={action!r} records", step,
                    dedup_key=("deploy", counter))
        shed_quota = sum(1 for r in self._events("request_shed")
                         if r.get("reason") == "quota")
        have = counters.get("requests_shed_quota", 0)
        if have != shed_quota:
            self._report(
                "counter_reconcile",
                f"counter requests_shed_quota={have} but {shed_quota} "
                f"'request_shed' events claim reason='quota'", step,
                dedup_key="requests_shed_quota")
        autoscale = self._records("autoscale")
        for action, counter in (("scale_up", "replica_scale_ups"),
                                ("scale_down", "replica_scale_downs")):
            applied = sum(1 for r in autoscale
                          if r.get("action") == action)
            if applied > counters.get(counter, 0):
                self._report(
                    "counter_reconcile",
                    f"{applied} kind=\"autoscale\" {action} records "
                    f"exceed counter {counter}="
                    f"{counters.get(counter, 0)}", step,
                    dedup_key=("autoscale", counter))
