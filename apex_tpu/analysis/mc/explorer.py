"""Schedule exploration: seeded random walks, exhaustive enumeration
at small depth, and delta-debug minimization of failing schedules.

Everything here is deterministic: :func:`explore` walks seeds
``cfg.seed, cfg.seed+1, ...``, each seed names one schedule
(:func:`~apex_tpu.analysis.mc.events.generate_schedule`), and a failure
is minimized by ddmin — repeatedly re-running subsets of the schedule
and keeping the smallest subset that still trips the SAME invariant.
The minimized reproduction is therefore ``(seed, kept indices)``: two
integers and a list, replayable anywhere with ``--replay``.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from apex_tpu.analysis.mc.events import (
    Event,
    format_schedule,
    generate_schedule,
)
from apex_tpu.analysis.mc.harness import MCConfig, RunResult, run_schedule

__all__ = ["ExploreResult", "explore", "exhaustive", "minimize", "replay"]


@dataclass
class ExploreResult:
    """Outcome of one exploration: how much was covered, and — on
    failure — the seed, the minimized index set, and the failing run."""

    explored: int
    cfg: MCConfig
    seed: Optional[int] = None
    schedule: List[Event] = field(default_factory=list)
    indices: Optional[List[int]] = None
    failure: Optional[RunResult] = None

    @property
    def ok(self) -> bool:
        return self.failure is None

    def render(self) -> str:
        if self.ok:
            return (f"mc: explored {self.explored} schedules "
                    f"(depth {self.cfg.depth}, "
                    f"{self.cfg.replicas} replicas, "
                    f"faults={'on' if self.cfg.faults else 'off'}) — "
                    f"no invariant violations")
        lines = [f"mc: VIOLATION after {self.explored} schedules"]
        if self.seed is not None:
            lines.append(f"  seed: {self.seed}")
        lines.append("  minimized schedule: "
                     + format_schedule(self.schedule, self.indices))
        for v in self.failure.violations:
            lines.append(f"  {v.render()}")
        if self.seed is not None:
            cmd = (f"python -m apex_tpu.analysis mc --replay {self.seed} "
                   f"--depth {self.cfg.depth} "
                   f"--replicas {self.cfg.replicas}")
            if self.indices is not None:
                cmd += " --indices " + ",".join(map(str, self.indices))
            if self.cfg.mutation:
                cmd += f" --mutate {self.cfg.mutation}"
            if not self.cfg.faults:
                cmd += " --no-faults"
            if self.cfg.preempt:
                cmd += " --preempt"
            lines.append(f"  replay: {cmd}")
        return "\n".join(lines)


def _trips(cfg: MCConfig, schedule: Sequence[Event],
           indices: Sequence[int], invariant: str) -> bool:
    sub = [schedule[i] for i in indices]
    res = run_schedule(cfg, sub)
    return any(v.invariant == invariant for v in res.violations)


def minimize(cfg: MCConfig, schedule: Sequence[Event],
             invariant: str) -> List[int]:
    """ddmin over event indices: the smallest (1-minimal) subset of the
    schedule that still violates ``invariant``. Every probe is a full
    deterministic re-run, so the result is trustworthy, not a guess."""
    indices = list(range(len(schedule)))
    if not _trips(cfg, schedule, indices, invariant):
        return indices       # flaky elsewhere; don't pretend to minimize
    n = 2
    while len(indices) >= 2:
        chunk = max(1, len(indices) // n)
        reduced = False
        for start in range(0, len(indices), chunk):
            candidate = indices[:start] + indices[start + chunk:]
            if candidate and _trips(cfg, schedule, candidate, invariant):
                indices = candidate
                n = max(2, n - 1)
                reduced = True
                break
        if not reduced:
            if n >= len(indices):
                break
            n = min(len(indices), n * 2)
    return indices


def explore(cfg: MCConfig) -> ExploreResult:
    """The main entry: run ``cfg.schedules`` seeded schedules, stop at
    the first invariant violation, minimize it, and report the
    seed-replayable reproduction."""
    for i in range(cfg.schedules):
        seed = cfg.seed + i
        schedule = generate_schedule(seed, cfg.depth, faults=cfg.faults,
                                     preempt=cfg.preempt)
        res = run_schedule(cfg, schedule, seed=seed)
        if res.ok:
            continue
        indices = minimize(cfg, schedule, res.violations[0].invariant)
        final = run_schedule(cfg, [schedule[j] for j in indices],
                             seed=seed)
        if not final.violations:     # minimization lost it; keep original
            indices, final = list(range(len(schedule))), res
        return ExploreResult(explored=i + 1, cfg=cfg, seed=seed,
                             schedule=list(schedule), indices=indices,
                             failure=final)
    return ExploreResult(explored=cfg.schedules, cfg=cfg)


def replay(cfg: MCConfig, seed: int,
           indices: Optional[Sequence[int]] = None) -> RunResult:
    """Re-run the schedule named by ``seed`` (optionally restricted to
    the minimized ``indices``) — the other half of the reproduction
    contract printed by :class:`ExploreResult`."""
    schedule = generate_schedule(seed, cfg.depth, faults=cfg.faults,
                                 preempt=cfg.preempt)
    if indices is not None:
        schedule = [schedule[i] for i in indices]
    return run_schedule(cfg, schedule, seed=seed)


def exhaustive(cfg: MCConfig, *,
               kinds: Sequence[str] = ("tick", "arrive", "drain",
                                       "cancel"),
               depth: Optional[int] = None,
               max_runs: Optional[int] = None) -> ExploreResult:
    """Exhaustively enumerate every schedule over a reduced alphabet at
    small depth (|kinds|^depth runs — keep depth <= 5). Complements the
    seeded walk: within its bounds this is a proof, not a sample."""
    depth = cfg.depth if depth is None else depth
    alphabet = [Event(k, a=1, b=3) for k in kinds]
    runs = 0
    for combo in itertools.product(alphabet, repeat=depth):
        if max_runs is not None and runs >= max_runs:
            break
        runs += 1
        res = run_schedule(cfg, list(combo))
        if not res.ok:
            return ExploreResult(explored=runs, cfg=cfg,
                                 schedule=list(combo),
                                 indices=list(range(depth)),
                                 failure=res)
    return ExploreResult(explored=runs, cfg=cfg)
