"""Deterministic in-memory engine for the fleet model checker.

:class:`SimEngine` plugs into :class:`~apex_tpu.serving.EngineSupervisor`
through the ``engine_factory`` seam and honors the full engine contract
the supervisor and fleet program against — ``submit`` / ``cancel`` /
``tick`` / ``close``, ``completed``, ``active_count`` /
``queued_count`` / ``queued_tokens``, ``prefill_compiles`` /
``decode_compiles``, ``scheduler.snapshot()``, ``inflight()`` — plus the
serving telemetry contract (``requests_submitted`` once per arrival,
exactly one terminal ``kind="request"`` record and one
``requests_<reason>`` counter per request), so the REAL supervisor /
router / autoscaler / deploy code runs unmodified on top of it.

Token streams are a pure function of (first prompt token, absolute
position): :func:`sim_token`. Because a migration/restart continuation's
prompt is the original prompt plus the recovered prefix, a continuation
resumes at exactly the next absolute position — so the checker can
assert token-exact conservation across any number of drains, restarts,
and migrations without knowing the schedule.

KV pages are modeled host-side by :class:`SimPagePool` (one per engine):
``ceil(total_len / page_size)`` pages reserved at admission, released at
the request's terminal state and on ``close()``. The pool's balance —
pages in use equals the sum over live requests, and zero after close —
is the checker's page-refcount invariant.

Faults come from :class:`~apex_tpu.testing_faults.ServingFaultInjector`:
``before_decode`` is called at the same host-side point as the real
engine's, so scripted ``decode_raise_calls`` drive the supervisor's
genuine restart-with-recovery path (the injector's call counter
advances across rebuilds, exactly as in the real fleet).

Poisoned weights (``testing_faults.corrupt_checkpoint_weights`` — every
integrity check green, values NaN) are modeled the way the real stack
experiences them: the one-token health probe still succeeds (argmax of
NaN logits is a valid token), while live traffic finishes with
``finish_reason="error"`` — so the deploy canary's SLO score is
genuinely the first detector, as in production.

Everything here is stdlib-only — no jax, no numpy — so exploring
thousands of schedules costs milliseconds each.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from apex_tpu.observability.trace import SPAN_PREEMPT, emit_span
from apex_tpu.serving import clock
from apex_tpu.serving.request import (
    FINISH_CANCELLED,
    FINISH_EOS,
    FINISH_LENGTH,
    FINISH_REJECTED,
    FINISH_TIMEOUT,
    FINISH_ERROR,
    PRIORITY_RANK,
    Request,
    RequestResult,
)
from apex_tpu.serving.scheduler import DeadlineExpiredError, QueueFullError

__all__ = ["SimModelConfig", "SimModel", "SimPagePool", "SimEngine",
           "sim_token", "sim_stream", "is_probe"]

#: the engine-side terminal counters, declared up front like the real
#: engine's so final snapshots carry every key
_SIM_COUNTERS = ("requests_submitted", "requests_eos", "requests_length",
                 "requests_cancelled", "requests_timeout",
                 "requests_rejected", "requests_error",
                 "requests_preempted")


def sim_token(first_prompt_token: int, position: int) -> int:
    """The deterministic token at absolute ``position`` of the stream
    seeded by ``first_prompt_token`` — pure, so expected streams can be
    recomputed independently of any schedule."""
    return (first_prompt_token * 7919 + position * 31 + 13) % 50021


def sim_stream(prompt: List[int], n: int) -> List[int]:
    """The canonical first ``n`` generated tokens for ``prompt``."""
    base = len(prompt)
    return [sim_token(prompt[0], base + i) for i in range(n)]


def is_probe(request: Request) -> bool:
    """The fleet's rebuild health probe (``prompt=[0]``, one token) —
    the one request shape that succeeds even on poisoned weights
    (argmax of NaN logits is a valid token; see module docstring)."""
    return list(request.prompt) == [0] and request.max_new_tokens == 1


def _params_healthy(params) -> bool:
    """True unless some float leaf of the (nested dict/list) params
    pytree is non-finite — NaN weights mark a poisoned checkpoint."""
    if isinstance(params, dict):
        return all(_params_healthy(v) for v in params.values())
    if isinstance(params, (list, tuple)):
        return all(_params_healthy(v) for v in params)
    # numpy arrays (checkpoint restores) without importing numpy here:
    # anything exposing flat iteration via tolist()
    tolist = getattr(params, "tolist", None)
    if tolist is not None:
        return _params_healthy(tolist())
    if isinstance(params, float):
        return math.isfinite(params)
    return True


@dataclass(frozen=True)
class SimModelConfig:
    """Just enough architecture surface for
    :func:`~apex_tpu.serving.prefix.prefix_salt` to fingerprint."""

    num_layers: int = 2
    hidden_size: int = 8
    num_attention_heads: int = 2
    kv_heads: int = 2
    vocab_size: int = 50021
    position_embedding_type: str = "sim"


@dataclass
class SimModel:
    """The model stub a :class:`~apex_tpu.serving.fleet.ReplicaFleet`
    constructor accepts (it only reads ``.config``)."""

    config: SimModelConfig = field(default_factory=SimModelConfig)


class SimPagePool:
    """Host-side model of the paged-KV pool's refcount ledger."""

    def __init__(self, page_size: int):
        self.page_size = max(1, int(page_size))
        self.used = 0
        self.total_allocs = 0
        self.total_frees = 0

    def pages_for(self, request: Request) -> int:
        return max(1, math.ceil(request.total_len / self.page_size))

    def alloc(self, n: int) -> int:
        self.used += n
        self.total_allocs += n
        return n

    def free(self, n: int) -> None:
        self.used -= n
        self.total_frees += n


class _SimActive:
    """One admitted (slot-resident) request."""

    __slots__ = ("request", "tokens", "submit_ts", "pages", "cancelled")

    def __init__(self, request: Request, submit_ts: float, pages: int):
        self.request = request
        self.tokens: List[int] = []
        self.submit_ts = submit_ts
        self.pages = pages
        self.cancelled = False


class _SimScheduler:
    """The queue view the supervisor snapshots during a restart."""

    def __init__(self, engine: "SimEngine"):
        self._engine = engine

    def snapshot(self) -> List[Tuple[Request, float]]:
        return [(req, ts) for req, ts in self._engine._queue]


class SimEngine:
    """See the module docstring. Constructor signature matches the
    ``engine_factory`` seam the supervisor rebuilds through."""

    def __init__(self, model, params, config, *,
                 metrics, faults=None, replica_id: Optional[int] = None,
                 adapters=None):
        self.model = model
        self.params = params
        self.config = config
        self.metrics = metrics
        self.metrics.declare_counters(*_SIM_COUNTERS)
        self._faults = faults
        self.replica_id = replica_id
        self._adapters = adapters
        self.healthy = _params_healthy(params)
        self.pool = SimPagePool(getattr(config, "page_size", 64))
        self.completed: Dict[int, RequestResult] = {}
        self._queue: List[Tuple[Request, float]] = []
        self._active: Dict[int, _SimActive] = {}
        #: preempted requests: (request, generated_tokens, submit_ts) —
        #: same shape the real engine parks (pages already released)
        self._parked: List[Tuple[Request, List[int], float]] = []
        #: set True by the supervisor (it drains take_parked each tick);
        #: gates engine-initiated preemption, same as the real engine
        self.resume_consumer = False
        self._floor: Optional[str] = None
        self.scheduler = _SimScheduler(self)
        self.prefill_compiles = 0
        self.decode_compiles = 0
        self._closed = False

    # -- introspection ----------------------------------------------------

    @property
    def active_count(self) -> int:
        return len(self._active)

    @property
    def queued_count(self) -> int:
        return len(self._queue)

    @property
    def queued_tokens(self) -> int:
        return sum(req.prompt_len for req, _ in self._queue)

    def queued_depth_by_class(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for req, _ in self._queue:
            p = req.sampling.priority
            out[p] = out.get(p, 0) + 1
        return out

    def queued_tokens_by_class(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for req, _ in self._queue:
            p = req.sampling.priority
            out[p] = out.get(p, 0) + req.prompt_len
        return out

    @property
    def parked_count(self) -> int:
        return len(self._parked)

    def inflight(self) -> List:
        out = [(rec.request, list(rec.tokens), rec.submit_ts)
               for _, rec in sorted(self._active.items())]
        out.extend((req, list(toks), ts)
                   for req, toks, ts in self._parked)
        return out

    # -- request lifecycle ------------------------------------------------

    def submit(self, request: Request, *, resubmission: bool = False) -> int:
        if self._closed:
            raise RuntimeError("engine is closed")
        if request.request_id in self.completed:
            raise ValueError(
                f"request id {request.request_id} already completed")
        if request.total_len > self.config.max_len:
            raise ValueError(
                f"prompt ({request.prompt_len}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds max_len "
                f"({self.config.max_len})")
        now = clock.now()
        if not resubmission:
            self.metrics.inc("requests_submitted")
        if len(self._queue) >= self.config.scheduler.max_queue:
            self._finish(request, [], FINISH_REJECTED, now, now)
            raise QueueFullError(
                f"queue full ({self.config.scheduler.max_queue})")
        start = request.arrival_ts if request.arrival_ts is not None else now
        if request.deadline_s is not None \
                and now - start > request.deadline_s:
            self._finish(request, [], FINISH_REJECTED, now, now)
            raise DeadlineExpiredError(
                f"request {request.request_id} deadline already elapsed")
        self._queue.append((request, now))
        return request.request_id

    def cancel(self, request_id: int) -> bool:
        for i, (req, ts) in enumerate(self._queue):
            if req.request_id == request_id:
                del self._queue[i]
                self._finish(req, [], FINISH_CANCELLED, ts, clock.now())
                return True
        rec = self._active.get(request_id)
        if rec is not None:
            rec.cancelled = True
            return True
        for i, (req, toks, ts) in enumerate(self._parked):
            if req.request_id == request_id:
                del self._parked[i]
                self._finish(req, toks, FINISH_CANCELLED, ts, clock.now())
                return True
        return False

    def tick(self) -> List[RequestResult]:
        """One scheduler iteration, same phase order as the real engine:
        expire deadlines, evict cancellations, admit FCFS (decode-
        starvation capped), then one batched decode step."""
        if self._closed:
            raise RuntimeError("engine is closed")
        before = set(self.completed)
        now = clock.now()
        self._expire(now)
        self._evict_cancelled(now)
        self._maybe_preempt(now)
        self._admit(now)
        if self._active:
            if self._faults is not None:
                # same host-side hook point as the real engine: a
                # scripted fault here IS a tick failure the supervisor
                # must survive
                self._faults.before_decode()
            self.decode_compiles = max(self.decode_compiles, 1)
            self._decode(now)
        return [self.completed[rid] for rid in sorted(
            set(self.completed) - before)]

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for rec in self._active.values():
            self.pool.free(rec.pages)
        self._active.clear()
        self._queue.clear()
        self._parked.clear()    # pages already released at park time

    # -- the phases -------------------------------------------------------

    def _expire(self, now: float) -> None:
        for req, ts in list(self._queue):
            if self._deadline_over(req, ts, now):
                self._queue.remove((req, ts))
                self._finish(req, [], FINISH_TIMEOUT, ts, now)
        for rid, rec in list(self._active.items()):
            if self._deadline_over(rec.request, rec.submit_ts, now):
                self._retire_active(rid, FINISH_TIMEOUT, now)
        for req, toks, ts in list(self._parked):
            if self._deadline_over(req, ts, now):
                self._parked.remove((req, toks, ts))
                self._finish(req, toks, FINISH_TIMEOUT, ts, now)

    @staticmethod
    def _deadline_over(req: Request, submit_ts: float, now: float) -> bool:
        if req.deadline_s is None:
            return False
        start = req.arrival_ts if req.arrival_ts is not None else submit_ts
        return now - start > req.deadline_s

    def _evict_cancelled(self, now: float) -> None:
        for rid, rec in list(self._active.items()):
            if rec.cancelled:
                self._retire_active(rid, FINISH_CANCELLED, now)

    def _admissible(self) -> List[int]:
        """Queue indices dispatchable under the admission floor, in
        class-then-FCFS order (strict priority, same policy as the real
        scheduler; the sim has no aging — schedules are short)."""
        floor = PRIORITY_RANK.get(self._floor) if self._floor else None
        order = []
        for i, (req, _) in enumerate(self._queue):
            rank = PRIORITY_RANK[req.sampling.priority]
            if floor is not None and rank > floor:
                continue
            order.append((rank, i))
        return [i for _, i in sorted(order)]

    def _maybe_preempt(self, now: float) -> None:
        """Engine-initiated preemption, mirroring the real engine: when
        the highest-class queued request is blocked on slots, park ONE
        strictly-lower-class active slot (most tokens-cheap victim
        first). Gated on ``resume_consumer`` — only a supervisor that
        drains ``take_parked()`` may trigger it."""
        if not self.resume_consumer or not self._active:
            return
        order = self._admissible()
        if not order:
            return
        if len(self._active) < self.config.max_slots:
            return
        head_rank = PRIORITY_RANK[
            self._queue[order[0]][0].sampling.priority]
        victims = [
            (PRIORITY_RANK[rec.request.sampling.priority],
             -len(rec.tokens), rid)
            for rid, rec in self._active.items()
            if PRIORITY_RANK[rec.request.sampling.priority] > head_rank
            and not rec.cancelled]
        if not victims:
            return
        _, _, rid = max(victims)
        self._park(rid, now, cause="schedule")

    def _park(self, rid: int, now: float, *, cause: str) -> None:
        rec = self._active.pop(rid)
        self.pool.free(rec.pages)
        self._parked.append((rec.request, list(rec.tokens),
                             rec.submit_ts))
        self.metrics.inc("requests_preempted")
        self.metrics.event("request_preempted",
                           request_id=rid, cause=cause,
                           priority=rec.request.sampling.priority,
                           tokens_parked=len(rec.tokens))
        emit_span(self.metrics, SPAN_PREEMPT,
                  trace_id=rec.request.trace_id, request_id=rid,
                  start_s=now, end_s=now, wall=clock.wall(),
                  replica_id=self.replica_id, detail=cause,
                  tokens_parked=len(rec.tokens),
                  priority=rec.request.sampling.priority)

    def park_class(self, priority: str, *, cause: str = "brownout") -> int:
        """Park EVERY active slot of ``priority``; the caller owns the
        ``take_parked()`` drain (same contract as the real engine)."""
        parked = 0
        for rid in sorted(self._active):
            rec = self._active[rid]
            if rec.request.sampling.priority != priority or rec.cancelled:
                continue
            self._park(rid, clock.now(), cause=cause)
            parked += 1
        return parked

    def take_parked(self) -> List[Tuple[Request, List[int], float]]:
        out, self._parked = self._parked, []
        return out

    def set_admission_floor(self, priority: Optional[str]) -> None:
        self._floor = priority

    @property
    def admission_floor(self) -> Optional[str]:
        return self._floor

    def _admit(self, now: float) -> None:
        admitted = 0
        cap = self.config.scheduler.max_prefills_per_tick
        while (self._queue and len(self._active) < self.config.max_slots
               and admitted < cap):
            order = self._admissible()
            if not order:
                break
            req, ts = self._queue.pop(order[0])
            pages = self.pool.pages_for(req)
            self.pool.alloc(pages)
            self._active[req.request_id] = _SimActive(req, ts, pages)
            self.prefill_compiles = max(self.prefill_compiles, 1)
            admitted += 1

    def _decode(self, now: float) -> None:
        for rid in sorted(self._active):
            rec = self._active[rid]
            req = rec.request
            if not self.healthy and not is_probe(req):
                # NaN weights: the token stream is garbage the integrity
                # check quarantines — terminal error, partial tokens kept
                self._retire_active(rid, FINISH_ERROR, now)
                continue
            position = req.prompt_len + len(rec.tokens)
            token = sim_token(req.prompt[0], position)
            rec.tokens.append(token)
            if req.eos_token is not None and token == req.eos_token:
                self._retire_active(rid, FINISH_EOS, now)
            elif len(rec.tokens) >= req.max_new_tokens:
                self._retire_active(rid, FINISH_LENGTH, now)

    # -- terminal emission (the serving telemetry contract) ----------------

    def _retire_active(self, rid: int, reason: str, now: float) -> None:
        rec = self._active.pop(rid)
        self.pool.free(rec.pages)
        self._finish(rec.request, rec.tokens, reason, rec.submit_ts, now)

    def _finish(self, request: Request, tokens: List[int], reason: str,
                submit_ts: float, now: float) -> None:
        result = RequestResult(
            request_id=request.request_id, prompt_len=request.prompt_len,
            tokens=list(tokens), finish_reason=reason,
            queue_s=0.0, prefill_s=0.0, decode_s=0.0,
            total_s=now - submit_ts,
            ttft_s=(now - submit_ts) if tokens else None,
            replica_id=self.replica_id,
            adapter_id=request.sampling.adapter_id,
            trace_id=request.trace_id,
            priority=request.sampling.priority)
        self.completed[request.request_id] = result
        self.metrics.inc(f"requests_{reason}")
        self.metrics.emit_record(result.record(wall=clock.wall()))
