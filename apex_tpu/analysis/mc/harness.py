"""The deterministic fleet-under-test harness.

:class:`FleetHarness` stands up one REAL :class:`ReplicaFleet` — sim
engines behind the ``engine_factory`` seam, a
:class:`~apex_tpu.serving.clock.VirtualClock` behind the clock seam, an
:class:`~apex_tpu.observability.sinks.InMemorySink` capturing the full
telemetry stream — and applies one schedule event at a time, running
the :class:`~apex_tpu.analysis.mc.invariants.InvariantChecker` after
every step. Nothing here is wall-clock-, thread-, or RNG-dependent:
the same ``(config, schedule)`` pair replays the same run bit-for-bit,
which is what makes delta-debug minimization and ``--replay`` honest.

Events degrade to recorded no-ops when their precondition does not hold
(see :mod:`~apex_tpu.analysis.mc.events`); control-plane perturbations
(drain / scale / deploy) mirror production policy by holding while a
deployment is in flight, exactly as the autoscaler does.

``MUTATIONS`` holds named, deliberately-injected protocol bugs used by
the mutation gate (tests prove the checker actually catches them):
``double_terminal_drain`` makes a draining supervisor emit a second
terminal record for the first continuation it hands over — the classic
exactly-once violation a drain/migration race would produce.
``double_terminal_preempt`` does the same on the preempt/resume path:
the parked-request drain records its first continuation as terminal
while the resume goes on to finish again (requires ``preempt`` mode so
schedules actually park something).
"""

from __future__ import annotations

import contextlib
import logging
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from apex_tpu.analysis.mc.events import Event
from apex_tpu.analysis.mc.invariants import InvariantChecker, Violation
from apex_tpu.analysis.mc.sim import SimEngine, SimModel
from apex_tpu.observability import MetricsRegistry
from apex_tpu.observability.sinks import InMemorySink
from apex_tpu.serving import clock
from apex_tpu.serving.clock import VirtualClock, use_clock
from apex_tpu.serving.engine import EngineConfig
from apex_tpu.serving.scheduler import SchedulerConfig
from apex_tpu.serving.fleet.autoscale import AutoscaleConfig
from apex_tpu.serving.fleet.deploy import CanaryConfig
from apex_tpu.serving.fleet.router import (
    REPLICA_ACTIVE,
    FleetConfig,
    ReplicaFleet,
)
from apex_tpu.serving.fleet.quota import QuotaConfig, TenantQuota
from apex_tpu.serving.request import (
    FINISH_LENGTH,
    PRIORITIES,
    Request,
    RequestResult,
    SamplingParams,
)
from apex_tpu.serving.supervisor import EngineSupervisor
from apex_tpu.testing_faults import (
    ServingFaultInjector,
    corrupt_checkpoint_weights,
)

__all__ = ["MCConfig", "RunResult", "FleetHarness", "run_schedule",
           "MUTATIONS"]


@dataclass(frozen=True)
class MCConfig:
    """Exploration bounds. Deliberately tight engine limits (2 slots,
    queue of 4, 4-token pages) so bounded schedules actually reach the
    queue-full / deadline / migration / page-churn corners."""

    replicas: int = 2
    depth: int = 12
    schedules: int = 50
    seed: int = 0
    faults: bool = True
    preempt: bool = False
    mutation: Optional[str] = None
    max_replicas: int = 4
    max_queue: int = 4
    max_slots: int = 2
    page_size: int = 4
    tick_dt: float = 0.05
    liveness_ticks: int = 200
    settle_ticks: int = 400


@dataclass
class RunResult:
    """One schedule's outcome: what was applied (including degraded
    no-ops, for the trace) and every violation found."""

    seed: int
    schedule: List[Event]
    applied: List[str] = field(default_factory=list)
    violations: List[Violation] = field(default_factory=list)
    requests: int = 0
    counters: Dict[str, int] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations


def _mutate_double_terminal(stack: contextlib.ExitStack) -> None:
    """The injected exactly-once bug: after a real
    ``detach_for_migration``, the draining supervisor ALSO records the
    first handed-over continuation as terminal (``length``) — while the
    continuation goes on to finish again on a peer. Control flow is
    untouched (tracking maps, return value), so the run proceeds
    normally and only the telemetry contract is broken: one request id,
    two terminal records, counters that no longer sum."""
    orig = EngineSupervisor.detach_for_migration

    def buggy(sup):
        conts = orig(sup)
        if conts:
            cont, recovered = conts[0]
            res = RequestResult(
                request_id=cont.request_id, prompt_len=cont.prompt_len,
                tokens=list(recovered), finish_reason=FINISH_LENGTH,
                queue_s=0.0, total_s=0.0, replica_id=sup.replica_id)
            sup.metrics.inc(f"requests_{FINISH_LENGTH}")
            sup.metrics.emit_record(res.record(wall=clock.wall()))
        return conts

    EngineSupervisor.detach_for_migration = buggy
    stack.callback(
        lambda: setattr(EngineSupervisor, "detach_for_migration", orig))


def _mutate_double_terminal_preempt(stack: contextlib.ExitStack) -> None:
    """The preempt-path exactly-once bug: when the supervisor drains a
    parked (preempted) request into its resume continuation, it ALSO
    records the first one as terminal (``length``) with its parked
    partial tokens — while the continuation goes on to finish again.
    The resume path itself is untouched (the drain still runs, tracking
    maps stay consistent), so only the telemetry contract breaks: one
    request id, two terminal records, counters that no longer sum."""
    orig = EngineSupervisor._drain_parked

    def buggy(sup, now):
        parked = list(getattr(sup.engine, "_parked", ()))[:1]
        orig(sup, now)
        for request, tokens, _submit_ts in parked:
            res = RequestResult(
                request_id=request.request_id,
                prompt_len=request.prompt_len, tokens=list(tokens),
                finish_reason=FINISH_LENGTH, queue_s=0.0, total_s=0.0,
                replica_id=sup.replica_id,
                priority=request.sampling.priority)
            sup.metrics.inc(f"requests_{FINISH_LENGTH}")
            sup.metrics.emit_record(res.record(wall=clock.wall()))

    EngineSupervisor._drain_parked = buggy
    stack.callback(
        lambda: setattr(EngineSupervisor, "_drain_parked", orig))


MUTATIONS = {
    "double_terminal_drain": _mutate_double_terminal,
    "double_terminal_preempt": _mutate_double_terminal_preempt,
}


class FleetHarness:
    """One fleet under one virtual clock, driven event by event.
    Build inside ``with use_clock(VirtualClock()):`` — see
    :func:`run_schedule`, which owns that plumbing."""

    def __init__(self, cfg: MCConfig):
        self.cfg = cfg
        self.sink = InMemorySink()
        self.registry = MetricsRegistry(sinks=[self.sink])
        self.model = SimModel()
        self.params = {"w": [[0.5, 0.5], [0.5, 0.5]]}
        self.engines: List[SimEngine] = []
        self.injectors: Dict[int, ServingFaultInjector] = {
            i: ServingFaultInjector() for i in range(cfg.replicas)}
        self.expected: Dict[int, Tuple[List[int], int]] = {}
        self.ticks = 0
        # explicit ids keep runs deterministic; the high base keeps them
        # clear of the process-global auto-id counter health probes draw
        # from (which can never plausibly reach it)
        self._next_rid = 10_000_001
        self._ckpt_dir: Optional[str] = None
        self._ckpt_step = 0

        def factory(model, params, config, *, metrics=None, faults=None,
                    replica_id=None, adapters=None):
            eng = SimEngine(model, params, config, metrics=metrics,
                            faults=faults, replica_id=replica_id,
                            adapters=adapters)
            self.engines.append(eng)
            return eng

        engine_config = EngineConfig(
            max_slots=cfg.max_slots, max_len=64,
            page_size=cfg.page_size,
            scheduler=SchedulerConfig(max_queue=cfg.max_queue,
                                      max_prefills_per_tick=1))
        # preempt mode adds a rate-and-inflight-capped tenant so the
        # quota_exceeded event has a door to bounce off; every other
        # tenant (the adapterless "base" arrivals) stays unlimited,
        # keeping the base vocabulary's behaviour untouched
        quotas = QuotaConfig(tenants={"t0": TenantQuota(
            rate_rps=1.0, burst=2, max_inflight=2)}) \
            if cfg.preempt else None
        self.fleet = ReplicaFleet(
            self.model, self.params, engine_config,
            fleet=FleetConfig(n_replicas=cfg.replicas),
            metrics=self.registry,
            faults=self.injectors,
            engine_factory=factory,
            quotas=quotas,
            autoscale=AutoscaleConfig(
                min_replicas=1, max_replicas=cfg.max_replicas,
                poll_interval_s=0.1, cooldown_s=0.3,
                hysteresis_polls=2, scale_up_queue_per_replica=2.0))
        self.checker = InvariantChecker(self)

    # -- event application -------------------------------------------------

    def apply(self, ev: Event) -> str:
        """Apply one event; returns a human-readable trace line (what
        actually happened, including the degraded no-op cases)."""
        handler = getattr(self, f"_ev_{ev.kind}")
        return handler(ev)

    def _tick_once(self) -> None:
        clock.get_clock().advance(self.cfg.tick_dt)
        self.fleet.tick()
        self.ticks += 1

    def _ev_tick(self, ev: Event) -> str:
        self._tick_once()
        return "tick"

    def _submit(self, ev: Event, deadline_s: Optional[float], *,
                adapter_id: Optional[str] = None) -> str:
        prompt = [1 + ev.b % 7] + [2] * (ev.a % 4)
        max_new = 1 + (ev.a + ev.b) % 5
        rid = self._next_rid
        self._next_rid += 1
        tag = ""
        kwargs = {}
        if self.cfg.preempt:
            # stamp a class (and optionally a tenant) only in preempt
            # mode — the base vocabulary keeps default-sampled requests,
            # so pre-priority (seed, depth) runs replay bit-for-bit
            priority = PRIORITIES[ev.b % len(PRIORITIES)]
            kwargs["sampling"] = SamplingParams(
                adapter_id=adapter_id, priority=priority)
            tag = f" class={priority}" + \
                (f" tenant={adapter_id}" if adapter_id else "")
        req = Request(prompt=prompt, max_new_tokens=max_new,
                      request_id=rid, arrival_ts=clock.now(),
                      deadline_s=deadline_s, **kwargs)
        self.expected[rid] = (list(req.prompt), max_new)
        try:
            self.fleet.submit(req)
        except Exception as exc:   # shed/rejected: recorded terminally
            return (f"arrive r{rid}{tag} -> rejected at the door "
                    f"({type(exc).__name__})")
        return f"arrive r{rid}{tag} prompt={len(prompt)} max_new={max_new}"

    def _ev_arrive(self, ev: Event) -> str:
        return self._submit(ev, None)

    def _ev_arrive_deadline(self, ev: Event) -> str:
        # tight enough that a queue wait or a mid-flight drain can blow
        # it: 2-6 tick intervals of budget
        budget = self.cfg.tick_dt * (2 + (ev.a + ev.b) % 5)
        return self._submit(ev, budget) + f" deadline={budget:.3f}"

    def _ev_advance(self, ev: Event) -> str:
        dt = self.cfg.tick_dt * (1 + ev.a % 4)
        clock.get_clock().advance(dt)
        return f"advance {dt:.3f}s"

    def _ev_cancel(self, ev: Event) -> str:
        live = [rid for rid in sorted(self.expected)
                if rid not in self.fleet.completed]
        if not live:
            return "cancel: no-op (nothing outstanding)"
        rid = live[ev.a % len(live)]
        found = self.fleet.cancel(rid)
        return f"cancel r{rid} -> {'cancelled' if found else 'miss'}"

    def _topology_clear(self) -> bool:
        dep = self.fleet.deployment
        return (self.fleet.topology_busy is None
                and (dep is None or dep.done))

    def _ev_drain(self, ev: Event) -> str:
        if not self._topology_clear():
            return "drain: no-op (topology busy or deployment active)"
        active = [r for r in self.fleet.replicas
                  if r.state == REPLICA_ACTIVE]
        if not active:
            return "drain: no-op (no active replica)"
        rid = active[ev.a % len(active)].replica_id
        self.fleet.drain_restart(rid)
        return f"drain replica {rid}"

    def _ev_scale_up(self, ev: Event) -> str:
        if not self._topology_clear():
            return "scale_up: no-op (topology busy or deployment active)"
        if len(self.fleet.replicas) >= self.cfg.max_replicas:
            return "scale_up: no-op (at max_replicas)"
        rid = self.fleet.add_replica()
        return f"scale_up -> replica {rid}"

    def _ev_scale_down(self, ev: Event) -> str:
        if not self._topology_clear():
            return "scale_down: no-op (topology busy or deployment active)"
        active = [r for r in self.fleet.replicas
                  if r.state == REPLICA_ACTIVE]
        if len(active) < 2:
            return "scale_down: no-op (last active replica)"
        rid = active[ev.a % len(active)].replica_id
        self.fleet.retire_replica(rid)
        return f"scale_down replica {rid}"

    def _deploy(self, ev: Event, poisoned: bool) -> str:
        kind = "deploy_poisoned" if poisoned else "deploy_good"
        if not self._topology_clear():
            return f"{kind}: no-op (topology busy or deployment active)"
        from apex_tpu.checkpoint import ShardedCheckpointManager
        if self._ckpt_dir is None:
            self._ckpt_dir = tempfile.mkdtemp(prefix="apex-mc-ckpt-")
        self._ckpt_step += 1
        step = self._ckpt_step
        mgr = ShardedCheckpointManager(self._ckpt_dir)
        import numpy as np
        value = 0.5 + step * 0.001
        mgr.save(step, {"w": np.full((2, 2), value, dtype=np.float32)},
                 force=True)
        if poisoned:
            corrupt_checkpoint_weights(self._ckpt_dir, step)
        try:
            self.fleet.deploy(
                checkpoint_dir=self._ckpt_dir, step=step,
                canary=CanaryConfig(window_s=self.cfg.tick_dt * 4,
                                    min_requests=1,
                                    max_window_s=self.cfg.tick_dt * 20))
        except Exception as exc:   # rejected deploys record themselves
            return f"{kind} step={step} -> rejected ({type(exc).__name__})"
        return f"{kind} step={step} started"

    def _ev_deploy_good(self, ev: Event) -> str:
        return self._deploy(ev, poisoned=False)

    def _ev_deploy_poisoned(self, ev: Event) -> str:
        return self._deploy(ev, poisoned=True)

    def _ev_preempt(self, ev: Event) -> str:
        if not self.cfg.preempt:
            return "preempt: no-op (preempt mode off)"
        # one tick first: arrivals admit at tick time, so without it a
        # preempt right after an arrive would always find empty slots
        self._tick_once()
        active = [r for r in self.fleet.replicas
                  if r.state == REPLICA_ACTIVE]
        if not active:
            return "preempt: no-op (no active replica)"
        replica = active[ev.a % len(active)]
        # never interactive: no class outranks it, so in production
        # nothing can preempt it — the checker verifies the mechanism
        # on the classes the ladder actually parks. Try the drawn class
        # first, fall back to the other preemptible one, so the event
        # parks whenever ANY preemptible slot is running
        first = 1 + ev.b % (len(PRIORITIES) - 1)
        parked, cls = 0, None
        for idx in (first, 3 - first):
            cls = PRIORITIES[idx]
            parked = replica.supervisor.preempt_class(
                cls, cause="schedule")
            if parked:
                break
        return (f"preempt replica {replica.replica_id} class={cls} "
                f"-> parked {parked}")

    def _ev_resume(self, ev: Event) -> str:
        if not self.cfg.preempt:
            return "resume: no-op (preempt mode off)"
        # resume is the supervisor's own tick-time drain of parked
        # continuations — the event just guarantees one happens here
        self._tick_once()
        return "resume: tick (drain parked continuations)"

    def _ev_quota_exceeded(self, ev: Event) -> str:
        if not self.cfg.preempt:
            return "quota_exceeded: no-op (preempt mode off)"
        # a same-instant burst from the capped tenant: past the bucket
        # burst (2) and inflight cap (2), the tail is shed at the door
        n = 3 + ev.a % 2
        lines = [self._submit(Event("arrive", a=(ev.a + i) % 8,
                                    b=(ev.b + i) % 8),
                              None, adapter_id="t0")
                 for i in range(n)]
        shed = sum("rejected at the door" in line for line in lines)
        return f"quota_exceeded: burst {n} as tenant t0 -> {shed} shed"

    def _ev_fault(self, ev: Event) -> str:
        if not self.injectors:
            return "fault: no-op (no injectors)"
        keys = sorted(self.injectors)
        inj = self.injectors[keys[ev.a % len(keys)]]
        target = inj.decode_calls + ev.b % 3
        inj.decode_raise_calls = frozenset(
            set(inj.decode_raise_calls) | {target})
        return (f"fault: arm replica {keys[ev.a % len(keys)]} "
                f"decode call {target}")

    # -- run shape ----------------------------------------------------------

    def settle(self) -> bool:
        """Tick until the fleet is quiescent (nothing tracked, no
        backlog, topology free, deployment done) or the settle budget
        runs out. Returns True when quiescence was reached."""
        for _ in range(self.cfg.settle_ticks):
            if (not self.fleet._tracked and not self.fleet._backlog
                    and self.fleet.topology_busy is None
                    and (self.fleet.deployment is None
                         or self.fleet.deployment.done)):
                return True
            self._tick_once()
        return False

    def cleanup(self) -> None:
        with contextlib.suppress(Exception):
            self.fleet.close()
        if self._ckpt_dir is not None:
            shutil.rmtree(self._ckpt_dir, ignore_errors=True)
            self._ckpt_dir = None


def run_schedule(cfg: MCConfig, schedule: Sequence[Event], *,
                 seed: int = -1) -> RunResult:
    """Run one schedule end to end: apply every event (checking the
    full invariant catalog after each), settle to quiescence, run the
    final reconciliation, tear down. Deterministic in
    ``(cfg, schedule)``; ``seed`` only labels the result."""
    result = RunResult(seed=seed, schedule=list(schedule))
    with contextlib.ExitStack() as stack:
        if cfg.mutation is not None:
            try:
                MUTATIONS[cfg.mutation](stack)
            except KeyError:
                raise ValueError(
                    f"unknown mutation {cfg.mutation!r} "
                    f"(have: {sorted(MUTATIONS)})") from None
        # injected faults / drains / rollbacks are the POINT here — the
        # serving stack's incident WARNINGs would drown the report
        prev_disable = logging.root.manager.disable
        logging.disable(logging.WARNING)
        stack.callback(logging.disable, prev_disable)
        stack.enter_context(use_clock(VirtualClock()))
        harness = FleetHarness(cfg)
        stack.callback(harness.cleanup)
        for i, ev in enumerate(schedule):
            try:
                result.applied.append(harness.apply(ev))
            except Exception as exc:
                result.violations.append(Violation(
                    "unhandled_exception",
                    f"{ev.render()} raised "
                    f"{type(exc).__name__}: {exc}", i))
                break
            result.violations.extend(harness.checker.check(i))
        else:
            if not harness.settle():
                result.violations.append(Violation(
                    "quiescence",
                    f"fleet not quiescent after {cfg.settle_ticks} "
                    f"settle ticks (tracked="
                    f"{sorted(harness.fleet._tracked)}, backlog="
                    f"{len(harness.fleet._backlog)}, busy="
                    f"{harness.fleet.topology_busy})"))
            result.violations.extend(harness.checker.final())
        result.requests = len(harness.expected)
        result.counters = dict(harness.registry.counters())
    return result
