"""Fleet control-plane model checker (docs/analysis.md#model-checker).

A loom/Shuttle-style bounded schedule explorer that drives the REAL
``serving/fleet`` control plane — :class:`~apex_tpu.serving.fleet.Router`
dispatch, drain/migration, autoscaling, canary deployment — under a
:class:`~apex_tpu.serving.clock.VirtualClock`, systematically running
seeded interleavings of tick / request-arrival / scale / deploy / fault
events and checking machine-readable invariants after every step.

Only the data plane is simulated: :class:`~.sim.SimEngine` stands in for
the jitted :class:`~apex_tpu.serving.engine.InferenceEngine` behind the
``engine_factory`` seam, honoring the engine's full supervisor-facing
interface and telemetry contract, so every protocol decision under test
(admission, routing, drain, migration stitching, probe gating, canary
scoring, counter/record emission) is made by production code.

Entry points: ``python -m apex_tpu.analysis mc`` (see :mod:`~.cli`),
:func:`~.explorer.explore` / :func:`~.explorer.replay` from Python.
A violation reports a delta-debug-minimized schedule that replays
deterministically from its seed:
``python -m apex_tpu.analysis mc --replay <seed> --indices i,j,...``.
"""

from apex_tpu.analysis.mc.events import Event, generate_schedule
from apex_tpu.analysis.mc.harness import MCConfig, RunResult, run_schedule
from apex_tpu.analysis.mc.invariants import Violation
from apex_tpu.analysis.mc.explorer import (
    ExploreResult,
    explore,
    exhaustive,
    minimize,
    replay,
)

__all__ = ["Event", "generate_schedule", "MCConfig", "RunResult",
           "run_schedule", "Violation", "ExploreResult", "explore",
           "exhaustive", "minimize", "replay"]
