"""The schedule vocabulary the explorer enumerates.

A schedule is a flat list of :class:`Event` values applied in order by
:class:`~apex_tpu.analysis.mc.harness.FleetHarness`. Events carry small
integer arguments (``a``/``b``) that the harness resolves against live
fleet state (replica index modulo the active set, prompt shape, ...) so
EVERY event is applicable in every state — an event whose precondition
does not hold (drain while another drain is running, scale past the
bounds) degrades to a recorded no-op instead of invalidating the
schedule. That keeps the schedule space dense: delta-debugging can drop
any subset of events and the remainder is still a legal run.

Schedules are generated from a seed via :func:`generate_schedule`
(``random.Random(seed)`` — no global RNG state), so a violation report
is reproducible from ``(seed, config)`` alone.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import List, Optional, Sequence

__all__ = ["Event", "EVENT_KINDS", "generate_schedule", "format_schedule"]

#: every kind the harness understands, with the generator's draw weight.
#: tick dominates — protocol progress happens there — with a spread of
#: control-plane perturbations layered on top.
_WEIGHTED_KINDS = (
    ("tick", 10),
    ("arrive", 6),
    ("arrive_deadline", 2),
    ("advance", 2),
    ("cancel", 1),
    ("drain", 2),
    ("scale_up", 1),
    ("scale_down", 1),
    ("deploy_good", 1),
    ("deploy_poisoned", 1),
    ("fault", 2),
)

#: priority/quota kinds (ISSUE 20), appended AFTER the base vocabulary
#: and only drawn when ``preempt=True`` — with the flag off the
#: generator's draw table is byte-identical to the pre-priority one,
#: so existing (seed, depth) reproductions keep replaying the same run
_PREEMPT_KINDS = (
    ("preempt", 2),
    ("resume", 2),
    ("quota_exceeded", 2),
)

EVENT_KINDS = tuple(kind for kind, _ in _WEIGHTED_KINDS + _PREEMPT_KINDS)


@dataclass(frozen=True)
class Event:
    """One schedule step: a kind plus two small resolver arguments."""

    kind: str
    a: int = 0
    b: int = 0

    def render(self) -> str:
        if self.kind in ("tick", "scale_up"):
            return self.kind
        return f"{self.kind}({self.a},{self.b})"


def generate_schedule(seed: int, depth: int, *,
                      faults: bool = True,
                      preempt: bool = False,
                      kinds: Optional[Sequence[str]] = None) -> List[Event]:
    """The seeded schedule: ``depth`` weighted draws from the event
    vocabulary. ``faults=False`` drops the fault/poisoned-deploy kinds
    (the bug-free baseline run); ``preempt=True`` adds the
    priority-preemption/quota kinds (and makes the harness stamp
    priority classes on arrivals); ``kinds`` restricts the alphabet
    (the exhaustive mode drives this)."""
    rng = random.Random(seed)
    vocab = _WEIGHTED_KINDS + (_PREEMPT_KINDS if preempt else ())
    table = [(k, w) for k, w in vocab
             if (kinds is None or k in kinds)
             and (faults or k not in ("fault", "deploy_poisoned"))]
    population = [k for k, _ in table]
    weights = [w for _, w in table]
    return [Event(rng.choices(population, weights)[0],
                  a=rng.randrange(8), b=rng.randrange(8))
            for _ in range(depth)]


def format_schedule(events: Sequence[Event],
                    indices: Optional[Sequence[int]] = None) -> str:
    keep = set(indices) if indices is not None else None
    parts = []
    for i, ev in enumerate(events):
        if keep is not None and i not in keep:
            continue
        parts.append(f"[{i}] {ev.render()}")
    return " ".join(parts)
