"""``python -m apex_tpu.analysis mc`` — the model-checker CLI.

Exit status 0 means every explored schedule upheld the invariant
catalog; 1 means a violation was found (the minimized, seed-replayable
schedule is printed), 2 means bad usage. ``--json`` emits the same
information machine-readably for CI gates.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from apex_tpu.analysis.mc.events import format_schedule
from apex_tpu.analysis.mc.explorer import exhaustive, explore, replay
from apex_tpu.analysis.mc.harness import MCConfig, MUTATIONS

__all__ = ["main"]


def _parse_indices(text: str) -> List[int]:
    try:
        return [int(p) for p in text.split(",") if p.strip()]
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--indices wants comma-separated ints, got {text!r}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis mc",
        description="Bounded model checker for the serving fleet "
                    "control plane (docs/analysis.md#model-checker).")
    p.add_argument("--schedules", type=int, default=50,
                   help="seeded schedules to explore (default 50)")
    p.add_argument("--depth", type=int, default=12,
                   help="events per schedule (default 12)")
    p.add_argument("--replicas", type=int, default=2,
                   help="initial fleet size (default 2)")
    p.add_argument("--seed", type=int, default=0,
                   help="first schedule seed (default 0)")
    p.add_argument("--no-faults", action="store_true",
                   help="drop fault/poisoned-deploy events from the "
                        "schedule vocabulary")
    p.add_argument("--preempt", action="store_true",
                   help="add the preempt/resume/quota_exceeded events "
                        "(arrivals get priority classes, one tenant is "
                        "quota-capped)")
    p.add_argument("--mutate", choices=sorted(MUTATIONS), default=None,
                   help="inject a named protocol bug (the mutation "
                        "gate: the checker must catch it)")
    p.add_argument("--exhaustive", action="store_true",
                   help="enumerate EVERY schedule over a reduced "
                        "alphabet at --depth (keep depth small)")
    p.add_argument("--replay", type=int, default=None, metavar="SEED",
                   help="re-run one schedule by seed instead of "
                        "exploring")
    p.add_argument("--indices", type=_parse_indices, default=None,
                   help="with --replay: restrict to these "
                        "comma-separated event indices (the minimized "
                        "subset a violation report printed)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    cfg = MCConfig(replicas=args.replicas, depth=args.depth,
                   schedules=args.schedules, seed=args.seed,
                   faults=not args.no_faults, preempt=args.preempt,
                   mutation=args.mutate)

    if args.replay is not None:
        res = replay(cfg, args.replay, args.indices)
        if args.as_json:
            print(json.dumps({
                "seed": args.replay,
                "indices": args.indices,
                "applied": res.applied,
                "violations": [vars(v) for v in res.violations],
                "requests": res.requests,
            }, indent=2))
        else:
            print(f"replay seed={args.replay}: "
                  + format_schedule(res.schedule))
            for line in res.applied:
                print(f"  {line}")
            for v in res.violations:
                print(f"  {v.render()}")
            if res.ok:
                print(f"ok: {res.requests} requests, "
                      f"no invariant violations")
        return 0 if res.ok else 1

    if args.exhaustive:
        er = exhaustive(cfg, depth=args.depth)
    else:
        er = explore(cfg)
    if args.as_json:
        out = {"explored": er.explored, "ok": er.ok}
        if not er.ok:
            out.update({
                "seed": er.seed,
                "indices": er.indices,
                "schedule": [ev.render() for ev in er.schedule],
                "violations": [vars(v) for v in er.failure.violations],
            })
        print(json.dumps(out, indent=2))
    else:
        print(er.render())
    return 0 if er.ok else 1


if __name__ == "__main__":
    sys.exit(main())
