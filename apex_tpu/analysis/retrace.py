"""Retrace watchdog: the runtime half of the hazard tooling.

The static rules (APX004) catch signatures *designed* to retrace; this
module catches the storms that only manifest at run time — a data
pipeline that emits a ragged final batch, a checkpoint restore that
changes a pytree's structure, a shape-dependent branch.  A recompilation
storm is the nastiest kind of perf bug: nothing is wrong, the step just
takes 10× longer, and on a preemptible TPU slice the job dies of slowness
before anyone looks at a profile (the PR 1 tier-1 gate truncation was
this, in miniature).

:class:`RetraceWatchdog` wraps a step function.  Per call it measures
whether a compilation happened — via the jit wrapper's ``_cache_size()``
when available, falling back to tracking distinct abstract signatures
``(shape, dtype, pytree structure)`` of the arguments — and

- emits structured ``log_event`` telemetry (``event=retrace``) with the
  call count and signature, ordered by ``seq``/``ts`` stamps;
- mirrors each counted retrace into an attached
  :class:`apex_tpu.observability.MetricsRegistry` (``metrics=`` — a
  ``retraces`` counter plus ``retrace`` events), so the monitor CLI
  reports recompilation storms without scraping log lines;
- raises :class:`RetraceBudgetExceeded` once retraces (compilations
  beyond ``expected_compiles``) exceed ``budget``.

``resilience.run_training`` wraps its ``step_fn`` automatically (config
``retrace_budget``), so a storm surfaces as a watchdog event instead of a
silent slowdown.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

from apex_tpu.utils.logging import get_logger, log_event

__all__ = ["RetraceBudgetExceeded", "RetraceWatchdog"]


class RetraceBudgetExceeded(RuntimeError):
    """Raised when a wrapped callable recompiles more than its budget."""

    def __init__(self, message: str, *, name: str, retraces: int,
                 budget: int):
        super().__init__(message)
        self.name = name
        self.retraces = retraces
        self.budget = budget


def _abstract_signature(args: Tuple[Any, ...], kwargs: dict) -> Tuple:
    """Hashable jit-cache key proxy: pytree structure + per-leaf
    (shape, dtype) for array leaves, the value itself for hashable
    non-array leaves (weak-typed scalars collapse to their type, which
    matches jit's weak-type bucketing closely enough for storm
    detection)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append(("arr", tuple(shape), str(dtype)))
        else:
            try:
                hash(leaf)
                sig.append(("val", type(leaf).__name__, leaf))
            except TypeError:
                sig.append(("obj", type(leaf).__name__))
    return (str(treedef), tuple(sig))


class RetraceWatchdog:
    """Wrap a (typically jitted) callable and count its recompilations.

    Args:
      fn: the callable. A ``jax.jit`` wrapper is detected via its
        ``_cache_size()`` method (jax 0.4.x+) and counted exactly; any
        other callable falls back to abstract-signature tracking.
      budget: retraces allowed beyond ``expected_compiles`` before
        :class:`RetraceBudgetExceeded` is raised.  ``None`` = never raise,
        log only.
      expected_compiles: compilations that are legitimate (default 1 —
        the warmup trace).  Donated-buffer aware restarts that *should*
        recompile can raise this.
      name: label for telemetry (defaults to the callable's ``__name__``).
      on_retrace: optional ``(watchdog, signature) -> None`` hook, called
        after telemetry on every counted retrace.
      metrics: optional :class:`apex_tpu.observability.MetricsRegistry` —
        each counted retrace then also increments its ``retraces``
        counter and emits an ``event="retrace"`` record, so the monitor
        CLI reports retraces without scraping log lines.
    """

    def __init__(self, fn: Callable, *, budget: Optional[int] = None,
                 expected_compiles: int = 1, name: Optional[str] = None,
                 logger=None, on_retrace: Optional[Callable] = None,
                 metrics=None):
        self._fn = fn
        self.budget = budget
        self.expected_compiles = expected_compiles
        self.name = name or getattr(fn, "__name__", type(fn).__name__)
        self._log = logger or get_logger(__name__)
        self._on_retrace = on_retrace
        self.metrics = metrics
        self.calls = 0
        self.compiles = 0
        self._signatures: set = set()
        self._cache_probe = getattr(fn, "_cache_size", None)
        # a pre-warmed jit cache is not this watchdog's doing: baseline it
        self._last_cache_size = (self._cache_probe()
                                 if callable(self._cache_probe) else None)

    @property
    def retraces(self) -> int:
        """Compilations beyond the expected warmup count."""
        return max(0, self.compiles - self.expected_compiles)

    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        self.calls += 1
        self._observe(args, kwargs)
        return out

    # -- counting ---------------------------------------------------------

    def _observe(self, args, kwargs) -> None:
        new_compiles = 0
        sig = None
        if self._last_cache_size is not None and callable(self._cache_probe):
            size = self._cache_probe()
            if size > self._last_cache_size:
                new_compiles = size - self._last_cache_size
            self._last_cache_size = size
        else:
            sig = _abstract_signature(args, kwargs)
            if sig not in self._signatures:
                self._signatures.add(sig)
                new_compiles = 1
        if not new_compiles:
            return
        retraces_before = self.retraces
        self.compiles += new_compiles
        if self.compiles <= self.expected_compiles:
            return
        if sig is None:
            sig = _abstract_signature(args, kwargs)
        log_event(self._log, "retrace", fn=self.name, call=self.calls,
                  compiles=self.compiles, retraces=self.retraces,
                  budget=("none" if self.budget is None else self.budget),
                  signature=hex(abs(hash(sig)))[:10])
        if self.metrics is not None:
            # counter delta, not a bare +1: one batched _cache_size jump
            # can cover several compiles
            self.metrics.inc("retraces", self.retraces - retraces_before)
            self.metrics.event("retrace", fn=self.name, call=self.calls,
                               compiles=self.compiles,
                               retraces=self.retraces)
        if self._on_retrace is not None:
            self._on_retrace(self, sig)
        if self.budget is not None and self.retraces > self.budget:
            line = log_event(
                self._log, "retrace_budget_exceeded", fn=self.name,
                retraces=self.retraces, budget=self.budget,
                calls=self.calls, level="error")
            raise RetraceBudgetExceeded(
                f"'{self.name}' recompiled {self.retraces} times past the "
                f"expected {self.expected_compiles} (budget "
                f"{self.budget}) — recompilation storm; check for "
                f"varying shapes/dtypes or pytree-structure churn in its "
                f"arguments [{line}]",
                name=self.name, retraces=self.retraces, budget=self.budget)
