"""APX011 — wall-clock hygiene in the serving/loadtest planes.

Everything under ``serving/`` and ``loadtest/`` must tell time through
:mod:`apex_tpu.serving.clock` (``clock.now()``/``clock.wall()``/
``clock.sleep()``).  A direct ``time.time()``/``time.monotonic()``/
``perf_counter()``/``time.sleep()`` read punches through the virtual
clock: the model checker's deterministic schedules stop being
deterministic, and replay traces stop replaying.  The clock module
itself is the single sanctioned consumer of :mod:`time` in those trees.

Detection: any resolved call to the :mod:`time` entry points below, in
a module whose path lies under ``serving/`` or ``loadtest/`` — except
``serving/clock.py``.
"""

from __future__ import annotations

import ast
from typing import List

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor

_WALL_CLOCK_CALLS = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.sleep",
}

#: replacement suggestions, keyed by the time.* entry point
_SUBSTITUTE = {
    "time.time": "clock.wall()", "time.time_ns": "clock.wall()",
    "time.monotonic": "clock.now()", "time.monotonic_ns": "clock.now()",
    "time.perf_counter": "clock.now()",
    "time.perf_counter_ns": "clock.now()",
    "time.sleep": "clock.sleep()",
}


def _scoped(path: str) -> bool:
    norm = "/" + path.replace("\\", "/")
    if norm.endswith("/serving/clock.py"):
        return False
    return "/serving/" in norm or "/loadtest/" in norm


class APX011WallClock(Rule):
    code = "APX011"
    name = "wall-clock-hygiene"
    description = ("direct time.time/monotonic/perf_counter/sleep in "
                   "serving/ or loadtest/ bypasses the virtual clock "
                   "seam — use apex_tpu.serving.clock")

    def check(self, module: ModuleContext) -> List[Finding]:
        if not _scoped(module.path):
            return []
        v = RuleVisitor(self, module)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = v.resolve(node.func)
            if fname in _WALL_CLOCK_CALLS:
                v.report(node, (
                    f"`{fname}()` bypasses the clock seam — use "
                    f"`{_SUBSTITUTE[fname]}` so VirtualClock schedules "
                    f"(model checker, scenario replay) stay "
                    f"deterministic"))
        return v.findings
