"""APX004 — recompile hazards on jitted signatures.

``jax.jit`` caches compilations by the abstract signature of every
non-static argument.  Two signature shapes silently defeat the cache:

1. **mutable defaults** (``def f(x, opts={})``) — a dict/list default is
   a pytree of leaves, and any call that mutates or replaces it changes
   the tree structure → retrace.  Worse, an *unhashable* value passed for
   a ``static_argnames`` parameter raises at call time.
2. **shape-like Python scalars not marked static** — a ``shape``/
   ``*_shape`` parameter consumed by ``reshape``/``zeros``-style calls
   must be concrete at trace time; passing it as a traced arg either
   fails or, when it arrives as a plain int that changes per call,
   triggers a retrace per distinct value (the recompilation-storm shape
   that truncated this repo's tier-1 gate in PR 1).

Detection is signature-only (no cross-call dataflow): jitted defs with
list/dict/set displays (or ``list()``/``dict()``/``set()`` calls) as
defaults, and params named ``shape``/``*_shape``/``*_shapes`` absent
from ``static_argnames``/``static_argnums``.
"""

from __future__ import annotations

import ast
from typing import List

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor
from apex_tpu.analysis.rules._common import param_names, traced_functions

_MUTABLE_CTORS = {"list", "dict", "set", "bytearray"}


def _is_mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in _MUTABLE_CTORS
    return False


def _shape_like(name: str) -> bool:
    return name == "shape" or name.endswith("_shape") or name.endswith(
        "_shapes")


class APX004Recompile(Rule):
    code = "APX004"
    name = "recompile-hazard"
    description = ("mutable/unhashable defaults or unmarked shape args on "
                   "a jitted signature defeat the jit cache")

    def check(self, module: ModuleContext) -> List[Finding]:
        v = RuleVisitor(self, module)
        for func, info in traced_functions(module.tree, v.resolve).items():
            if info.kind != "jit":
                continue  # grad/vmap tracing recompiles nothing
            static = info.resolve_static(func)
            args = func.args
            defaults = list(zip(
                [a.arg for a in (args.posonlyargs + args.args)][
                    -len(args.defaults):] if args.defaults else [],
                args.defaults))
            defaults += [(a.arg, d) for a, d in zip(args.kwonlyargs,
                                                    args.kw_defaults)
                         if d is not None]
            for pname, default in defaults:
                if _is_mutable_default(default):
                    v.report(default, (
                        f"mutable default for parameter '{pname}' of "
                        f"jitted '{func.name}' — every structural change "
                        f"retraces; pass an immutable (tuple/frozen) "
                        f"value or mark it static"))
            for pname in param_names(func):
                if _shape_like(pname) and pname not in static:
                    v.report(func, (
                        f"shape-like parameter '{pname}' of jitted "
                        f"'{func.name}' is not in static_argnames — "
                        f"per-value retraces (or a trace-time failure) "
                        f"instead of one compile per shape"))
        return v.findings
