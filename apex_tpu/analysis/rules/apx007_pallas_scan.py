"""APX007 — interpret-mode ``pallas_call`` inside ``lax.scan`` bodies.

The exact SPMD-partitioner trap PR 1 hit in ``ring_attention``: on
jax 0.4.x, a ``pallas_call`` with ``interpret=True`` (or a
runtime-configurable ``interpret=`` flag) inside a ``lax.scan`` body
makes XLA's SPMD partitioner choke when the scan traces under a sharded
mesh — the interpreter's callback lowering can't be partitioned.  The
fix that shipped was unrolling the hops under interpret mode and keeping
the scan only on real hardware; this rule keeps the trap from being
reintroduced.

Detection: functions (or lambdas) used as a scan body in the same file —
``lax.scan(f, ...)`` — whose body (directly, or through one local call
hop) contains a ``pallas_call`` with an ``interpret`` keyword that is not
the literal ``False``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor

_SCAN_FUNCS = {"jax.lax.scan"}


def _is_pallas_call(fname) -> bool:
    return fname is not None and (
        fname.endswith(".pallas_call") or fname == "pallas_call"
        or fname.endswith(".pl.pallas_call"))


def _interpret_not_off(call: ast.Call) -> bool:
    """True when the call carries interpret= that is not literally False —
    literal True and runtime-selected flags are both the hazard (the
    latter becomes interpret=True exactly on the CPU paths that trace
    under a forced mesh)."""
    for kw in call.keywords:
        if kw.arg == "interpret":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is False)
    return False


class APX007PallasScan(Rule):
    code = "APX007"
    name = "interpret-pallas-in-scan"
    description = ("pallas_call with interpret mode inside a lax.scan "
                   "body trips XLA's SPMD partitioner (ring_attention "
                   "postmortem, PR 1)")

    def check(self, module: ModuleContext) -> List[Finding]:
        v = RuleVisitor(self, module)
        # local function name -> the offending pallas_call nodes inside it
        offenders: Dict[str, List[ast.Call]] = {}
        callers: Dict[str, Set[str]] = {}  # fn name -> local fns it calls
        local_funcs: Dict[str, ast.AST] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_funcs[node.name] = node
        for name, func in local_funcs.items():
            for sub in ast.walk(func):
                if isinstance(sub, ast.Call):
                    fname = v.resolve(sub.func)
                    if _is_pallas_call(fname) and _interpret_not_off(sub):
                        offenders.setdefault(name, []).append(sub)
                    elif isinstance(sub.func, ast.Name) and \
                            sub.func.id in local_funcs:
                        callers.setdefault(name, set()).add(sub.func.id)
        # one transitive hop: f calls g, g holds the pallas_call
        reaches: Dict[str, List[ast.Call]] = dict(offenders)
        for name, callees in callers.items():
            for c in callees:
                if c in offenders:
                    reaches.setdefault(name, []).extend(offenders[c])

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = v.resolve(node.func)
            if fname not in _SCAN_FUNCS or not node.args:
                continue
            body = node.args[0]
            if isinstance(body, ast.Lambda):
                for sub in ast.walk(body):
                    if isinstance(sub, ast.Call) and _is_pallas_call(
                            v.resolve(sub.func)) and _interpret_not_off(sub):
                        v.report(node, self._msg("<lambda>"))
            elif isinstance(body, ast.Name) and body.id in reaches:
                v.report(node, self._msg(body.id))
        return v.findings

    @staticmethod
    def _msg(body_name: str) -> str:
        return (f"lax.scan body '{body_name}' reaches a pallas_call with "
                f"interpret mode enabled — the SPMD partitioner cannot "
                f"split the interpreter callback; unroll the loop under "
                f"interpret mode (ring_attention pattern) or force "
                f"interpret=False inside scans")
