"""APX010 — scenario schema drift (cross-file).

The load-test scenario schema lives in three places that must agree:
the :class:`~apex_tpu.loadtest.scenario.Scenario` dataclass fields, the
strict ``known`` key set its ``from_dict`` validates against, and the
``scenario.<attr>`` reads the runner performs.  Drift in any direction
is a silent contract break: a field missing from ``known`` can never be
loaded from JSON; a ``known`` key with no field is validated but
dropped; a runner read of a name the dataclass does not carry is an
``AttributeError`` waiting for the first scenario that exercises it.

Detection (project-wide pass, fires only when
``loadtest/scenario.py`` is part of the analyzed set):

- ``known`` keys vs ``Scenario`` field names, both directions;
- every ``scenario.<attr>`` access in ``loadtest/runner.py`` must name
  a ``Scenario`` field, property, or method.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set, Tuple

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor

_SCENARIO_PATH = "loadtest/scenario.py"
_RUNNER_PATH = "loadtest/runner.py"


def _find_module(modules: Sequence[ModuleContext],
                 suffix: str) -> Optional[ModuleContext]:
    for m in modules:
        if m.path.replace("\\", "/").endswith(suffix):
            return m
    return None


def _scenario_surface(cls: ast.ClassDef
                      ) -> Tuple[dict, Set[str], Optional[ast.Assign],
                                 Set[str]]:
    """(field name -> AnnAssign, method/property names, the ``known``
    assignment inside ``from_dict``, its key set)."""
    fields: dict = {}
    callables: Set[str] = set()
    known_node: Optional[ast.Assign] = None
    known: Set[str] = set()
    for stmt in cls.body:
        if (isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
                and not stmt.target.id.startswith("_")):
            fields[stmt.target.id] = stmt
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            callables.add(stmt.name)
            if stmt.name != "from_dict":
                continue
            for node in ast.walk(stmt):
                if (isinstance(node, ast.Assign)
                        and any(isinstance(t, ast.Name)
                                and t.id == "known"
                                for t in node.targets)
                        and isinstance(node.value, ast.Set)):
                    known_node = node
                    known = {e.value for e in node.value.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str)}
    return fields, callables, known_node, known


class APX010ScenarioSchema(Rule):
    code = "APX010"
    name = "scenario-schema-drift"
    description = ("Scenario fields, from_dict's strict key set, and the "
                   "runner's attribute reads must agree")
    project = True

    def check_project(self, modules: Sequence[ModuleContext]
                      ) -> List[Finding]:
        scen = _find_module(modules, _SCENARIO_PATH)
        if scen is None:
            return []
        cls = next((n for n in ast.walk(scen.tree)
                    if isinstance(n, ast.ClassDef)
                    and n.name == "Scenario"), None)
        if cls is None:
            return []
        fields, callables, known_node, known = _scenario_surface(cls)
        findings: List[Finding] = []

        v = RuleVisitor(self, scen)
        if known_node is not None:
            for key in sorted(known - set(fields)):
                v.report(known_node, (
                    f"from_dict accepts key {key!r} but Scenario has no "
                    f"such field — the key validates, then vanishes"))
            for name in sorted(set(fields) - known):
                v.report(fields[name], (
                    f"Scenario field {name!r} is missing from "
                    f"from_dict's strict key set — no JSON scenario can "
                    f"ever set it"))
        findings.extend(v.findings)

        runner = _find_module(modules, _RUNNER_PATH)
        if runner is not None:
            surface = set(fields) | callables
            rv = RuleVisitor(self, runner)
            for node in ast.walk(runner.tree):
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "scenario"
                        and node.attr not in surface
                        and not node.attr.startswith("__")):
                    rv.report(node, (
                        f"runner reads scenario.{node.attr} but Scenario "
                        f"defines no such field/property — "
                        f"AttributeError on first use"))
            findings.extend(rv.findings)
        return findings
