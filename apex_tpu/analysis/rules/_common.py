"""Helpers shared by the APX rule pack: jit-decorator detection and
traced-value taint propagation.

Several rules only fire *inside jitted code* (concretization, host sync,
mutable-state mutation) — they all need the same answer to "is this
function jit-compiled, and which of its parameters are static?".
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

#: attributes of a traced array that are static under tracing — reading
#: them never concretizes the value.
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                "weak_type", "itemsize"}

#: builtins whose result on a traced argument is static (or that inspect
#: rather than concretize).
STATIC_CALLS = {"len", "isinstance", "type", "id", "repr", "getattr",
                "hasattr"}

JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}

#: transforms that trace their operand — concretization inside any of
#: these is a hazard even without jit (grad/vmap use tracers too), and
#: this repo jits via call form (``jax.jit(shard_map(per_rank, ...))``)
#: far more often than via decorators.
TRACING_WRAPPER_SUFFIXES = (
    ".jit", ".pjit", ".pmap", ".vmap", ".grad", ".value_and_grad",
    ".shard_map", ".checkify",
)
TRACING_WRAPPER_NAMES = {"jit", "pjit", "pmap", "vmap", "grad",
                         "value_and_grad", "shard_map"}


def _is_tracing_wrapper(fname: Optional[str]) -> bool:
    if fname is None:
        return False
    return (fname in TRACING_WRAPPER_NAMES
            or fname.endswith(TRACING_WRAPPER_SUFFIXES))


def _is_jit_name(fname: Optional[str]) -> bool:
    return fname is not None and (
        fname in JIT_NAMES or fname == "jit"
        or fname.endswith((".jit", ".pjit")))


@dataclass
class JitInfo:
    """How a function is jitted: which params are compile-time static."""

    static_names: Set[str] = field(default_factory=set)
    static_nums: Set[int] = field(default_factory=set)
    #: "jit" when compiled (hot path), "traced" for grad/vmap-style
    #: transforms that trace but don't cache compilations
    kind: str = "jit"

    def resolve_static(self, func: ast.FunctionDef) -> Set[str]:
        names = set(self.static_names)
        plist = param_names(func)
        for n in self.static_nums:
            if 0 <= n < len(plist):
                names.add(plist[n])
        return names


def _const_str_seq(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
        return out
    return []


def _const_int_seq(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def param_names(func: ast.FunctionDef) -> List[str]:
    a = func.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if a.vararg:
        names.append(a.vararg.arg)
    names += [p.arg for p in a.kwonlyargs]
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def jit_info(func: ast.FunctionDef, resolve) -> Optional[JitInfo]:
    """Return :class:`JitInfo` if ``func`` carries a jit decorator
    (``@jax.jit``, ``@jit``, ``@jax.jit(...)``, ``@partial(jax.jit, ...)``),
    else None.  ``resolve`` maps a Name/Attribute node to its canonical
    dotted path (``RuleVisitor.resolve``)."""
    for deco in func.decorator_list:
        target = deco
        partial_wrapped = False
        if isinstance(deco, ast.Call):
            fname = resolve(deco.func)
            if fname in ("functools.partial", "partial"):
                if not deco.args:
                    continue
                target = deco.args[0]
                partial_wrapped = True
            else:
                target = deco.func
        name = resolve(target)
        if name not in JIT_NAMES and name != "jit":
            continue
        info = JitInfo()
        if isinstance(deco, ast.Call):
            # positional static args of partial(jax.jit, fn?, ...) never
            # appear in practice; only keywords carry staticness
            _static_kwargs_into(info, deco.keywords)
            del partial_wrapped
        return info
    return None


def expr_names(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class Taint:
    """Forward taint pass over one function body: which local names can
    hold traced values.  Seeds from the non-static parameters, propagates
    through assignments in source order.  Reads through static attributes
    (``x.shape`` etc.) and static builtins (``len``/``isinstance``) do not
    propagate taint."""

    def __init__(self, func: ast.FunctionDef, static: Set[str]):
        self.tainted: Set[str] = {
            n for n in param_names(func)
            if n not in static and n not in ("self", "cls")}
        # taint is monotone; iterate to a fixpoint so chains of
        # assignments resolve regardless of ast.walk's visit order
        for _ in range(8):
            before = len(self.tainted)
            for stmt in ast.walk(func):
                if isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                    value = stmt.value
                    if value is None or not self.is_traced(value):
                        continue
                    targets = (stmt.targets if isinstance(stmt, ast.Assign)
                               else [stmt.target])
                    for t in targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                self.tainted.add(n.id)
                elif isinstance(stmt, ast.For):
                    if self.is_traced(stmt.iter):
                        for n in ast.walk(stmt.target):
                            if isinstance(n, ast.Name):
                                self.tainted.add(n.id)
            if len(self.tainted) == before:
                break

    def is_traced(self, node: ast.AST) -> bool:
        """Can evaluating ``node`` yield a traced value (conservatively)?"""
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.is_traced(node.value)
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in STATIC_CALLS:
                return False
            return any(self.is_traced(a) for a in node.args) or any(
                self.is_traced(k.value) for k in node.keywords) or (
                self.is_traced(fn) if isinstance(fn, ast.Attribute)
                else False)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` inspects pytree structure,
            # not the traced value
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return any(self.is_traced(c)
                       for c in [node.left] + node.comparators)
        if isinstance(node, (ast.BoolOp, ast.BinOp, ast.UnaryOp,
                             ast.IfExp, ast.Subscript, ast.Starred,
                             ast.Tuple, ast.List, ast.Set, ast.Dict,
                             ast.JoinedStr, ast.FormattedValue)):
            return any(self.is_traced(c) for c in ast.iter_child_nodes(node)
                       if isinstance(c, ast.expr))
        return False


def walk_functions(tree: ast.AST):
    """Yield every FunctionDef/AsyncFunctionDef in the module."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _static_kwargs_into(info: JitInfo, keywords) -> None:
    for kw in keywords:
        if kw.arg in ("static_argnames",):
            info.static_names |= set(_const_str_seq(kw.value))
        elif kw.arg in ("static_argnums", "static_broadcasted_argnums"):
            info.static_nums |= set(_const_int_seq(kw.value))


def traced_functions(tree: ast.AST, resolve) -> Dict[ast.AST, JitInfo]:
    """Every function in the module that runs under a tracing transform,
    with its staticness info.  Catches both the decorator form
    (``@jax.jit``) and the call form this repo favors —
    ``jax.jit(shard_map(per_rank, ...), ...)`` / ``jax.grad(loss_fn)`` —
    by resolving the first positional argument back to a local def
    (unwrapping one nested wrapper level)."""
    defs: Dict[str, ast.AST] = {}
    for func in walk_functions(tree):
        defs[func.name] = func
    out: Dict[ast.AST, JitInfo] = {}
    for func in walk_functions(tree):
        info = jit_info(func, resolve)
        if info is not None:
            out[func] = info
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fname = resolve(node.func)
        if not _is_tracing_wrapper(fname):
            continue
        target = node.args[0]
        if isinstance(target, ast.Call) and target.args and \
                _is_tracing_wrapper(resolve(target.func)):
            # jax.jit(shard_map(per_rank, ...)): the inner function is
            # what traces; jit staticness still comes from the outer call
            target = target.args[0]
        if not isinstance(target, ast.Name) or target.id not in defs:
            continue
        func = defs[target.id]
        info = out.get(func)
        if info is None:
            info = JitInfo(kind="traced")
            out[func] = info
        if _is_jit_name(fname) or fname.endswith(".pmap") or \
                fname == "pmap":
            info.kind = "jit"
            _static_kwargs_into(info, node.keywords)
    return out
