"""APX013 — incident counter maps drifting from the flight-recorder
trigger table.

``observability/report.py``'s ``*_INCIDENT_COUNTERS`` maps are the
monitor's reconcile contract: every key is an incident-class event the
report counts key-for-key against a registry counter.  The
:class:`~apex_tpu.observability.recorder.FlightRecorder` promises a
postmortem bundle for exactly that class of event
(``recorder.TRIGGER_EVENTS``).  An incident the monitor reconciles but
the recorder sleeps through is the failure mode this rule exists for: a
new subsystem adds ``foo_melted`` to an incident map, the report
dutifully counts it, and the first real meltdown leaves no bundle —
the evidence the counter was supposed to guarantee.

Detection: in ``observability/report.py``, every constant string key of
a top-level ``NAME = {...}`` assignment where ``NAME`` ends with
``_INCIDENT_COUNTERS`` must be a member of the runtime
``TRIGGER_EVENTS`` frozenset (imported from the installed
``apex_tpu.observability.recorder`` — pure stdlib, safe at lint time).
The recorder builds ``TRIGGER_EVENTS`` from those same maps by
construction, so the real tree is clean by definition; the rule
catches a map edited in a checkout that bypasses the recorder import
(or a trigger table someone hand-pruned).  The inverse direction is
deliberately allowed: recorder-only extras like ``retrace`` trigger
bundles without a strict counter pairing.
"""

from __future__ import annotations

import ast
from typing import List

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor

_MAP_SUFFIX = "_INCIDENT_COUNTERS"


def _scoped(path: str) -> bool:
    return ("/" + path.replace("\\", "/")).endswith(
        "/observability/report.py")


def _trigger_events() -> frozenset:
    from apex_tpu.observability.recorder import TRIGGER_EVENTS
    return TRIGGER_EVENTS


class APX013TriggerTable(Rule):
    code = "APX013"
    name = "trigger-table"
    description = ("*_INCIDENT_COUNTERS event missing from the flight "
                   "recorder's TRIGGER_EVENTS — incidents the monitor "
                   "reconciles must dump a postmortem bundle")

    def check(self, module: ModuleContext) -> List[Finding]:
        if not _scoped(module.path):
            return []
        triggers = _trigger_events()
        v = RuleVisitor(self, module)
        for node in module.tree.body:
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id.endswith(_MAP_SUFFIX)
                    and isinstance(node.value, ast.Dict)):
                continue
            map_name = node.targets[0].id
            for key in node.value.keys:
                if not (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)):
                    continue
                if key.value not in triggers:
                    v.report(key, (
                        f'incident event "{key.value}" ({map_name}) is '
                        f"not a FlightRecorder trigger — add it to the "
                        f"recorder's trigger table so the incident the "
                        f"monitor reconciles also leaves a postmortem "
                        f"bundle"))
        return v.findings
