"""APX006 — dtype discipline in bf16 paths.

Two shapes of silent precision drift:

1. **chained round-trip casts** — ``x.astype(jnp.float32).astype(
   jnp.bfloat16)`` destroys information while looking like a no-op; the
   inner cast is either redundant or hiding a computation that should
   have declared its precision explicitly.
2. **fp32 constructions inside bf16 functions** — a function that casts
   activations to ``bfloat16`` but also materializes ``float32`` buffers
   mid-path usually has an accidental upcast (the PR 1 sparsity
   permutation search noise-floor bug was exactly an unintended fp32/bf16
   mismatch).  Deliberate fp32 accumulators are fine — baseline them with
   a justification, which documents the policy decision in-tree.

Detection: (a) any ``.astype(A).astype(B)`` chain with distinct float
dtypes; (b) within a function that casts to bfloat16, ``astype(jnp.
float32)`` casts and ``dtype=jnp.float32`` construction keywords.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor
from apex_tpu.analysis.rules._common import walk_functions

_FLOAT_DTYPES = {"float32", "float16", "bfloat16", "float64", "float8_e4m3fn",
                 "float8_e5m2"}


def _dtype_name(node: ast.AST) -> Optional[str]:
    """'float32' for jnp.float32 / np.float32 / 'float32' literals."""
    if isinstance(node, ast.Attribute) and node.attr in _FLOAT_DTYPES:
        return node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str) and \
            node.value in _FLOAT_DTYPES:
        return node.value
    return None


class APX006DtypeDiscipline(Rule):
    code = "APX006"
    name = "bf16-dtype-drift"
    description = ("float32 casts/constructions inside bf16-policy "
                   "functions, or information-destroying chained astype "
                   "round-trips")

    def check(self, module: ModuleContext) -> List[Finding]:
        v = RuleVisitor(self, module)
        # (a) chained .astype(A).astype(B), A != B, both floats
        chain_inner = set()  # inner Call nodes of chains, skipped in (b)
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype" and node.args):
                continue
            outer = _dtype_name(node.args[0])
            inner_call = node.func.value
            if (outer and isinstance(inner_call, ast.Call)
                    and isinstance(inner_call.func, ast.Attribute)
                    and inner_call.func.attr == "astype"
                    and inner_call.args):
                inner = _dtype_name(inner_call.args[0])
                if inner and inner != outer:
                    chain_inner.add(inner_call)
                    v.report(node, (
                        f"chained `.astype({inner}).astype({outer})` — "
                        f"the round-trip destroys precision silently; "
                        f"cast once to the intended dtype"))
        # (b) fp32 constructions in functions that also cast to bf16
        for func in walk_functions(module.tree):
            if not self._casts_to_bf16(func):
                continue
            for node in ast.walk(func):
                if isinstance(node, ast.Call):
                    if (isinstance(node.func, ast.Attribute)
                            and node.func.attr == "astype" and node.args
                            and _dtype_name(node.args[0]) == "float32"
                            and node not in chain_inner):
                        v.report(node, (
                            f"`.astype(float32)` inside bf16-policy "
                            f"function '{func.name}' — deliberate fp32 "
                            f"accumulation should be baselined with a "
                            f"justification"))
                        continue
                    for kw in node.keywords:
                        if kw.arg == "dtype" and _dtype_name(
                                kw.value) == "float32":
                            v.report(node, (
                                f"`dtype=float32` construction inside "
                                f"bf16-policy function '{func.name}' — "
                                f"unintended upcast, or an fp32 "
                                f"accumulator worth a baseline entry"))
        return v.findings

    @staticmethod
    def _casts_to_bf16(func: ast.AST) -> bool:
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "astype" and node.args
                        and _dtype_name(node.args[0]) == "bfloat16"):
                    return True
                for kw in node.keywords:
                    if kw.arg == "dtype" and _dtype_name(
                            kw.value) == "bfloat16":
                        return True
        return False
