"""APX003 — blocking host sync in training-step bodies.

``.block_until_ready()`` / ``jax.block_until_ready()`` /
``jax.device_get()`` inside the per-step path serializes the host against
the device every step: the dispatch pipeline drains, and TPU utilization
falls off a cliff (this is why ``resilience.run_training`` polls metrics
in batches off the critical path instead of syncing per step).  Blocking
belongs at poll boundaries, timers, and test assertions — never in the
step function.

Detection: functions that look like step bodies — name contains ``step``
as a word segment, or the function is jit-decorated (a jitted function IS
the hot path) — containing a blocking call.
"""

from __future__ import annotations

import ast
import re
from typing import List

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor
from apex_tpu.analysis.rules._common import traced_functions, walk_functions

_STEP_NAME = re.compile(r"(^|_)step(_|$)|(^|_)per_rank(_|$)")
_BLOCKING_FUNCS = {"jax.block_until_ready", "jax.device_get"}


class APX003HostSync(Rule):
    code = "APX003"
    name = "host-sync-in-step"
    description = ("block_until_ready()/jax.device_get() inside a "
                   "training-step body serializes host and device every "
                   "step")

    def check(self, module: ModuleContext) -> List[Finding]:
        v = RuleVisitor(self, module)
        compiled = {f for f, info in traced_functions(
            module.tree, v.resolve).items() if info.kind == "jit"}
        for func in walk_functions(module.tree):
            # test bodies assert on host values by design
            if func.name.startswith("test_"):
                continue
            is_step = bool(_STEP_NAME.search(func.name))
            if not is_step and func not in compiled:
                continue
            where = (f"step body '{func.name}'" if is_step
                     else f"jitted function '{func.name}'")
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if (isinstance(fn, ast.Attribute)
                        and fn.attr == "block_until_ready"):
                    v.report(node, (
                        f"`.block_until_ready()` in {where} — move the "
                        f"sync to a poll boundary outside the hot loop"))
                    continue
                fname = v.resolve(fn)
                if fname in _BLOCKING_FUNCS:
                    short = fname.split(".", 1)[1]
                    v.report(node, (
                        f"`jax.{short}()` in {where} — batch device reads "
                        f"off the critical path instead"))
        return v.findings
