"""APX002 — concretization / host sync inside jit-decorated functions.

``float(x)`` / ``int(x)`` / ``bool(x)`` / ``np.asarray(x)`` / ``x.item()``
on a traced value either raises ``TracerBoolConversionError`` at trace
time or — worse, under ``io_callback``-style escapes — silently forces a
device→host transfer per step.  ``if``/``while`` on a traced value is the
same hazard spelled as control flow (the fix is ``lax.cond`` /
``jnp.where`` or marking the argument static).

Detection: for each jit-decorated function, run a forward taint pass
seeded from the non-static parameters (reads through ``.shape`` /
``.ndim`` / ``.dtype`` / ``len()`` / ``is None`` stay untainted — those
are static under tracing), then flag concretizing builtins, numpy
materializations, ``.item()`` / ``.tolist()``, and ``if``/``while`` tests
over tainted values.
"""

from __future__ import annotations

import ast
from typing import List

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor
from apex_tpu.analysis.rules._common import Taint, traced_functions

_CONCRETIZING_BUILTINS = {"float", "int", "bool", "complex"}
_NUMPY_MATERIALIZERS = {"numpy.asarray", "numpy.array", "numpy.asanyarray",
                        "numpy.ascontiguousarray"}
_HOST_METHODS = {"item", "tolist", "__array__"}


class APX002Concretization(Rule):
    code = "APX002"
    name = "concretization-in-jit"
    description = ("float()/int()/bool()/np.asarray()/.item() or Python "
                   "control flow on a traced value inside a jitted "
                   "function")

    def check(self, module: ModuleContext) -> List[Finding]:
        v = RuleVisitor(self, module)
        for func, info in traced_functions(module.tree, v.resolve).items():
            taint = Taint(func, info.resolve_static(func))
            nested = set()
            for sub in ast.walk(func):
                if sub is not func and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for inner in ast.walk(sub):
                        if inner is not sub:
                            nested.add(inner)
            for node in ast.walk(func):
                if node in nested:
                    continue  # nested defs are judged in their own right
                if isinstance(node, ast.Call):
                    self._check_call(v, node, taint)
                elif isinstance(node, (ast.If, ast.While)):
                    if taint.is_traced(node.test):
                        kind = ("if" if isinstance(node, ast.If)
                                else "while")
                        v.report(node, (
                            f"`{kind}` on a traced value inside traced "
                            f"function '{func.name}' — use lax.cond/"
                            f"jnp.where or mark the argument static"))
        return v.findings

    @staticmethod
    def _check_call(v: RuleVisitor, node: ast.Call, taint: Taint) -> None:
        fn = node.func
        if (isinstance(fn, ast.Name)
                and fn.id in _CONCRETIZING_BUILTINS
                and node.args and taint.is_traced(node.args[0])):
            v.report(node, (
                f"`{fn.id}()` concretizes a traced value inside a jitted "
                f"function — keep it on device or mark the argument "
                f"static"))
            return
        fname = v.resolve(fn)
        if fname in _NUMPY_MATERIALIZERS and node.args and taint.is_traced(
                node.args[0]):
            v.report(node, (
                f"`{fname.replace('numpy', 'np')}()` materializes a traced "
                f"value to host numpy inside a jitted function"))
            return
        if (isinstance(fn, ast.Attribute) and fn.attr in _HOST_METHODS
                and taint.is_traced(fn.value)):
            v.report(node, (
                f"`.{fn.attr}()` forces a device→host sync on a traced "
                f"value inside a jitted function"))
