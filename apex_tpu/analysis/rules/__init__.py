"""The APX rule pack.

Each module contributes one :class:`~apex_tpu.analysis.engine.Rule`;
:func:`all_rules` instantiates the full pack in code order.  Adding a rule
= adding a module here and listing its class below — the engine, CLI,
baseline and gate pick it up automatically.
"""

from __future__ import annotations

from typing import List

from apex_tpu.analysis.engine import Rule
from apex_tpu.analysis.rules.apx001_prng_reuse import APX001PrngReuse
from apex_tpu.analysis.rules.apx002_concretization import APX002Concretization
from apex_tpu.analysis.rules.apx003_host_sync import APX003HostSync
from apex_tpu.analysis.rules.apx004_recompile import APX004Recompile
from apex_tpu.analysis.rules.apx005_collectives import APX005Collectives
from apex_tpu.analysis.rules.apx006_dtype import APX006DtypeDiscipline
from apex_tpu.analysis.rules.apx007_pallas_scan import APX007PallasScan
from apex_tpu.analysis.rules.apx008_mutable_state import APX008MutableState
from apex_tpu.analysis.rules.apx009_record_contract import (
    APX009RecordContract,
)
from apex_tpu.analysis.rules.apx010_scenario_schema import (
    APX010ScenarioSchema,
)
from apex_tpu.analysis.rules.apx011_wall_clock import APX011WallClock
from apex_tpu.analysis.rules.apx012_counter_bypass import APX012CounterBypass
from apex_tpu.analysis.rules.apx013_trigger_table import APX013TriggerTable

_RULE_CLASSES = [
    APX001PrngReuse,
    APX002Concretization,
    APX003HostSync,
    APX004Recompile,
    APX005Collectives,
    APX006DtypeDiscipline,
    APX007PallasScan,
    APX008MutableState,
    APX009RecordContract,
    APX010ScenarioSchema,
    APX011WallClock,
    APX012CounterBypass,
    APX013TriggerTable,
]

__all__ = ["all_rules"] + [c.__name__ for c in _RULE_CLASSES]


def all_rules() -> List[Rule]:
    return [cls() for cls in _RULE_CLASSES]
