"""APX012 — fleet incident counters mutated without their typed event.

The fleet's incident/action counters (``replica_drains``,
``deploys_rolled_back``, ...) are one half of a pair: every increment
is supposed to ride the typed-record emit helper that also writes the
matching ``.event(...)`` record, so counters and event streams
reconcile key-for-key (the model checker's ``counter_reconcile``
invariant enforces exactly this at runtime).  A bare ``.inc(...)`` of
one of these counters with no event in the same function is a bypass:
the counter drifts ahead of the record stream and every downstream
audit (build_report, the mc invariant, dashboards) disagrees about how
many incidents happened.

Detection: in ``serving/`` modules, a call ``*.inc("<counter>")`` with
the constant naming one of the paired fleet counters, inside a function
that never calls ``.event(...)``.  High-frequency counters that are
deliberately unpaired (``fleet_dispatches``, per-replica dispatch
counts) are not in the set.
"""

from __future__ import annotations

import ast
from typing import List

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor
from apex_tpu.analysis.rules._common import walk_functions

#: counters contractually paired with a same-name event record — keep in
#: sync with apex_tpu.analysis.mc.invariants.COUNTER_EVENTS
_PAIRED_COUNTERS = frozenset({
    "replica_drains", "replica_rebuilds", "requests_migrated",
    "replica_scale_ups", "replica_scale_downs",
    "deploys_started", "deploys_completed", "deploys_rolled_back",
    "deploys_rejected", "canary_promotions",
    "requests_preempted", "requests_resumed",
    "requests_deferred_quota",
    "brownouts_escalated", "brownouts_recovered",
})


def _scoped(path: str) -> bool:
    return "/serving/" in "/" + path.replace("\\", "/")


class APX012CounterBypass(Rule):
    code = "APX012"
    name = "counter-bypass"
    description = ("paired fleet counter inc'd outside a typed-record "
                   "emit helper (no co-sited .event call)")

    def check(self, module: ModuleContext) -> List[Finding]:
        if not _scoped(module.path):
            return []
        v = RuleVisitor(self, module)
        for func in walk_functions(module.tree):
            incs = []
            has_event = False
            for node in ast.walk(func):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                if node.func.attr == "event":
                    has_event = True
                elif (node.func.attr == "inc" and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and node.args[0].value in _PAIRED_COUNTERS):
                    incs.append(node)
            if has_event:
                continue
            for node in incs:
                counter = node.args[0].value
                v.report(node, (
                    f"`{counter}` inc'd with no `.event(...)` in "
                    f"'{func.name}' — route the increment through the "
                    f"typed-record helper so counters and event streams "
                    f"reconcile"))
        return v.findings
