"""APX005 — collective axis-name discipline.

``lax.psum(x, 'data')`` outside a ``shard_map``/``pmap`` binding ``'data'``
raises ``NameError: unbound axis name`` — but only on the code path that
actually executes the collective, which on a single-host dev box is often
never.  The cross-replica weight-update sharding literature (PAPERS.md)
identifies axis-name/collective discipline as where distributed JAX code
silently goes wrong: the string is a free variable checked only at trace
time under a live mesh.

Detection (single file): collect every axis name the file *binds* — mesh
constructions (``Mesh(devices, ('data', 'model'))``), ``axis_name=`` /
``axis_names=`` keywords (pmap/shard_map/psum-wrapper style), and
``PartitionSpec`` string literals — then flag collectives whose
string-literal axis argument names none of them.  Axis names passed as
variables/constants (``DATA_AXIS``) resolve across files and are skipped.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor

#: canonical collective -> positional index of the axis-name argument
_COLLECTIVES = {
    "jax.lax.psum": 1,
    "jax.lax.pmean": 1,
    "jax.lax.pmax": 1,
    "jax.lax.pmin": 1,
    "jax.lax.psum_scatter": 1,
    "jax.lax.all_gather": 1,
    "jax.lax.all_to_all": 1,
    "jax.lax.ppermute": 1,
    "jax.lax.pshuffle": 1,
    "jax.lax.axis_index": 0,
    "jax.lax.axis_size": 0,
}

_SPEC_CTORS = {"jax.sharding.PartitionSpec", "PartitionSpec",
               "jax.experimental.pjit.PartitionSpec"}


def _literal_axes(node: ast.AST) -> List[str]:
    """String-literal axis names in an expression (str or tuple/list of)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
        return out
    return []


class APX005Collectives(Rule):
    code = "APX005"
    name = "unbound-collective-axis"
    description = ("lax collective names a string-literal axis bound "
                   "nowhere in the file (no mesh/shard_map/pmap/"
                   "PartitionSpec mentions it)")

    def check(self, module: ModuleContext) -> List[Finding]:
        v = RuleVisitor(self, module)
        bound = self._bound_axes(module, v)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = v.resolve(node.func)
            if fname is None:
                continue
            idx = _COLLECTIVES.get(fname)
            if idx is None:
                continue
            axis_expr: Optional[ast.AST] = None
            if len(node.args) > idx:
                axis_expr = node.args[idx]
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis_names"):
                    axis_expr = kw.value
            if axis_expr is None:
                continue
            for axis in _literal_axes(axis_expr):
                if axis not in bound:
                    v.report(node, (
                        f"collective `{fname.rsplit('.', 1)[1]}` names "
                        f"axis '{axis}' but no mesh/shard_map/pmap/"
                        f"PartitionSpec in this file binds it — unbound "
                        f"axis names fail only when the collective "
                        f"actually traces under a mesh"))
        return v.findings

    @staticmethod
    def _bound_axes(module: ModuleContext, v: RuleVisitor) -> Set[str]:
        bound: Set[str] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = v.resolve(node.func) or ""
            # Mesh(devices, ('data', 'model')) / Mesh(..., axis_names=...)
            if fname.endswith("Mesh") or "mesh" in fname.rsplit(
                    ".", 1)[-1].lower():
                for arg in list(node.args[1:]) + [
                        kw.value for kw in node.keywords
                        if kw.arg == "axis_names"]:
                    bound.update(_literal_axes(arg))
            # any axis_name(s)= keyword anywhere binds/forwards an axis:
            # pmap, shard_map, and this repo's psum-wrapper helpers
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis_names", "data_axes",
                              "axis"):
                    bound.update(_literal_axes(kw.value))
            # PartitionSpec('data', ...) names mesh axes by construction
            if fname in _SPEC_CTORS or fname.endswith("PartitionSpec"):
                for arg in node.args:
                    bound.update(_literal_axes(arg))
        return bound
