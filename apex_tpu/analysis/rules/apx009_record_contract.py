"""APX009 — typed-record contract drift (cross-file).

Every structured record the serving stack emits (``emit_record`` with a
``"kind": "<name>"`` payload) is a three-party contract: the emit site
increments a counter alongside it (so cheap counter dashboards and the
full record stream cannot silently diverge), and
``observability/report.py`` knows the kind (so ``build_report``
reconciles it instead of dropping it on the floor).  A record emitted
without its counter — or a kind ``build_report`` has never heard of —
is how a new subsystem ships telemetry nobody can audit.

Detection (project-wide pass): for each ``emit_record(...)`` call
outside the observability/analysis planes whose payload is a dict
literal carrying a constant ``"kind"`` (directly, or via a local
variable assigned a dict literal in the same function):

- the emitting module must also call ``.inc(...)`` somewhere (the
  co-sited counter half of the contract — module scope, because
  well-factored emitters split the record and the counter across
  sibling helpers like ``deploy.py``'s ``_record``/``_incident``);
- the kind string must appear in ``observability/report.py`` when that
  file is part of the analyzed project (the reconcile half).

Calls whose payload is not a dict literal (``result.record(...)`` —
the typed ``RequestResult`` path) are reconciled by construction and
skipped.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor
from apex_tpu.analysis.rules._common import walk_functions

#: modules that ARE the metrics/analysis plane — the contract's
#: consumers, not its emitters
_EXEMPT_PARTS = ("observability", "analysis", "tests")


def _in_tree(path: str, part: str) -> bool:
    return f"/{part}/" in "/" + path.replace("\\", "/")


def _exempt(path: str) -> bool:
    norm = path.replace("\\", "/")
    if norm.rsplit("/", 1)[-1].startswith("test_"):
        return True
    return any(_in_tree(norm, part) for part in _EXEMPT_PARTS)


def _dict_kind(node: ast.AST) -> Optional[str]:
    """The constant ``"kind"`` value of a dict literal, if any."""
    if not isinstance(node, ast.Dict):
        return None
    for key, value in zip(node.keys, node.values):
        if (isinstance(key, ast.Constant) and key.value == "kind"
                and isinstance(value, ast.Constant)
                and isinstance(value.value, str)):
            return value.value
    return None


def _enclosing(func_spans, node: ast.AST):
    """Innermost function whose span contains ``node`` (None = module)."""
    line = getattr(node, "lineno", 0)
    best = None
    for func, start, end in func_spans:
        if start <= line <= end and (
                best is None or start >= best[1]):
            best = (func, start, end)
    return best[0] if best else None


def _resolve_kind(call: ast.Call, scope: Optional[ast.AST],
                  module_tree: ast.AST) -> Optional[str]:
    """The record kind flowing into ``emit_record`` — from the argument
    dict literal, or from the nearest preceding assignment when the
    argument is a bare name."""
    if not call.args:
        return None
    arg = call.args[0]
    kind = _dict_kind(arg)
    if kind is not None:
        return kind
    if not isinstance(arg, ast.Name):
        return None
    body = scope if scope is not None else module_tree
    kind = None
    for node in ast.walk(body):
        if (isinstance(node, ast.Assign)
                and node.lineno < call.lineno
                and any(isinstance(t, ast.Name) and t.id == arg.id
                        for t in node.targets)):
            kind = _dict_kind(node.value)
    return kind


class APX009RecordContract(Rule):
    code = "APX009"
    name = "record-contract"
    description = ("emit_record(kind=...) sites need a co-sited counter "
                   "inc and a build_report reconcile arm for the kind")
    project = True

    def check_project(self, modules: Sequence[ModuleContext]
                      ) -> List[Finding]:
        report_kinds: Optional[set] = None
        for m in modules:
            if m.path.replace("\\", "/").endswith(
                    "observability/report.py"):
                report_kinds = {n.value for n in ast.walk(m.tree)
                                if isinstance(n, ast.Constant)
                                and isinstance(n.value, str)}
        findings: List[Finding] = []
        for m in modules:
            if _exempt(m.path):
                continue
            v = RuleVisitor(self, m)
            spans = [(f, f.lineno, getattr(f, "end_lineno", f.lineno))
                     for f in walk_functions(m.tree)]
            module_has_inc = any(
                isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr == "inc"
                for c in ast.walk(m.tree))
            for node in ast.walk(m.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "emit_record"):
                    continue
                scope = _enclosing(spans, node)
                kind = _resolve_kind(node, scope, m.tree)
                if kind is None:
                    continue
                if not module_has_inc:
                    v.report(node, (
                        f'kind="{kind}" record emitted with no co-sited '
                        f"counter — `.inc(...)` the matching counter in "
                        f"the emitting module so dashboards and the "
                        f"record stream cannot diverge"))
                if report_kinds is not None and kind not in report_kinds:
                    v.report(node, (
                        f'record kind "{kind}" is unknown to '
                        f"observability/report.py — add a build_report "
                        f"reconcile arm or the records are emitted into "
                        f"a void"))
            findings.extend(v.findings)
        return findings
