"""APX001 — PRNG key reuse.

Feeding the same key object to two ``jax.random.*`` sampling calls draws
*correlated* streams — the exact bug behind PR 1's structurally-duplicated
packed-attention dropout seeds, and the classic silent JAX correctness
trap: nothing crashes, the statistics are just wrong.  Every consumed key
must come from ``split`` / ``fold_in`` of a fresh parent.

Detection: within one function scope, find a key name passed as the first
argument to two sampling calls with no intervening reassignment of that
name (``k = jax.random.split(k)`` / ``fold_in`` / any rebind kills the
taint).  Nested function scopes are analyzed independently.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor

#: jax.random functions that do NOT consume their key argument's
#: statistical budget (constructors and derivers).
_NON_CONSUMING = {
    "PRNGKey", "key", "split", "fold_in", "wrap_key_data", "key_data",
    "clone", "key_impl",
}


class APX001PrngReuse(Rule):
    code = "APX001"
    name = "prng-key-reuse"
    description = ("same PRNG key fed to two jax.random sampling calls "
                   "with no split/fold_in between")

    def check(self, module: ModuleContext) -> List[Finding]:
        v = _Visitor(self, module)
        v.scan(module.tree, "<module>")
        return v.findings


class _Visitor(RuleVisitor):
    def scan(self, scope: ast.AST, scope_name: str) -> None:
        """Analyze one scope's direct statements; recurse into nested
        function scopes separately (a closure capturing a key is its own
        stream discipline problem, judged in its own scope)."""
        uses: Dict[str, List[Tuple[int, str]]] = {}
        kills: Dict[str, List[int]] = {}
        nested: List[ast.AST] = []

        for node in ast.walk(scope):
            if node is not scope and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
                nested.append(node)
        nested_set = set()
        for fn in nested:
            for sub in ast.walk(fn):
                if sub is not fn:
                    nested_set.add(sub)
        # a sampling call whose key is the comprehension's own loop
        # variable draws a fresh key per element — not a reuse; those
        # calls are judged by the dedicated comprehension check below
        comp_bound_calls = set()
        for node in ast.walk(scope):
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                 ast.GeneratorExp)):
                bound = {n.id for g in node.generators
                         for n in ast.walk(g.target)
                         if isinstance(n, ast.Name)}
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Call) and sub.args
                            and isinstance(sub.args[0], ast.Name)
                            and sub.args[0].id in bound):
                        comp_bound_calls.add(sub)

        # only consider nodes belonging to THIS scope
        for node in ast.walk(scope):
            if node in nested_set or node is scope:
                continue
            if isinstance(node, ast.Call) and node not in comp_bound_calls:
                fname = self.resolve(node.func)
                if (fname and fname.startswith("jax.random.")
                        and fname.rsplit(".", 1)[1] not in _NON_CONSUMING
                        and node.args
                        and isinstance(node.args[0], ast.Name)):
                    key = node.args[0].id
                    uses.setdefault(key, []).append(
                        (node.lineno, fname.rsplit(".", 1)[1]))
            for tgt in self._assign_targets(node):
                kills.setdefault(tgt, []).append(node.lineno)

        for key, key_uses in uses.items():
            key_uses.sort()
            key_kills = sorted(kills.get(key, []))
            for (l1, f1), (l2, f2) in zip(key_uses, key_uses[1:]):
                if not any(l1 < k <= l2 for k in key_kills):
                    self.findings.append(Finding(
                        self.rule.code,
                        f"PRNG key '{key}' consumed by jax.random.{f2} "
                        f"at line {l2} was already consumed by "
                        f"jax.random.{f1} at line {l1} with no "
                        f"split/fold_in between — correlated streams",
                        self.module.path, l2, 0, self.module.snippet(l2)))

        # a single consuming call lexically inside a loop (or a
        # comprehension) reuses the key every iteration — the PR 1
        # duplicated-dropout-seed shape — unless the loop body rebinds it
        for node in ast.walk(scope):
            if node in nested_set or node is scope:
                continue
            if isinstance(node, (ast.For, ast.While)):
                span = (node.lineno, getattr(node, "end_lineno",
                                             node.lineno))
                for key, key_uses in uses.items():
                    in_loop = [u for u in key_uses
                               if span[0] <= u[0] <= span[1]]
                    # multi-use reuse is already caught by the pair check
                    if len(in_loop) != 1:
                        continue
                    if any(span[0] <= k <= span[1]
                           for k in kills.get(key, [])):
                        continue
                    l, f = in_loop[0]
                    self.findings.append(Finding(
                        self.rule.code,
                        f"PRNG key '{key}' consumed by jax.random.{f} "
                        f"inside a loop without a per-iteration "
                        f"split/fold_in — every iteration draws the same "
                        f"stream",
                        self.module.path, l, 0, self.module.snippet(l)))
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                bound = {n.id for g in node.generators
                         for n in ast.walk(g.target)
                         if isinstance(n, ast.Name)}
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        fname = self.resolve(sub.func)
                        if (fname and fname.startswith("jax.random.")
                                and fname.rsplit(".", 1)[1]
                                not in _NON_CONSUMING
                                and sub.args
                                and isinstance(sub.args[0], ast.Name)
                                and sub.args[0].id not in bound):
                            self.findings.append(Finding(
                                self.rule.code,
                                f"PRNG key '{sub.args[0].id}' consumed by "
                                f"jax.random.{fname.rsplit('.', 1)[1]} "
                                f"inside a comprehension — every element "
                                f"draws the same stream",
                                self.module.path, sub.lineno, 0,
                                self.module.snippet(sub.lineno)))

        for fn in nested:
            self.scan(fn, getattr(fn, "name", "<lambda>"))

    @staticmethod
    def _assign_targets(node: ast.AST) -> List[str]:
        out: List[str] = []
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        out.append(n.id)
        elif isinstance(node, ast.For):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    out.append(n.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # parameters rebind names inside nested scopes; handled there
            pass
        return out
