"""APX008 — module-level mutable state mutated from jitted code.

A jitted function runs its Python body ONCE per abstract signature; a
mutation of module-level state inside it (``_CACHE[key] = ...``,
``STATS.append(...)``, ``global counter``) executes at trace time, not at
run time.  The state then silently stops updating after the first call —
or worse, updates exactly once per retrace, turning a recompile storm
into corrupted bookkeeping.  Side state belongs outside jit (host
callbacks, returned metrics, or the functional carry).

Detection: module-level names bound to mutable containers (dict/list/set
displays or constructor calls), then — inside jit-decorated functions —
``global`` declarations, subscript stores, ``del`` statements, and
mutating method calls (``append``/``update``/``setdefault``/...) on
those names.
"""

from __future__ import annotations

import ast
from typing import List, Set

from apex_tpu.analysis.engine import Finding, ModuleContext, Rule, RuleVisitor
from apex_tpu.analysis.rules._common import traced_functions

_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "remove", "discard", "clear", "sort",
             "reverse", "appendleft", "extendleft"}
_MUTABLE_CTORS = {"list", "dict", "set", "bytearray", "deque",
                  "defaultdict", "Counter", "OrderedDict"}


def _module_mutables(tree: ast.Module) -> Set[str]:
    names: Set[str] = set()
    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        mutable = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp))
        if isinstance(value, ast.Call):
            fn = value.func
            ctor = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute) else "")
            mutable = ctor in _MUTABLE_CTORS
        if not mutable:
            continue
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    names.add(n.id)
    return names


class APX008MutableState(Rule):
    code = "APX008"
    name = "mutable-state-in-jit"
    description = ("module-level mutable state mutated inside a jitted "
                   "function executes at trace time, not run time")

    def check(self, module: ModuleContext) -> List[Finding]:
        v = RuleVisitor(self, module)
        mutables = _module_mutables(module.tree)
        if not mutables:
            return []
        for func in traced_functions(module.tree, v.resolve):
            # a local rebinding shadows the module global — drop those
            shadowed = set()
            for sub in ast.walk(func):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            shadowed.add(t.id)
            live = mutables - shadowed
            for sub in ast.walk(func):
                if isinstance(sub, ast.Global):
                    for name in sub.names:
                        if name in mutables:
                            v.report(sub, self._msg(name, func.name,
                                                    "rebinds"))
                elif isinstance(sub, (ast.Assign, ast.AugAssign)):
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for t in targets:
                        if (isinstance(t, ast.Subscript)
                                and isinstance(t.value, ast.Name)
                                and t.value.id in live):
                            v.report(sub, self._msg(t.value.id, func.name,
                                                    "stores into"))
                elif isinstance(sub, ast.Delete):
                    for t in sub.targets:
                        if (isinstance(t, ast.Subscript)
                                and isinstance(t.value, ast.Name)
                                and t.value.id in live):
                            v.report(sub, self._msg(t.value.id, func.name,
                                                    "deletes from"))
                elif isinstance(sub, ast.Call):
                    fn = sub.func
                    if (isinstance(fn, ast.Attribute)
                            and fn.attr in _MUTATORS
                            and isinstance(fn.value, ast.Name)
                            and fn.value.id in live):
                        v.report(sub, self._msg(fn.value.id, func.name,
                                                f"calls .{fn.attr}() on"))
        return v.findings

    @staticmethod
    def _msg(name: str, func: str, verb: str) -> str:
        return (f"jitted '{func}' {verb} module-level mutable '{name}' — "
                f"this executes at trace time only; return the value or "
                f"use a host callback instead")
