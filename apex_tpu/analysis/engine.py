"""AST rule engine for JAX/TPU hazard linting.

The classes of bug that threaten a production jax_graft stack are not
generic Python bugs — they are JAX-specific hazards this repo has already
paid for in postmortems: reused PRNG keys (structurally-duplicated dropout
seeds, PR 1), host-sync inside jitted step functions, silent recompilation
storms (the tier-1 gate truncation, PR 1), dtype drift in bf16 paths, and
collectives naming unbound mesh axes.  TorchTitan-style production trainers
machine-check these invariants around the hot loop; this engine does the
same for the whole tree, statically.

Architecture:

- :class:`Rule` — one hazard detector, identified by an ``APX###`` code.
  Rules subclass :class:`RuleVisitor` (an ``ast.NodeVisitor`` with a
  ``report`` helper and a resolved-import map) and register themselves in
  ``apex_tpu.analysis.rules``.
- :class:`ModuleContext` — one parsed source file handed to every rule:
  tree, source lines, path, and the canonical-import resolver
  (:func:`build_import_map` / :func:`resolve_call`), so ``jr.normal`` and
  ``jax.random.normal`` look identical to every rule.
- suppression — a ``# noqa: APX###`` comment on the finding's line (or a
  bare ``# noqa``) silences it; a committed JSON **baseline** records
  pre-existing / deliberate findings (keyed by ``path + code + source
  snippet`` so line drift doesn't invalidate entries), each with a
  one-line justification.
- config — ``[tool.apex_tpu.analysis]`` in pyproject.toml (``paths``,
  ``baseline``, ``exclude``, ``select``, ``disable``).  Python 3.10 has no
  ``tomllib``; :func:`_read_toml_table` parses the one flat table this
  engine needs.
- CLI — ``python -m apex_tpu.analysis [paths ...]``; exit 0 when every
  finding is suppressed or baselined, 1 otherwise.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "AnalysisConfig",
    "Baseline",
    "Finding",
    "ModuleContext",
    "Rule",
    "RuleVisitor",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "build_import_map",
    "load_config",
    "main",
    "resolve_call",
]


# --------------------------------------------------------------------------
# findings
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class Finding:
    """One rule hit: ``code`` at ``path:line:col``.  ``snippet`` is the
    stripped source line — the stable baseline key (line numbers drift
    under unrelated edits; the offending line's text rarely does)."""

    code: str
    message: str
    path: str
    line: int
    col: int
    snippet: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"


# --------------------------------------------------------------------------
# import resolution shared by every rule
# --------------------------------------------------------------------------

def build_import_map(tree: ast.AST) -> Dict[str, str]:
    """Map local names to canonical dotted prefixes.

    ``import jax.numpy as jnp`` -> ``{"jnp": "jax.numpy"}``;
    ``from jax import random as jr`` -> ``{"jr": "jax.random"}``;
    ``from jax.experimental.pallas import pallas_call`` ->
    ``{"pallas_call": "jax.experimental.pallas.pallas_call"}``.
    """
    amap: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    amap[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    amap[head] = head
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                amap[a.asname or a.name] = f"{node.module}.{a.name}"
    return amap


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def resolve_call(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name of a Name/Attribute expression, resolving the
    leading segment through the import map: with ``jr -> jax.random``,
    ``jr.normal`` resolves to ``jax.random.normal``."""
    dotted = _dotted(node)
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    head = imports.get(head, head)
    return f"{head}.{rest}" if rest else head


# --------------------------------------------------------------------------
# rule framework
# --------------------------------------------------------------------------

class ModuleContext:
    """One parsed file, shared by all rules."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.imports = build_import_map(self.tree)

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


class Rule:
    """Base hazard detector.  Subclasses set ``code``/``name``/
    ``description`` and implement :meth:`check`.

    A rule with ``project = True`` is a **cross-file** detector: it
    implements :meth:`check_project` over every collected module in one
    pass (contract drift between an emitter in one file and its
    consumer in another can't be seen one file at a time).  Such rules
    still work under :func:`analyze_source` — they are handed a
    one-module project — but only surface their real findings when the
    whole tree is collected by :func:`analyze_paths`."""

    code: str = "APX000"
    name: str = ""
    description: str = ""
    project: bool = False

    def check(self, module: ModuleContext) -> List[Finding]:
        raise NotImplementedError

    def check_project(self, modules: Sequence[ModuleContext]
                      ) -> List[Finding]:
        raise NotImplementedError


class RuleVisitor(ast.NodeVisitor):
    """``ast.NodeVisitor`` with the boilerplate rules share: the module
    context, the import resolver, and a ``report`` helper that stamps the
    finding with the node position and source snippet."""

    def __init__(self, rule: Rule, module: ModuleContext):
        self.rule = rule
        self.module = module
        self.findings: List[Finding] = []

    def resolve(self, node: ast.AST) -> Optional[str]:
        return resolve_call(node, self.module.imports)

    def report(self, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        self.findings.append(Finding(
            code=self.rule.code, message=message, path=self.module.path,
            line=line, col=col, snippet=self.module.snippet(line)))


# --------------------------------------------------------------------------
# suppression: # noqa
# --------------------------------------------------------------------------

_NOQA_RE = re.compile(
    r"#\s*noqa(?P<sep>:\s*(?P<codes>[A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*))?",
    re.IGNORECASE)


def _noqa_codes(line: str) -> Optional[set]:
    """None = no directive; empty set = bare ``# noqa`` (suppress all);
    else the set of codes listed."""
    m = _NOQA_RE.search(line)
    if m is None:
        return None
    codes = m.group("codes")
    if not codes:
        return set()
    return {c.strip().upper() for c in codes.split(",")}


def _suppressed(finding: Finding, lines: Sequence[str]) -> bool:
    if not (1 <= finding.line <= len(lines)):
        return False
    codes = _noqa_codes(lines[finding.line - 1])
    if codes is None:
        return False
    return not codes or finding.code in codes


# --------------------------------------------------------------------------
# baseline
# --------------------------------------------------------------------------

#: the justification ``--write-baseline`` stamps on fresh entries — a
#: human must replace it before the entry suppresses anything
PLACEHOLDER_JUSTIFICATION = "TODO: justify"


class Baseline:
    """Committed ledger of accepted findings.

    Entries match on ``(path, code, snippet)``; duplicates are counted, so
    two identical offending lines in one file need two entries.  ``line``
    is for humans; ``justification`` is **enforced**: an entry whose
    justification is missing, blank, or still the literal
    ``--write-baseline`` placeholder (:data:`PLACEHOLDER_JUSTIFICATION`)
    does not suppress its finding — the finding stays "new" and the gate
    stays red until someone writes down *why* the hazard is acceptable.
    """

    def __init__(self, entries: Optional[List[dict]] = None, path: str = ""):
        self.path = path
        self.entries: List[dict] = list(entries or [])

    @staticmethod
    def _key(path: str, code: str, snippet: str) -> Tuple[str, str, str]:
        return (path.replace(os.sep, "/"), code, snippet.strip())

    @staticmethod
    def entry_justified(entry: dict) -> bool:
        """Whether an entry carries a real (non-placeholder)
        justification and may therefore suppress its finding."""
        j = entry.get("justification")
        return (isinstance(j, str) and bool(j.strip())
                and PLACEHOLDER_JUSTIFICATION not in j)

    def unjustified_entries(self) -> List[dict]:
        """Entries the gate refuses to honor (empty or placeholder
        justification); their findings surface as new."""
        return [e for e in self.entries if not self.entry_justified(e)]

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        return cls(data.get("entries", []), path=path)

    def save(self, path: Optional[str] = None) -> None:
        path = path or self.path
        data = {"version": 1, "entries": self.entries}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2, sort_keys=True)
            fh.write("\n")

    def partition(self, findings: Sequence[Finding]
                  ) -> Tuple[List[Finding], List[Finding], List[dict]]:
        """Split findings into (new, baselined); also return stale entries
        (baseline lines whose finding no longer exists — fixed code whose
        ledger entry should be dropped). Entries without a real
        justification (see :meth:`entry_justified`) are excluded from the
        budget entirely: their findings come back as new."""
        budget: Dict[Tuple[str, str, str], int] = {}
        for e in self.entries:
            if not self.entry_justified(e):
                continue
            k = self._key(e.get("path", ""), e.get("code", ""),
                          e.get("snippet", ""))
            budget[k] = budget.get(k, 0) + 1
        new: List[Finding] = []
        matched: List[Finding] = []
        for f in findings:
            k = self._key(f.path, f.code, f.snippet)
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                matched.append(f)
            else:
                new.append(f)
        stale: List[dict] = []
        for e in self.entries:
            if not self.entry_justified(e):
                continue        # reported via unjustified_entries()
            k = self._key(e.get("path", ""), e.get("code", ""),
                          e.get("snippet", ""))
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                stale.append(e)
        return new, matched, stale

    def prune(self, findings: Sequence[Finding]
              ) -> Tuple[List[dict], List[dict]]:
        """Split entries into (kept, dropped): an entry is dropped when
        NO current finding matches its ``(path, code, snippet)`` key —
        the code was fixed or deleted and the ledger line is dead
        weight.  Justification status is irrelevant here: a dead entry
        is dead either way.  Duplicate entries are budgeted against
        duplicate findings one-for-one.  Mutates ``self.entries`` to
        the kept list and returns both halves."""
        supply: Dict[Tuple[str, str, str], int] = {}
        for f in findings:
            k = self._key(f.path, f.code, f.snippet)
            supply[k] = supply.get(k, 0) + 1
        kept: List[dict] = []
        dropped: List[dict] = []
        for e in self.entries:
            k = self._key(e.get("path", ""), e.get("code", ""),
                          e.get("snippet", ""))
            if supply.get(k, 0) > 0:
                supply[k] -= 1
                kept.append(e)
            else:
                dropped.append(e)
        self.entries = kept
        return kept, dropped

    @classmethod
    def from_findings(cls, findings: Sequence[Finding],
                      justification: str = "TODO: justify") -> "Baseline":
        entries = [{
            "path": f.path.replace(os.sep, "/"), "code": f.code,
            "line": f.line, "snippet": f.snippet,
            "justification": justification,
        } for f in findings]
        return cls(entries)


# --------------------------------------------------------------------------
# config: [tool.apex_tpu.analysis] in pyproject.toml
# --------------------------------------------------------------------------

@dataclass
class AnalysisConfig:
    paths: List[str] = field(default_factory=lambda: ["apex_tpu"])
    baseline: Optional[str] = None       # path, relative to root
    exclude: List[str] = field(default_factory=list)  # substring/glob-ish
    select: List[str] = field(default_factory=list)   # empty = all rules
    disable: List[str] = field(default_factory=list)
    root: str = "."                      # directory holding pyproject.toml


def _parse_toml_value(text: str):
    text = text.strip()
    if text.startswith("[") and text.endswith("]"):
        inner = text[1:-1].strip()
        if not inner:
            return []
        return [_parse_toml_value(p) for p in _split_toml_list(inner)]
    if text.startswith(('"', "'")):
        return text[1:-1]
    if text in ("true", "false"):
        return text == "true"
    try:
        return int(text)
    except ValueError:
        return text


def _split_toml_list(inner: str) -> List[str]:
    parts, depth, buf, quote = [], 0, [], None
    for ch in inner:
        if quote:
            buf.append(ch)
            if ch == quote:
                quote = None
            continue
        if ch in "\"'":
            quote = ch
            buf.append(ch)
        elif ch == "[":
            depth += 1
            buf.append(ch)
        elif ch == "]":
            depth -= 1
            buf.append(ch)
        elif ch == "," and depth == 0:
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    if "".join(buf).strip():
        parts.append("".join(buf))
    return parts


def _read_toml_table(path: str, table: str) -> Dict[str, object]:
    """Parse one flat ``[table]`` from a TOML file.

    On Python 3.11+ this defers to the stdlib ``tomllib`` (a real TOML
    parser: escape sequences, inline comments, every string flavor).
    Python 3.10 ships no tomllib and the image policy forbids new deps,
    so the fallback is the hand-rolled reader below — just the subset
    this engine's config needs (strings, bools, ints, string arrays,
    including multi-line arrays).  Known fallback gap: backslash escape
    sequences inside basic strings are returned verbatim rather than
    decoded (tracked by a test; keep config values escape-free)."""
    try:
        import tomllib
    except ImportError:
        tomllib = None
    if tomllib is not None:
        try:
            with open(path, "rb") as fh:
                data: object = tomllib.load(fh)
        except OSError:
            return {}
        except tomllib.TOMLDecodeError:
            return {}
        for part in table.split("."):
            if not isinstance(data, dict):
                return {}
            data = data.get(part, {})
        return dict(data) if isinstance(data, dict) else {}
    try:
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return {}
    out: Dict[str, object] = {}
    in_table = False
    pending_key = None
    pending: List[str] = []
    for raw in lines:
        line = raw.strip()
        if line.startswith("["):
            in_table = line == f"[{table}]"
            continue
        if not in_table or not line or line.startswith("#"):
            continue
        if pending_key is not None:
            pending.append(line)
            joined = " ".join(pending)
            if joined.count("[") == joined.count("]"):
                out[pending_key] = _parse_toml_value(joined)
                pending_key, pending = None, []
            continue
        if "=" not in line:
            continue
        key, _, value = line.partition("=")
        key = key.strip().strip('"')
        value = value.split("#")[0].strip() if not value.strip().startswith(
            ('"', "'")) else value.strip()
        if value.startswith("[") and value.count("[") != value.count("]"):
            pending_key, pending = key, [value]
            continue
        out[key] = _parse_toml_value(value)
    return out


def load_config(start: str = ".",
                pyproject: Optional[str] = None) -> AnalysisConfig:
    """Find pyproject.toml (walking up from ``start`` unless given
    explicitly) and build the analysis config from its
    ``[tool.apex_tpu.analysis]`` table.  Missing file/table = defaults."""
    if pyproject is None:
        cur = os.path.abspath(start)
        if os.path.isfile(cur):
            cur = os.path.dirname(cur)
        while True:
            cand = os.path.join(cur, "pyproject.toml")
            if os.path.isfile(cand):
                pyproject = cand
                break
            parent = os.path.dirname(cur)
            if parent == cur:
                break
            cur = parent
    cfg = AnalysisConfig()
    if pyproject is None:
        return cfg
    cfg.root = os.path.dirname(os.path.abspath(pyproject))
    table = _read_toml_table(pyproject, "tool.apex_tpu.analysis")
    if "paths" in table:
        cfg.paths = [str(p) for p in table["paths"]]  # type: ignore[union-attr]
    if "baseline" in table:
        cfg.baseline = str(table["baseline"])
    if "exclude" in table:
        cfg.exclude = [str(p) for p in table["exclude"]]  # type: ignore[union-attr]
    if "select" in table:
        cfg.select = [str(p).upper() for p in table["select"]]  # type: ignore[union-attr]
    if "disable" in table:
        cfg.disable = [str(p).upper() for p in table["disable"]]  # type: ignore[union-attr]
    return cfg


# --------------------------------------------------------------------------
# driving the rules
# --------------------------------------------------------------------------

def _get_rules(select: Sequence[str] = (), disable: Sequence[str] = ()
               ) -> List[Rule]:
    from apex_tpu.analysis.rules import all_rules
    rules = all_rules()
    if select:
        rules = [r for r in rules if r.code in select]
    if disable:
        rules = [r for r in rules if r.code not in disable]
    return rules


def analyze_source(source: str, path: str = "<string>",
                   rules: Optional[Sequence[Rule]] = None,
                   respect_noqa: bool = True) -> List[Finding]:
    """Run the rule pack over one source string.  Syntax errors surface as
    a single APX000 finding rather than an exception — a lint run must
    never die on one unparseable file."""
    try:
        module = ModuleContext(path, source)
    except SyntaxError as e:
        return [Finding("APX000", f"syntax error: {e.msg}", path,
                        e.lineno or 1, e.offset or 0)]
    findings: List[Finding] = []
    for rule in (rules if rules is not None else _get_rules()):
        if rule.project:
            findings.extend(rule.check_project([module]))
        else:
            findings.extend(rule.check(module))
    if respect_noqa:
        findings = [f for f in findings
                    if not _suppressed(f, module.lines)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def analyze_file(path: str, rules: Optional[Sequence[Rule]] = None,
                 rel_to: Optional[str] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    shown = os.path.relpath(path, rel_to) if rel_to else path
    return analyze_source(source, shown.replace(os.sep, "/"), rules)


def _iter_py_files(paths: Iterable[str], exclude: Sequence[str] = ()
                   ) -> Iterable[str]:
    def excluded(p: str) -> bool:
        p = p.replace(os.sep, "/")
        return any(pat in p for pat in exclude) or "__pycache__" in p

    for path in paths:
        if os.path.isfile(path):
            if not excluded(path):
                yield path
        else:
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        full = os.path.join(dirpath, fn)
                        if not excluded(full):
                            yield full


def analyze_paths(paths: Sequence[str],
                  config: Optional[AnalysisConfig] = None,
                  rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Lint files/trees.  Paths in findings are reported relative to the
    config root (the pyproject directory) so they match baseline entries
    regardless of the invocation cwd."""
    cfg = config or load_config(paths[0] if paths else ".")
    if rules is None:
        rules = _get_rules(cfg.select, cfg.disable)
    per_module = [r for r in rules if not r.project]
    project = [r for r in rules if r.project]
    findings: List[Finding] = []
    modules: List[ModuleContext] = []
    for f in _iter_py_files(paths, cfg.exclude):
        with open(f, "r", encoding="utf-8") as fh:
            source = fh.read()
        shown = os.path.relpath(f, cfg.root).replace(os.sep, "/")
        try:
            module = ModuleContext(shown, source)
        except SyntaxError as e:
            findings.append(Finding(
                "APX000", f"syntax error: {e.msg}", shown,
                e.lineno or 1, e.offset or 0))
            continue
        modules.append(module)
        for rule in per_module:
            findings.extend(rule.check(module))
    for rule in project:
        findings.extend(rule.check_project(modules))
    lines_by_path = {m.path: m.lines for m in modules}
    findings = [f for f in findings
                if f.path not in lines_by_path
                or not _suppressed(f, lines_by_path[f.path])]
    findings.sort(key=lambda x: (x.path, x.line, x.col, x.code))
    return findings


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description="JAX/TPU hazard linter (APX rule pack)")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: config paths)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON (default: config baseline)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring the baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write all current findings to the baseline "
                             "file and exit 0")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="drop baseline entries whose finding no "
                             "longer exists (fixed/deleted code), "
                             "rewrite the file, then lint as usual")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule codes to run")
    parser.add_argument("--disable", default=None,
                        help="comma-separated rule codes to skip")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--show-baselined", action="store_true",
                        help="also print findings matched by the baseline")
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in _get_rules():
            print(f"{r.code}  {r.name}: {r.description}")
        return 0

    cfg = load_config(args.paths[0] if args.paths else ".")
    paths = list(args.paths) or [os.path.join(cfg.root, p)
                                 for p in cfg.paths]
    select = ([c.strip().upper() for c in args.select.split(",")]
              if args.select else cfg.select)
    disable = ([c.strip().upper() for c in args.disable.split(",")]
               if args.disable else cfg.disable)
    rules = _get_rules(select, disable)

    findings = analyze_paths(paths, cfg, rules)

    baseline_path = args.baseline
    if baseline_path is None and cfg.baseline:
        baseline_path = os.path.join(cfg.root, cfg.baseline)

    if args.write_baseline:
        if baseline_path is None:
            print("no baseline path (config [tool.apex_tpu.analysis] "
                  "baseline or --baseline)", file=sys.stderr)
            return 2
        Baseline.from_findings(findings).save(baseline_path)
        print(f"wrote {len(findings)} entries to {baseline_path}")
        return 0

    if args.prune_baseline:
        if baseline_path is None or not os.path.exists(baseline_path):
            print("no baseline file to prune "
                  f"({baseline_path or 'no path configured'})",
                  file=sys.stderr)
            return 2
        bl = Baseline.load(baseline_path)
        kept, dropped = bl.prune(findings)
        if dropped:
            bl.save()
        print(f"pruned {len(dropped)} stale baseline "
              f"entr{'ies' if len(dropped) != 1 else 'y'} "
              f"({len(kept)} kept) in {baseline_path}")

    baselined: List[Finding] = []
    stale: List[dict] = []
    unjustified: List[dict] = []
    if baseline_path and not args.no_baseline and os.path.exists(
            baseline_path):
        bl = Baseline.load(baseline_path)
        findings, baselined, stale = bl.partition(findings)
        unjustified = bl.unjustified_entries()

    if args.format == "json":
        print(json.dumps({
            "findings": [vars(f) for f in findings],
            "baselined": len(baselined),
            "stale_baseline_entries": stale,
            "unjustified_baseline_entries": unjustified,
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        if args.show_baselined:
            for f in baselined:
                print(f"{f.render()}  [baselined]")
        for e in stale:
            print(f"stale baseline entry (code fixed? drop it): "
                  f"{e.get('path')}:{e.get('line')} {e.get('code')} "
                  f"{e.get('snippet', '')!r}", file=sys.stderr)
        for e in unjustified:
            print(f"baseline entry lacks a justification (placeholder "
                  f"or blank — finding NOT suppressed): "
                  f"{e.get('path')}:{e.get('line')} {e.get('code')}",
                  file=sys.stderr)
        n = len(findings)
        print(f"{n} finding{'s' if n != 1 else ''} "
              f"({len(baselined)} baselined, {len(stale)} stale baseline "
              f"entr{'ies' if len(stale) != 1 else 'y'})")
    return 1 if findings else 0
