"""Distributed checkpoint / resume.

The reference leaves model checkpointing to user scripts
(``examples/imagenet/main_amp.py:254-260`` uses ``torch.save``) and layers
three pieces on top (SURVEY.md §5):

- amp scaler state round-trip (``apex/amp/frontend.py:365-404``, recommended
  flow ``README.md:63-103``),
- fp32 master groups in ``FP16_Optimizer.state_dict``
  (``apex/fp16_utils/fp16_optimizer.py:212-273``),
- sharded optimizer state gather/scatter in ``DistributedFusedAdam``.

On TPU all three collapse into one capability: **save and restore an
arbitrarily-sharded JAX pytree without gathering it to one host**, provided
here on orbax — each host writes exactly the array shards it owns (the
analog of the reference's shard-aware gather/scatter, minus the gather).
Loss-scaler state, fp32 masters, and ZeRO shards are ordinary pytree leaves,
so the whole train state round-trips through one call pair.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, List, Optional

import jax

__all__ = [
    "save_checkpoint",
    "load_checkpoint",
    "CheckpointManager",
]


_CKPTR = None


def _checkpointer():
    # one long-lived checkpointer: orbax spins up async-IO resources per
    # instance, so per-call construction leaks in long training loops
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _as_restore_target(template: Any) -> Any:
    """Template pytree -> ShapeDtypeStruct pytree carrying shardings, so each
    leaf is restored with the layout the training state expects."""
    return jax.tree.map(
        lambda x: (x if isinstance(x, jax.ShapeDtypeStruct)
                   else jax.ShapeDtypeStruct(
                       x.shape, x.dtype,
                       sharding=getattr(x, "sharding", None))),
        template)


def save_checkpoint(path: str, state: Any, *, force: bool = True) -> None:
    """Write ``state`` (any pytree of jax.Arrays, sharded or not) to
    ``path``. Sharded leaves are written distributed: every host persists its
    own shards (no host gather — contrast the reference's
    ``DistributedFusedAdam.state_dict`` gather)."""
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(os.fspath(path)), state, force=force)
    ckptr.wait_until_finished()


def load_checkpoint(path: str, template: Optional[Any] = None) -> Any:
    """Restore a checkpoint. ``template`` (a pytree of arrays or
    ``jax.ShapeDtypeStruct``, possibly carrying shardings) restores each leaf
    with the requested sharding/dtype; without it, arrays come back
    replicated on the default device."""
    ckptr = _checkpointer()
    path = os.path.abspath(os.fspath(path))
    if template is None:
        return ckptr.restore(path)
    return ckptr.restore(path, _as_restore_target(template))


class CheckpointManager:
    """Rotating step-indexed checkpoints with resume — the role the
    reference's AutoResume hook + user save scripts play
    (``pipeline_parallel/utils.py:142-144``, ``examples/imagenet``).

    ``save(step, state)`` / ``restore(template) -> (step, state) | None``;
    keeps the newest ``max_to_keep``.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp
        self.directory = os.path.abspath(os.fspath(directory))
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """``force=True`` bypasses ``save_interval_steps`` gating (and
        overwrites an existing step) — the emergency-save path."""
        import orbax.checkpoint as ocp
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state),
                               force=force)
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        """Committed checkpoint steps, ascending. Uncommitted (killed
        mid-write) step directories are excluded by orbax's atomicity
        protocol, so everything listed here finished its write."""
        return sorted(self._mgr.all_steps())

    def restore(self, template: Any):
        step = self._mgr.latest_step()
        if step is None:
            return None
        return step, self.restore_step(step, template)

    def restore_step(self, step: int, template: Any) -> Any:
        import orbax.checkpoint as ocp
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(_as_restore_target(template)))

    def delete(self, step: int) -> None:
        self._mgr.delete(step)

    def uncommitted_steps(self) -> List[int]:
        """Steps with leftover uncommitted write directories (orbax's
        ``*.orbax-checkpoint-tmp-*`` debris from interrupted saves)."""
        return sorted(self._partial_dirs())

    def cleanup_partial(self, *, exclude=()) -> List[int]:
        """Delete uncommitted write directories; returns the steps whose
        debris was removed. Call only when no save is in flight."""
        skip = {int(s) for s in exclude}
        removed = []
        for step, name in sorted(self._partial_dirs().items()):
            if step in skip:
                continue
            shutil.rmtree(os.path.join(self.directory, name),
                          ignore_errors=True)
            removed.append(step)
        return removed

    def _partial_dirs(self) -> dict:
        out = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            head, sep, _ = name.partition(".orbax-checkpoint-tmp")
            if sep and os.path.isdir(os.path.join(self.directory, name)):
                try:
                    out[int(head)] = name
                except ValueError:
                    continue
        return out

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


