"""fsck for sharded checkpoint directories.

``python -m apex_tpu.checkpoint verify <dir>`` walks every step directory
under ``<dir>`` and classifies it:

- **ok** — committed (COMMIT marker present), manifest hashes to the
  sha256 the marker pinned, every shard file present with the manifested
  byte size and (``deep``, the default) sha256. These are the *adoptable*
  steps: ``restore_latest`` on this directory will succeed from the
  newest of them.
- **damaged** — committed but failing any of those checks (bit rot, torn
  manifest, missing/truncated shard). A damaged step makes the exit code
  non-zero: the step *claims* to be restorable and is not.
- **uncommitted** — no readable COMMIT marker. Listed informationally
  (it is debris from an interrupted save, invisible to restore) and does
  NOT affect the exit code; ``--gc`` deletes it.

Pure stdlib (shares :mod:`apex_tpu.checkpoint.manifest`), so it runs on
any machine that can see the filesystem — no jax, no accelerator.
"""

from __future__ import annotations

import os
import shutil
from typing import Dict, List, Optional

from apex_tpu.checkpoint.manifest import (
    list_step_dirs,
    read_commit,
    validate_step_dir,
)

__all__ = ["StepReport", "verify_directory", "format_report", "main"]


class StepReport:
    """Verification outcome for one step directory."""

    __slots__ = ("step", "dirname", "status", "problems")

    def __init__(self, step: int, dirname: str, status: str,
                 problems: List[str]):
        self.step = step
        self.dirname = dirname
        self.status = status  # "ok" | "damaged" | "uncommitted"
        self.problems = problems

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (f"StepReport(step={self.step}, status={self.status!r}, "
                f"problems={self.problems!r})")


def verify_directory(root: str, *, deep: bool = True) -> List[StepReport]:
    """Validate every step directory under ``root``; reports sorted by
    step. An empty / nonexistent ``root`` yields an empty list (nothing
    claimed, nothing damaged)."""
    reports: List[StepReport] = []
    for step, dirname in sorted(list_step_dirs(root).items()):
        step_dir = os.path.join(root, dirname)
        if read_commit(step_dir) is None:
            reports.append(StepReport(step, dirname, "uncommitted", []))
            continue
        problems = validate_step_dir(step_dir, deep=deep)
        reports.append(StepReport(
            step, dirname, "damaged" if problems else "ok", problems))
    return reports


def format_report(root: str, reports: List[StepReport]) -> str:
    lines = [f"checkpoint directory: {os.path.abspath(root)}"]
    if not reports:
        lines.append("  (no step directories)")
    for r in reports:
        lines.append(f"  step {r.step:>8}  {r.status}")
        for p in r.problems:
            lines.append(f"    - {p}")
    adoptable = [r.step for r in reports if r.status == "ok"]
    damaged = [r.step for r in reports if r.status == "damaged"]
    uncommitted = [r.step for r in reports if r.status == "uncommitted"]
    lines.append(f"adoptable steps: {adoptable or 'none'}")
    if damaged:
        lines.append(f"DAMAGED steps:   {damaged}")
    if uncommitted:
        lines.append(f"uncommitted (debris, ignored by restore): "
                     f"{uncommitted}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.checkpoint",
        description="Offline integrity checks for sharded checkpoints.")
    sub = parser.add_subparsers(dest="cmd", required=True)
    v = sub.add_parser(
        "verify", help="fsck a checkpoint directory: validate manifests "
        "and shard checksums across all steps")
    v.add_argument("directory", help="checkpoint root (parent of the "
                   "per-step directories)")
    v.add_argument("--shallow", action="store_true",
                   help="skip per-shard sha256 re-hash (presence + byte "
                   "size only)")
    v.add_argument("--gc", action="store_true",
                   help="delete uncommitted debris directories")
    args = parser.parse_args(argv)

    reports = verify_directory(args.directory, deep=not args.shallow)
    print(format_report(args.directory, reports))
    if args.gc:
        for r in reports:
            if r.status == "uncommitted":
                shutil.rmtree(os.path.join(args.directory, r.dirname),
                              ignore_errors=True)
                print(f"gc: removed uncommitted step {r.step}")
    return 1 if any(r.status == "damaged" for r in reports) else 0
