"""Sharded checkpoint save/restore with elastic mesh-reshape restore.

The orbax-backed :class:`~apex_tpu.checkpoint.CheckpointManager` treats a
checkpoint as one opaque blob restored onto the save-time layout; the
dryrun topologies (pp×tp×cp×MoE×ZeRO, two-slice dp) need the TorchTitan
property (PAPERS.md 2410.06511) of checkpoints that are **stored sharded
and re-laid-out on restore** (the cross-replica sharding implication of
PAPERS.md 2004.13336). :class:`ShardedCheckpointManager` provides it:

- **save** snapshots each leaf's addressable shards to host (replicas
  deduplicated by global shard index — a dp-replicated leaf is written
  once per distinct shard, not once per device), serializes each shard
  to its own file, and records global shape/dtype/PartitionSpec plus a
  per-shard sha256 in ``manifest.json``; a ``COMMIT`` marker written
  last via atomic rename makes the step visible
  (:mod:`apex_tpu.checkpoint.manifest` is the protocol).
- **restore is elastic**: the target template's shardings — a different
  mesh shape (dp=4,tp=2 -> dp=2,tp=4), a single device, or no mesh at
  all — drive reassembly. Each target shard region is rebuilt from the
  intersecting saved shards via ``jax.make_array_from_callback``, so
  data moves host->device already in the new layout; no save-time
  topology information is needed beyond the manifest.
- every shard read is verified against its manifest sha256; any
  mismatch, missing file, or torn manifest raises
  :class:`~apex_tpu.checkpoint.manifest.CheckpointCorruptionError`,
  which :class:`~apex_tpu.checkpoint.RetryingCheckpointManager` turns
  into fallback-to-an-older-step.

Asynchrony lives one layer up: :class:`RetryingCheckpointManager` calls
the two-phase API (:meth:`snapshot` on the critical path, then
:meth:`write_snapshot` on its background writer, retries included).
:meth:`save` composes the two phases synchronously for standalone use.
"""

from __future__ import annotations

import os
import shutil
import threading
from io import BytesIO
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from apex_tpu.checkpoint.manifest import (
    FORMAT_NAME,
    CheckpointCorruptionError,
    list_step_dirs,
    load_manifest,
    read_commit,
    sha256_bytes,
    validate_step_dir,
    write_commit,
    write_manifest,
)

__all__ = ["ShardedCheckpointManager", "HostSnapshot",
           "CheckpointCorruptionError"]


def _spec_entries(sharding) -> Optional[list]:
    """PartitionSpec of a NamedSharding as JSON-serializable entries
    (None | axis name | list of axis names), or None when the leaf has no
    named sharding (informational only — restore is driven by the
    *target* template, never by this)."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    out = []
    for entry in tuple(spec):
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append([str(a) for a in entry])
    return out


def _mesh_axes(sharding) -> Optional[Dict[str, int]]:
    mesh = getattr(sharding, "mesh", None)
    if mesh is None:
        return None
    try:
        return {str(k): int(v) for k, v in mesh.shape.items()}
    except Exception:  # noqa: BLE001 — informational field only
        return None


def _bounds(index: Tuple[slice, ...], shape: Sequence[int]
            ) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """Concrete (start, stop) per dim from a shard's slice tuple."""
    start, stop = [], []
    for sl, dim in zip(index, shape):
        start.append(0 if sl.start is None else int(sl.start))
        stop.append(int(dim) if sl.stop is None else int(sl.stop))
    return tuple(start), tuple(stop)


class HostSnapshot:
    """Device->host copy of one train-state pytree, shard-structured:
    the only part of a save that blocks the step loop. Leaves are listed
    in ``jax.tree_util.keystr`` order with per-shard host arrays and
    bounds; serialization/checksums happen later, on the writer."""

    __slots__ = ("leaves", "nbytes")

    def __init__(self, leaves: List[dict], nbytes: int):
        self.leaves = leaves
        self.nbytes = nbytes


class ShardedCheckpointManager:
    """Step-addressed sharded checkpoints under one root directory.

    API-compatible with :class:`apex_tpu.checkpoint.CheckpointManager`
    (``save``/``restore``/``restore_step``/``all_steps``/``delete``/…)
    so :class:`RetryingCheckpointManager` and
    :func:`apex_tpu.resilience.run_training` drive either; adds the
    two-phase :meth:`snapshot`/:meth:`write_snapshot` split (async saves),
    :meth:`uncommitted_steps`/:meth:`cleanup_partial` (interrupted-save
    debris), and :meth:`verify_step` (deep fsck of one step).
    """

    #: RetryingCheckpointManager keys on this to run writes on its
    #: background writer instead of inline
    supports_async = True

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1, fsync: bool = True):
        self.directory = os.path.abspath(os.fspath(directory))
        self.max_to_keep = int(max_to_keep)
        self.save_interval_steps = int(save_interval_steps)
        self.fsync = bool(fsync)
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()  # serializes directory mutation

    # -- step listing ------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(int(step)))

    def all_steps(self) -> List[int]:
        """Committed steps, ascending. Commit = a readable ``COMMIT``
        marker; anything else is invisible debris (see
        :meth:`uncommitted_steps`)."""
        steps = []
        for step, name in list_step_dirs(self.directory).items():
            if read_commit(os.path.join(self.directory, name)) is not None:
                steps.append(step)
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def uncommitted_steps(self) -> List[int]:
        """Integer-named child directories with no commit marker — the
        debris a killed or failed save leaves behind."""
        out = []
        for step, name in list_step_dirs(self.directory).items():
            if read_commit(os.path.join(self.directory, name)) is None:
                out.append(step)
        return sorted(out)

    def cleanup_partial(self, *, exclude: Sequence[int] = ()) -> List[int]:
        """Remove uncommitted step directories (``exclude`` protects
        steps a writer is mid-save on). Returns the steps removed."""
        removed = []
        skip = {int(s) for s in exclude}
        with self._lock:
            for step in self.uncommitted_steps():
                if step in skip:
                    continue
                shutil.rmtree(self._step_dir(step), ignore_errors=True)
                removed.append(step)
        return removed

    def should_save(self, step: int, *, force: bool = False) -> bool:
        if force:
            return True
        if self.save_interval_steps > 1 and step % self.save_interval_steps:
            return False
        return True

    # -- save: snapshot (critical path) + write (background-safe) ----------
    def snapshot(self, state: Any) -> HostSnapshot:
        """Copy every leaf's addressable shards to host — the ONLY part
        of a save the train loop must block on. Replicated copies are
        deduplicated by global shard index, so a dp-replicated leaf costs
        one transfer per distinct shard."""
        flat, _ = jax.tree_util.tree_flatten_with_path(state)
        leaves: List[dict] = []
        nbytes = 0
        for path, leaf in flat:
            key = jax.tree_util.keystr(path)
            shards: List[dict] = []
            if isinstance(leaf, jax.Array) and hasattr(
                    leaf, "addressable_shards"):
                shape = tuple(leaf.shape)
                seen = set()
                for shard in leaf.addressable_shards:
                    start, stop = _bounds(shard.index, shape)
                    if (start, stop) in seen:
                        continue  # a replica of a shard already captured
                    seen.add((start, stop))
                    # np.array (not asarray): on the CPU backend asarray
                    # can alias the device buffer, and a donated state
                    # would scribble over an in-flight async write
                    shards.append({"start": start, "stop": stop,
                                   "data": np.array(shard.data)})
                spec = _spec_entries(leaf.sharding)
                mesh = _mesh_axes(leaf.sharding)
                dtype = str(np.dtype(leaf.dtype))
            else:
                arr = np.array(leaf)
                shape = tuple(arr.shape)
                shards.append({"start": tuple(0 for _ in shape),
                               "stop": shape, "data": arr})
                spec, mesh, dtype = None, None, str(arr.dtype)
            nbytes += sum(s["data"].nbytes for s in shards)
            leaves.append({"path": key, "shape": shape, "dtype": dtype,
                           "spec": spec, "mesh": mesh, "shards": shards})
        return HostSnapshot(leaves, nbytes)

    def write_snapshot(self, step: int, snap: HostSnapshot, *,
                       force: bool = False) -> None:
        """Serialize + fsync + checksum a :class:`HostSnapshot` into the
        step directory and commit it. Safe to call from a background
        writer thread (touches only host memory and the filesystem).
        An existing committed step is replaced only under ``force`` —
        the retry/emergency semantics."""
        step = int(step)
        step_dir = self._step_dir(step)
        with self._lock:
            if os.path.isdir(step_dir):
                if not force and read_commit(step_dir) is not None:
                    raise FileExistsError(
                        f"step {step} already committed at {step_dir} "
                        f"(pass force=True to replace)")
                shutil.rmtree(step_dir, ignore_errors=True)
            os.makedirs(step_dir, exist_ok=True)
        manifest_leaves: Dict[str, dict] = {}
        for i, leaf in enumerate(snap.leaves):
            entries = []
            for j, shard in enumerate(leaf["shards"]):
                fname = f"leaf{i:04d}_s{j:02d}.npy"
                buf = BytesIO()
                np.save(buf, shard["data"], allow_pickle=False)
                data = buf.getvalue()
                with open(os.path.join(step_dir, fname), "wb") as f:
                    f.write(data)
                    if self.fsync:
                        f.flush()
                        os.fsync(f.fileno())
                entries.append({
                    "file": fname,
                    "index": j,
                    "start": list(shard["start"]),
                    "stop": list(shard["stop"]),
                    "bytes": len(data),
                    "sha256": sha256_bytes(data),
                })
            manifest_leaves[leaf["path"]] = {
                "shape": list(leaf["shape"]),
                "dtype": leaf["dtype"],
                "spec": leaf["spec"],
                "mesh": leaf["mesh"],
                "shards": entries,
            }
        manifest = {"format": FORMAT_NAME, "step": step,
                    "leaves": manifest_leaves}
        sha = write_manifest(step_dir, manifest, fsync=self.fsync)
        write_commit(step_dir, sha, step, fsync=self.fsync)
        self._prune()

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Synchronous save: snapshot + write + commit. Returns False when
        gated by ``save_interval_steps``; see
        :class:`RetryingCheckpointManager` for the async composition."""
        if not self.should_save(step, force=force):
            return False
        self.write_snapshot(step, self.snapshot(state), force=force)
        return True

    def _prune(self) -> None:
        if self.max_to_keep <= 0:
            return
        steps = self.all_steps()
        with self._lock:
            for step in steps[:-self.max_to_keep]:
                shutil.rmtree(self._step_dir(step), ignore_errors=True)

    # -- restore -----------------------------------------------------------
    def restore(self, template: Any) -> Optional[Tuple[int, Any]]:
        step = self.latest_step()
        if step is None:
            return None
        return step, self.restore_step(step, template)

    def restore_step(self, step: int, template: Any) -> Any:
        """Reassemble the pytree of a committed step onto the layout the
        ``template`` asks for — leaf by leaf, each target shard region is
        rebuilt from the intersecting saved shards, so a checkpoint
        written under dp=4×tp=2 restores onto dp=2×tp=4, a single
        device, or any other mesh whose global shapes match. Every shard
        file read is checksum-verified against the manifest."""
        step_dir = self._step_dir(int(step))
        manifest = load_manifest(step_dir)
        leaves = manifest["leaves"]
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)
        out = []
        for path, leaf in flat:
            key = jax.tree_util.keystr(path)
            entry = leaves.get(key)
            if entry is None:
                raise ValueError(
                    f"checkpoint step {step} has no leaf {key} — the "
                    f"template's pytree structure differs from the "
                    f"saved state")
            out.append(self._restore_leaf(step_dir, key, entry, leaf))
        return jax.tree_util.tree_unflatten(treedef, out)

    def _restore_leaf(self, step_dir: str, key: str, entry: dict,
                      template_leaf: Any):
        shape = tuple(entry["shape"])
        t_shape = tuple(getattr(template_leaf, "shape",
                                np.shape(template_leaf)))
        if t_shape != shape:
            raise ValueError(
                f"{key}: checkpoint global shape {shape} != template "
                f"shape {t_shape} (elastic restore re-shards, it does "
                f"not reshape)")
        dtype = np.dtype(entry["dtype"])
        t_dtype = getattr(template_leaf, "dtype", None)
        target = np.dtype(t_dtype) if t_dtype is not None else dtype
        cache: Dict[str, np.ndarray] = {}

        def load_shard(shard: dict) -> np.ndarray:
            fname = shard["file"]
            if fname not in cache:
                fpath = os.path.join(step_dir, fname)
                try:
                    with open(fpath, "rb") as f:
                        data = f.read()
                except OSError as e:
                    raise CheckpointCorruptionError(
                        f"{key}: shard {fname} unreadable: {e}") from e
                if sha256_bytes(data) != shard.get("sha256"):
                    raise CheckpointCorruptionError(
                        f"{key}: shard {fname} sha256 mismatch "
                        f"(bit rot / torn write)")
                arr = np.load(BytesIO(data), allow_pickle=False)
                want = (tuple(shard["stop"][d] - shard["start"][d]
                              for d in range(len(shape)))
                        if shape else ())
                if tuple(arr.shape) != want:
                    raise CheckpointCorruptionError(
                        f"{key}: shard {fname} has shape {arr.shape}, "
                        f"manifest says {want}")
                cache[fname] = arr
            return cache[fname]

        def region(index: Tuple[slice, ...]) -> np.ndarray:
            """Assemble one target region from intersecting saved
            shards — the re-shard: save-time and restore-time tilings
            need not align."""
            start, stop = _bounds(tuple(index), shape)
            out = np.empty(tuple(b - a for a, b in zip(start, stop)),
                           dtype=dtype)
            filled = 0
            for shard in entry["shards"]:
                s_start, s_stop = shard["start"], shard["stop"]
                lo = tuple(max(a, b) for a, b in zip(start, s_start))
                hi = tuple(min(a, b) for a, b in zip(stop, s_stop))
                if any(a >= b for a, b in zip(lo, hi)):
                    continue  # no overlap with this saved shard
                block = load_shard(shard)
                src = tuple(slice(a - o, b - o)
                            for a, b, o in zip(lo, hi, s_start))
                dst = tuple(slice(a - o, b - o)
                            for a, b, o in zip(lo, hi, start))
                out[dst] = block[src]
                filled += int(np.prod([b - a for a, b in zip(lo, hi)]))
            if filled < int(np.prod(out.shape)):
                raise CheckpointCorruptionError(
                    f"{key}: saved shards cover only {filled} of "
                    f"{int(np.prod(out.shape))} elements of the "
                    f"requested region (manifest damaged?)")
            if target != dtype:
                out = out.astype(target)
            return out

        sharding = getattr(template_leaf, "sharding", None)
        if (isinstance(sharding, jax.sharding.Sharding)
                and not isinstance(sharding,
                                   jax.sharding.SingleDeviceSharding)):
            return jax.make_array_from_callback(
                shape, sharding, lambda idx: region(idx))
        # a single-device template leaf (e.g. a step counter next to
        # mesh-sharded params) restores UNCOMMITTED: committing it to one
        # device while its siblings commit to the mesh would make the
        # restored state unjittable ("incompatible devices")
        whole = region(tuple(slice(0, d) for d in shape))
        return jax.device_put(whole)

    # -- maintenance -------------------------------------------------------
    def verify_step(self, step: int, *, deep: bool = True) -> None:
        """Deep fsck of one committed step; raises
        :class:`CheckpointCorruptionError` listing every problem."""
        problems = validate_step_dir(self._step_dir(int(step)), deep=deep)
        if problems:
            raise CheckpointCorruptionError(
                f"step {step}: " + "; ".join(problems))

    def delete(self, step: int) -> None:
        step_dir = self._step_dir(int(step))
        if not os.path.isdir(step_dir):
            raise FileNotFoundError(step_dir)
        with self._lock:
            shutil.rmtree(step_dir, ignore_errors=True)

    def wait_until_finished(self) -> None:
        """Writes here are synchronous; asynchrony (and its drain) lives
        in :class:`RetryingCheckpointManager`."""

    def close(self) -> None:
        pass
