"""Sharded-checkpoint manifest + commit protocol (pure stdlib).

One committed step of the sharded format
(:class:`apex_tpu.checkpoint.ShardedCheckpointManager`) is a directory::

    <root>/<step>/
        leaf0000_s00.npy     # one file per (param-path, global-shard-index)
        leaf0000_s01.npy
        ...
        manifest.json        # global shapes, dtypes, sharding specs,
                             # per-shard start/stop offsets + sha256
        COMMIT               # {"manifest_sha256": ...} — written LAST,
                             # via atomic rename

The commit marker is the atomicity boundary: a writer killed at any
point before the final ``os.replace`` leaves a directory without
``COMMIT``, which every reader (``all_steps``, ``restore_latest``, the
``verify`` CLI) treats as invisible debris, never as a step. The marker
records the manifest's own sha256, so a manifest torn *after* commit
(bit rot, partial overwrite) is also detected — validation walks
commit -> manifest checksum -> per-shard checksums.

This module is deliberately jax-free: the ``python -m apex_tpu.checkpoint
verify`` fsck and the restore-side validation share these helpers, and
the former must run on a machine far from any accelerator.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional

__all__ = [
    "MANIFEST_NAME",
    "COMMIT_NAME",
    "FORMAT_NAME",
    "CheckpointCorruptionError",
    "sha256_bytes",
    "sha256_file",
    "atomic_write_bytes",
    "write_manifest",
    "write_commit",
    "read_commit",
    "load_manifest",
    "validate_step_dir",
    "list_step_dirs",
]

MANIFEST_NAME = "manifest.json"
COMMIT_NAME = "COMMIT"
FORMAT_NAME = "apex_tpu.sharded_checkpoint.v1"


class CheckpointCorruptionError(RuntimeError):
    """A committed checkpoint step failed integrity validation: torn or
    checksum-mismatched manifest, or a missing/garbled shard file. The
    restore path treats it like any other corruption — fall back to the
    next-older committed step."""


def sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def atomic_write_bytes(path: str, data: bytes, *, fsync: bool = True) -> None:
    """Write ``data`` to ``path`` via a same-directory temp file +
    ``os.replace`` so a reader never observes a half-written file; fsync
    the file (and containing directory) so a committed marker survives a
    host crash, not just a process kill."""
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if fsync:
            dfd = os.open(directory, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_manifest(step_dir: str, manifest: dict, *,
                   fsync: bool = True) -> str:
    """Serialize + atomically write ``manifest.json``; returns its sha256
    (what the commit marker will pin)."""
    data = json.dumps(manifest, indent=1, sort_keys=True).encode("utf-8")
    atomic_write_bytes(os.path.join(step_dir, MANIFEST_NAME), data,
                       fsync=fsync)
    return sha256_bytes(data)


def write_commit(step_dir: str, manifest_sha256: str, step: int, *,
                 fsync: bool = True) -> None:
    """The LAST write of a save: once this atomic rename lands, the step
    is visible; before it, the directory is invisible debris."""
    data = json.dumps({"format": FORMAT_NAME, "step": int(step),
                       "manifest_sha256": manifest_sha256}).encode("utf-8")
    atomic_write_bytes(os.path.join(step_dir, COMMIT_NAME), data,
                       fsync=fsync)


def read_commit(step_dir: str) -> Optional[dict]:
    """The parsed commit marker, or None when absent/unparseable (an
    uncommitted or garbled directory — never adopted as a step)."""
    try:
        with open(os.path.join(step_dir, COMMIT_NAME), "rb") as f:
            marker = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError, UnicodeDecodeError):
        return None
    return marker if isinstance(marker, dict) else None


def load_manifest(step_dir: str, *, verify_commit: bool = True) -> dict:
    """Load + validate ``manifest.json`` of a committed step. With
    ``verify_commit`` the manifest bytes must hash to the sha256 the
    commit marker pinned — a torn/garbled manifest (even one damaged
    after commit) raises :class:`CheckpointCorruptionError`."""
    marker = read_commit(step_dir)
    if verify_commit and marker is None:
        raise CheckpointCorruptionError(
            f"{step_dir}: no readable commit marker (uncommitted or "
            f"garbled step)")
    path = os.path.join(step_dir, MANIFEST_NAME)
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise CheckpointCorruptionError(
            f"{step_dir}: manifest unreadable: {e}") from e
    if verify_commit and marker is not None:
        want = marker.get("manifest_sha256")
        got = sha256_bytes(data)
        if want != got:
            raise CheckpointCorruptionError(
                f"{step_dir}: manifest sha256 {got[:12]}… does not match "
                f"commit marker {str(want)[:12]}… (torn manifest)")
    try:
        manifest = json.loads(data.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise CheckpointCorruptionError(
            f"{step_dir}: manifest is not valid JSON: {e}") from e
    if not isinstance(manifest, dict) or "leaves" not in manifest:
        raise CheckpointCorruptionError(
            f"{step_dir}: manifest has no 'leaves' table")
    return manifest


def validate_step_dir(step_dir: str, *, deep: bool = True) -> List[str]:
    """fsck one step directory; returns the list of problems (empty ⇒
    healthy). ``deep`` re-hashes every shard file against its manifest
    checksum; without it only presence + size are checked."""
    problems: List[str] = []
    if read_commit(step_dir) is None:
        return [f"no commit marker ({COMMIT_NAME} missing or garbled)"]
    try:
        manifest = load_manifest(step_dir)
    except CheckpointCorruptionError as e:
        return [str(e)]
    for path_key, leaf in sorted(manifest.get("leaves", {}).items()):
        for shard in leaf.get("shards", []):
            fname = shard.get("file", "?")
            fpath = os.path.join(step_dir, fname)
            if not os.path.isfile(fpath):
                problems.append(f"{path_key}: shard {fname} missing")
                continue
            size = os.path.getsize(fpath)
            if size != shard.get("bytes"):
                problems.append(
                    f"{path_key}: shard {fname} is {size} bytes, manifest "
                    f"says {shard.get('bytes')} (truncated?)")
                continue
            if deep and sha256_file(fpath) != shard.get("sha256"):
                problems.append(
                    f"{path_key}: shard {fname} sha256 mismatch "
                    f"(bit rot / torn write)")
    return problems


def list_step_dirs(root: str) -> Dict[int, str]:
    """``{step: dirname}`` for every integer-named child of ``root`` —
    committed or not; callers split on :func:`read_commit`."""
    out: Dict[int, str] = {}
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        full = os.path.join(root, name)
        if os.path.isdir(full):
            try:
                out[int(name)] = name
            except ValueError:
                continue
    return out
