"""Fault-tolerant, async-capable wrapper over a checkpoint manager.

:class:`RetryingCheckpointManager` is the storage-robustness slice of
TorchTitan-style resilient checkpointing (PAPERS.md 2410.06511), wrapping
either the orbax-backed :class:`~apex_tpu.checkpoint.CheckpointManager`
or the :class:`~apex_tpu.checkpoint.ShardedCheckpointManager`:

- ``save`` retries with exponential backoff — flaky storage must not
  kill a training run over a transient error;
- with a manager that ``supports_async`` (the sharded format's two-phase
  ``snapshot``/``write_snapshot`` split), a periodic save blocks the
  caller **only for the device->host snapshot**; serialization + fsync +
  checksum — and the whole retry loop around them — run on a single
  background writer thread. A forced (emergency) save first quiesces the
  writer deterministically: ``drain_on_force=True`` waits for in-flight
  writes to commit, ``False`` abandons queued ones (the running write
  still completes — commit is atomic either way, never torn);
- ``restore_latest`` / ``restore_before`` treat a failed restore as a
  corrupt checkpoint and fall back to the next-older step (optionally
  deleting the corrupt one so it is never picked again); integrity
  failures the sharded format *detects* (checksum mismatch, torn
  manifest — :class:`CheckpointCorruptionError`) are counted separately
  as ``verify_failures``;
- partial/uncommitted step directories left by failed or interrupted
  saves are swept (``manager.cleanup_partial``) after failed attempts
  and before every restore, so debris never accumulates and is never
  adopted as a step.

``before_save`` is a hook called as ``before_save(step)`` at the top of
every save *attempt* (on the writer thread for async saves); raising
from it fails that attempt. It exists for deterministic fault injection
(:class:`apex_tpu.testing_faults.FaultInjector`) but any callable works.

``telemetry`` counts every incident class; attach an
:class:`apex_tpu.observability.MetricsRegistry` as ``metrics`` and the
same sites increment ``ckpt_<key>`` registry counters and emit
structured events, which ``python -m apex_tpu.monitor`` reconciles
key-for-key (:data:`apex_tpu.observability.report.
CHECKPOINT_INCIDENT_COUNTERS`).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Tuple

from apex_tpu.checkpoint.manifest import CheckpointCorruptionError

__all__ = ["RetryingCheckpointManager", "CheckpointSaveError"]


class CheckpointSaveError(RuntimeError):
    """A checkpoint save failed after exhausting its retry budget."""


class RetryingCheckpointManager:
    def __init__(self, manager, *, max_retries: int = 3,
                 backoff_base: float = 0.5, backoff_max: float = 8.0,
                 delete_corrupt: bool = True,
                 before_save: Optional[Callable[[int], None]] = None,
                 async_writes: bool = True, drain_on_force: bool = True,
                 metrics: Optional[Any] = None):
        self.manager = manager
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.delete_corrupt = bool(delete_corrupt)
        self.before_save = before_save
        self.drain_on_force = bool(drain_on_force)
        self.metrics = metrics
        self._async = bool(async_writes) and bool(
            getattr(manager, "supports_async", False))
        self._writer: Optional[ThreadPoolExecutor] = None
        self._futures: List[Tuple[int, Future]] = []
        self._futures_lock = threading.Lock()
        self.telemetry = {"save_attempts": 0, "save_retries": 0,
                          "save_failures": 0, "saves_abandoned": 0,
                          "restore_fallbacks": 0, "deleted_corrupt": 0,
                          "verify_failures": 0, "partials_cleaned": 0}

    # -- telemetry plumbing: one incident, two ledgers (+ the event) -------
    def _tick(self, key: str, n: int = 1) -> None:
        self.telemetry[key] += n
        if self.metrics is not None:
            self.metrics.inc("ckpt_" + key, n)

    def _event(self, name: str, *, level: str = "warning",
               **fields) -> None:
        from apex_tpu.utils.logging import get_logger, log_event

        log_event(get_logger(__name__), name, level=level, **fields)
        if self.metrics is not None:
            self.metrics.event(name, **fields)

    def _observe(self, name: str, value: float) -> None:
        if self.metrics is not None:
            self.metrics.observe(name, value)

    # -- partial-directory sweep -------------------------------------------
    def _sweep_partials(self) -> None:
        """Remove uncommitted step directories (failed/interrupted-save
        debris). In-flight async steps are excluded — their directories
        are mid-write, not debris."""
        cleanup = getattr(self.manager, "cleanup_partial", None)
        if cleanup is None:
            return
        with self._futures_lock:
            inflight = [s for s, f in self._futures if not f.done()]
        try:
            removed = cleanup(exclude=inflight)
        except Exception:  # noqa: BLE001 — sweep must never mask the save
            return
        for step in removed:
            self._tick("partials_cleaned")
            self._event("checkpoint_partial_cleaned", step=step)

    # -- save --------------------------------------------------------------
    def save(self, step: int, state: Any, *, force: bool = False,
             raise_on_failure: bool = False) -> bool:
        """Save with retries. Returns True once a save is committed (sync
        path) or accepted by the background writer (async path), False
        when the step was gated by ``save_interval_steps`` or (with
        ``raise_on_failure=False``) every retry failed — a failed
        periodic save is logged and counted, not fatal; the caller keeps
        training and the next interval tries again.

        A ``force=True`` (emergency / rollback re-save) first quiesces
        the async writer per ``drain_on_force``, then writes
        synchronously — its outcome must be known before the caller
        exits."""
        if force:
            self.drain(abandon=not self.drain_on_force)
        if self._async and not force and not raise_on_failure:
            if not self.manager.should_save(step, force=False):
                return False
            t0 = time.monotonic()
            snap = self.manager.snapshot(state)
            self._observe("ckpt_snapshot_blocked_s", time.monotonic() - t0)
            if self._writer is None:
                self._writer = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="ckpt-writer")
            fut = self._writer.submit(self._attempts, step,
                                      lambda frc: self.manager.
                                      write_snapshot(step, snap, force=frc))
            with self._futures_lock:
                self._futures = [(s, f) for s, f in self._futures
                                 if not f.done()] + [(step, fut)]
            return True
        return self._attempts(step, self._sync_write(step, state, force),
                              raise_on_failure=raise_on_failure)

    def _sync_write(self, step: int, state: Any,
                    force: bool) -> Callable[[bool], Any]:
        def write(effective_force: bool):
            if effective_force:
                # orbax force= only bypasses interval gating — an
                # existing step still raises StepAlreadyExists. A forced
                # save (emergency, retry, re-save after rollback)
                # replaces it.
                try:
                    if step in self.manager.all_steps():
                        self.manager.delete(step)
                except Exception:  # noqa: BLE001
                    pass
            saved = self.manager.save(step, state, force=effective_force)
            # surface async write errors here, inside the retry loop
            self.manager.wait_until_finished()
            return saved
        return write

    def _attempts(self, step: int,
                  write: Callable[[bool], Any], *,
                  force: bool = True,
                  raise_on_failure: bool = False) -> bool:
        """The retry loop — shared verbatim between the sync path (caller
        thread) and the async path (background writer), so backoff,
        telemetry, and the injection hook behave identically."""
        delay = self.backoff_base
        last_err: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            self._tick("save_attempts")
            try:
                if self.before_save is not None:
                    self.before_save(step)
                t0 = time.monotonic()
                result = write(force or attempt > 0)
                self._observe("ckpt_write_s", time.monotonic() - t0)
                return bool(result)
            except Exception as e:  # noqa: BLE001 — storage errors vary
                last_err = e
                self._sweep_partials()
                if attempt < self.max_retries:
                    self._tick("save_retries")
                    self._event("checkpoint_save_retry", step=step,
                                attempt=attempt, error=repr(e))
                    if delay > 0:
                        time.sleep(min(delay, self.backoff_max))
                    delay *= 2.0
        self._tick("save_failures")
        self._event("checkpoint_save_failed", step=step,
                    retries=self.max_retries, error=repr(last_err),
                    level="error")
        if raise_on_failure:
            raise CheckpointSaveError(
                f"checkpoint save at step {step} failed after "
                f"{self.max_retries} retries") from last_err
        return False

    # -- async writer lifecycle --------------------------------------------
    def drain(self, *, abandon: bool = False) -> None:
        """Quiesce the background writer deterministically. Default:
        block until every enqueued write has committed or exhausted its
        retries. ``abandon=True`` cancels writes still in the queue
        (counted as ``saves_abandoned``) and waits only for the one
        already running — whose commit stays atomic, so the step set
        after either mode is torn-free."""
        with self._futures_lock:
            futures = list(self._futures)
            self._futures = []
        remaining: List[Tuple[int, Future]] = []
        # cancel pass first: waiting on the running write before
        # cancelling would let the queue drain into the worker behind
        # our back, and nothing would be left to abandon
        for step, fut in futures:
            if abandon and fut.cancel():
                self._tick("saves_abandoned")
                self._event("checkpoint_save_abandoned", step=step)
            else:
                remaining.append((step, fut))
        for step, fut in remaining:
            try:
                fut.result()
            except Exception:  # noqa: BLE001 — counted inside _attempts
                pass

    @property
    def pending_saves(self) -> List[int]:
        """Steps currently enqueued on (or running in) the writer."""
        with self._futures_lock:
            return [s for s, f in self._futures if not f.done()]

    # -- restore -----------------------------------------------------------
    def restore_latest(self, template: Any) -> Optional[Tuple[int, Any]]:
        """Restore the newest readable checkpoint, walking older on
        corruption. Returns ``(step, state)`` or None when nothing is
        restorable."""
        return self.restore_before(None, template)

    def restore_before(self, step_exclusive: Optional[int],
                       template: Any) -> Optional[Tuple[int, Any]]:
        """Like :meth:`restore_latest` but only considers steps strictly
        below ``step_exclusive`` — the rollback path's "newest checkpoint
        from before the poisoned window". Drains the async writer first
        (a pending save must either commit or fail before the step set
        is read) and sweeps uncommitted debris so it is never adopted."""
        self.drain()
        self.manager.wait_until_finished()
        self._sweep_partials()
        steps = self.manager.all_steps()
        if step_exclusive is not None:
            steps = [s for s in steps if s < step_exclusive]
        for step in reversed(steps):
            try:
                return step, self.manager.restore_step(step, template)
            except Exception as e:  # noqa: BLE001 — corruption is varied
                if isinstance(e, CheckpointCorruptionError):
                    # detected by the integrity layer (checksum / torn
                    # manifest), not just an unreadable blob
                    self._tick("verify_failures")
                    self._event("checkpoint_verify_failed", step=step,
                                error=str(e), level="error")
                self._tick("restore_fallbacks")
                self._event("checkpoint_restore_fallback", step=step,
                            error=repr(e))
                if self.delete_corrupt:
                    try:
                        self.manager.delete(step)
                        self._tick("deleted_corrupt")
                        self._event("checkpoint_deleted_corrupt",
                                    step=step)
                    except Exception:  # noqa: BLE001
                        pass  # unreadable AND undeletable: just skip it
        return None

    # -- lifecycle ---------------------------------------------------------
    def wait_until_finished(self) -> None:
        self.drain()
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.drain()
        if self._writer is not None:
            self._writer.shutdown(wait=True)
            self._writer = None
        self.manager.close()
