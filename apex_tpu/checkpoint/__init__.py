"""Distributed checkpoint / resume.

Two on-disk formats behind one manager protocol:

- :class:`CheckpointManager` — the original orbax-backed rotating store
  (``save_checkpoint``/``load_checkpoint`` for one-shot paths). Restore
  requires orbax and prefers the save-time layout.
- :class:`ShardedCheckpointManager` — the sharded format of
  :mod:`apex_tpu.checkpoint.sharded`: per-shard ``.npy`` files addressed
  by (param-path, global-shard-index), a JSON manifest with global
  shapes/specs/per-shard sha256, and a COMMIT marker written last via
  atomic rename. Saves split into ``snapshot`` (blocking device→host)
  and ``write_snapshot`` (background-safe); restore is *elastic* — a
  template sharded over a different mesh (dp=4×tp=2 → dp=2×tp=4, or a
  single device) is reassembled from the saved shards and re-sharded on
  device.

:class:`RetryingCheckpointManager` wraps either with retries,
corruption fallback, partial-directory cleanup, and — for the sharded
format — an async background writer (:mod:`apex_tpu.checkpoint.retry`).
``python -m apex_tpu.checkpoint verify <dir>`` is the offline fsck
(:mod:`apex_tpu.checkpoint.verify`).
"""

from apex_tpu.checkpoint._orbax import (
    CheckpointManager,
    load_checkpoint,
    save_checkpoint,
)
from apex_tpu.checkpoint.manifest import (
    COMMIT_NAME,
    FORMAT_NAME,
    MANIFEST_NAME,
    CheckpointCorruptionError,
)
from apex_tpu.checkpoint.retry import (
    CheckpointSaveError,
    RetryingCheckpointManager,
)
from apex_tpu.checkpoint.sharded import HostSnapshot, ShardedCheckpointManager
from apex_tpu.checkpoint.verify import StepReport, verify_directory

__all__ = [
    "save_checkpoint",
    "load_checkpoint",
    "CheckpointManager",
    "ShardedCheckpointManager",
    "HostSnapshot",
    "RetryingCheckpointManager",
    "CheckpointSaveError",
    "CheckpointCorruptionError",
    "StepReport",
    "verify_directory",
    "MANIFEST_NAME",
    "COMMIT_NAME",
    "FORMAT_NAME",
]
