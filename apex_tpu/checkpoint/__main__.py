"""``python -m apex_tpu.checkpoint verify <dir>`` — checkpoint fsck."""

import sys

from apex_tpu.checkpoint.verify import main

if __name__ == "__main__":
    sys.exit(main())
