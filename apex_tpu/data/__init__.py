from apex_tpu.data.loader import PrefetchLoader
from apex_tpu.data.pipeline import (
    disk_image_batches,
    make_input_pipeline,
    write_synthetic_imagenet,
)

__all__ = [
    "PrefetchLoader",
    "disk_image_batches",
    "make_input_pipeline",
    "write_synthetic_imagenet",
]
