from apex_tpu.data.loader import PrefetchLoader

__all__ = ["PrefetchLoader"]
