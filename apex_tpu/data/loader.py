"""Prefetching host->device data pipeline.

Role of the reference examples' input pipelines (``examples/imagenet/
main_amp.py`` leans on DALI/torch DataLoader worker processes + pinned-memory
prefetch): keep the accelerator fed by overlapping host batch preparation
with device compute. TPU-native shape: worker threads pull from the user's
iterable, stage each batch, and a bounded C++ token queue
(:class:`apex_tpu.native.TokenQueue` — blocking condvar ring, no GIL churn
while waiting) hands them to the training loop, which issues
``jax.device_put`` (async on TPU) one batch ahead.

Python threads suffice for the worker pool: the heavy lifting inside a
typical batch fn (numpy slicing/augmentation, file reads) drops the GIL, and
the queue blocking happens in C++.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

from apex_tpu.native import TokenQueue

__all__ = ["PrefetchLoader"]


class PrefetchLoader:
    """Wrap an iterable of host batches with background prefetch.

    Args:
      batches: iterable (or callable returning an iterator) of pytrees of
        numpy arrays.
      prefetch: queue depth (batches staged ahead).
      num_workers: worker threads pulling from ``batches``. With >1 worker
        the source iterator is shared behind a lock (order is then
        arrival-order, as with torch DataLoader workers).
      map_fn: optional per-batch transform run in the worker threads
        OUTSIDE the source lock — this is where the heavy work (decode,
        augment, normalize) must live for ``num_workers > 1`` to buy
        parallelism; keep the source iterator itself cheap (e.g. yield
        indices/descriptors).
      device_put: optional function applied to each batch on the consumer
        side (e.g. ``jax.device_put`` / a sharded put); done one batch ahead
        so the transfer overlaps the previous step.
    """

    def __init__(self, batches: Iterable[Any] | Callable[[], Iterator[Any]],
                 *, prefetch: int = 2, num_workers: int = 1,
                 map_fn: Optional[Callable[[Any], Any]] = None,
                 device_put: Optional[Callable[[Any], Any]] = None):
        self._make_iter = (batches if callable(batches)
                           else lambda: iter(batches))
        self.prefetch = max(1, prefetch)
        self.num_workers = max(1, num_workers)
        self.map_fn = map_fn
        self.device_put = device_put

    def __iter__(self) -> Iterator[Any]:
        queue = TokenQueue(self.prefetch)
        slots: dict[int, Any] = {}
        counter = itertools.count()
        src = self._make_iter()
        src_lock = threading.Lock()
        done = threading.Event()
        live_workers = [self.num_workers]
        workers_lock = threading.Lock()
        errors: list[BaseException] = []

        def worker():
            try:
                while not done.is_set():
                    with src_lock:
                        try:
                            batch = next(src)
                        except StopIteration:
                            break
                        tok = next(counter)
                    if self.map_fn is not None:
                        batch = self.map_fn(batch)   # parallel region
                    slots[tok] = batch
                    if not queue.put(tok):   # queue closed under us
                        slots.pop(tok, None)
                        break
            except BaseException as e:       # surface in the consumer,
                errors.append(e)             # torch-DataLoader style
            finally:
                with workers_lock:
                    live_workers[0] -= 1
                    if live_workers[0] == 0:
                        queue.close()

        def consume():
            # threads start lazily on first next(): an iterator that is
            # created but never consumed must not leak workers
            threads = [threading.Thread(target=worker, daemon=True)
                       for _ in range(self.num_workers)]
            for t in threads:
                t.start()
            staged = None
            try:
                while True:
                    # surface worker failures promptly: with an infinite
                    # source the queue never closes, so waiting for drain
                    # would swallow the error and silently drop the batch
                    if errors:
                        raise errors[0]
                    tok = queue.get()
                    if tok is None:           # closed + drained
                        break
                    batch = slots.pop(tok)
                    if self.device_put is not None:
                        batch = self.device_put(batch)   # async transfer
                    if staged is not None:
                        yield staged
                    staged = batch
                if errors:
                    raise errors[0]
                if staged is not None:
                    yield staged
            finally:
                done.set()
                queue.close()
                for t in threads:
                    t.join(timeout=5)

        return consume()
