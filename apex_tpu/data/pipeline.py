"""On-disk image dataset + input pipeline over the prefetch loader.

The reference's imagenet example drives a real loader (DALI / torch
DataLoader with worker processes, ``examples/imagenet/main_amp.py``); this
module is the TPU-native equivalent input path: uint8 image shards on disk,
worker threads doing decode/augment/normalize (numpy releases the GIL), the
C++ token queue (:class:`apex_tpu.native.TokenQueue`) staging batches, and
``jax.device_put`` issued one batch ahead so the host->HBM transfer overlaps
device compute.

``write_synthetic_imagenet`` materializes an ImageFolder-shaped synthetic
dataset (the reference's example is data-format-agnostic too — torchvision
``ImageFolder``); real datasets drop in by replacing the shard reader.
"""

from __future__ import annotations

import json
import os
from typing import Iterator, Optional, Tuple

import numpy as np

from apex_tpu.data.loader import PrefetchLoader

__all__ = [
    "write_synthetic_imagenet",
    "disk_image_batches",
    "make_input_pipeline",
]

_MEAN = np.array([0.485, 0.456, 0.406], np.float32) * 255.0
_STD = np.array([0.229, 0.224, 0.225], np.float32) * 255.0


def write_synthetic_imagenet(root: str, *, num_shards: int = 4,
                             per_shard: int = 256, image_size: int = 64,
                             num_classes: int = 1000,
                             seed: int = 0) -> str:
    """Materialize a synthetic uint8 image dataset on disk (idempotent:
    existing valid datasets are left alone). Layout: ``meta.json`` +
    ``shard_%04d.npz`` with ``images`` [n, S, S, 3] uint8 and ``labels``
    [n] int32 — the on-disk role of the reference example's ImageNet tree."""
    os.makedirs(root, exist_ok=True)
    meta_path = os.path.join(root, "meta.json")
    wanted = {"num_shards": num_shards, "per_shard": per_shard,
              "image_size": image_size, "num_classes": num_classes,
              "seed": seed}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            have = json.load(f)
        if have != wanted:
            raise ValueError(
                f"dataset at {root} was written with {have}, requested "
                f"{wanted}; point --data-dir elsewhere or delete it "
                "(silently reusing mismatched shards would mislabel "
                "image sizes / clamp out-of-range labels)")
        return root
    rng = np.random.default_rng(seed)
    for i in range(num_shards):
        images = rng.integers(0, 256, (per_shard, image_size, image_size, 3),
                              dtype=np.uint8)
        labels = rng.integers(0, num_classes, (per_shard,), dtype=np.int32)
        np.savez(os.path.join(root, f"shard_{i:04d}.npz"),
                 images=images, labels=labels)
    with open(meta_path, "w") as f:
        json.dump(wanted, f)
    return root


def _augment(images: np.ndarray, rng: np.random.Generator,
             crop: Optional[int]) -> np.ndarray:
    """Light train-time augmentation in worker threads: random crop (when
    ``crop`` < stored size) + horizontal flip, then normalize uint8 ->
    fp32 with the standard ImageNet statistics."""
    n, s = images.shape[0], images.shape[1]
    if crop is not None and crop < s:
        ys = rng.integers(0, s - crop + 1, n)
        xs = rng.integers(0, s - crop + 1, n)
        images = np.stack([img[y:y + crop, x:x + crop]
                           for img, y, x in zip(images, ys, xs)])
    flip = rng.random(n) < 0.5
    images = images.copy()
    images[flip] = images[flip, :, ::-1]
    return (images.astype(np.float32) - _MEAN) / _STD


def _center_crop(images: np.ndarray, crop: int) -> np.ndarray:
    s = images.shape[1]
    if crop >= s:
        return images
    y = (s - crop) // 2
    return images[:, y:y + crop, y:y + crop]


class _ShardReader:
    """Open dataset + per-batch materialization. ``materialize`` is the
    heavy step (gather + augment/normalize); it is thread-safe and meant to
    run as the loader's ``map_fn`` in parallel worker threads."""

    def __init__(self, root: str, crop: Optional[int], train: bool,
                 seed: int):
        with open(os.path.join(root, "meta.json")) as f:
            self.meta = json.load(f)
        shards = [np.load(os.path.join(root, f"shard_{i:04d}.npz"))
                  for i in range(self.meta["num_shards"])]
        self.images = [s["images"] for s in shards]
        self.labels = [s["labels"] for s in shards]
        self.total = self.meta["num_shards"] * self.meta["per_shard"]
        self.crop = crop
        self.train = train
        self.seed = seed

    def index_batches(self, batch_size: int,
                      epochs: Optional[int]) -> Iterator[Tuple[np.ndarray,
                                                               int]]:
        """Cheap source iterator (safe under the loader's shared lock):
        yields ``(global indices [b], batch counter)``. Per-epoch global
        shuffle; drops the ragged tail (reference samplers' drop_last)."""
        order_rng = np.random.default_rng(self.seed)
        epoch, counter = 0, 0
        while epochs is None or epoch < epochs:
            idx = (order_rng.permutation(self.total) if self.train
                   else np.arange(self.total))
            for start in range(0, self.total - batch_size + 1, batch_size):
                yield idx[start:start + batch_size], counter
                counter += 1
            epoch += 1

    def materialize(self, item) -> Tuple[np.ndarray, np.ndarray]:
        take, counter = item
        sh, off = np.divmod(take, self.meta["per_shard"])
        imgs = np.stack([self.images[s][o] for s, o in zip(sh, off)])
        labs = np.stack([self.labels[s][o] for s, o in zip(sh, off)])
        if self.train:
            # per-batch rng keyed by the batch counter: deterministic
            # regardless of which worker thread materializes the batch
            rng = np.random.default_rng((self.seed + 1) * 100003 + counter)
            imgs = _augment(imgs, rng, self.crop)
        else:
            if self.crop is not None:
                imgs = _center_crop(imgs, self.crop)
            imgs = (imgs.astype(np.float32) - _MEAN) / _STD
        return imgs, labs.astype(np.int32)


def disk_image_batches(root: str, batch_size: int, *,
                       crop: Optional[int] = None, train: bool = True,
                       epochs: Optional[int] = None,
                       seed: int = 0) -> Iterator[Tuple[np.ndarray,
                                                        np.ndarray]]:
    """Yield ``(images fp32 [b, S, S, 3], labels int32 [b])`` batches from a
    :func:`write_synthetic_imagenet`-layout directory. Train mode random-
    crops + flips; eval mode center-crops; both normalize. Sequential
    convenience wrapper — :func:`make_input_pipeline` runs the same steps
    with parallel workers and prefetch."""
    reader = _ShardReader(root, crop, train, seed)
    for item in reader.index_batches(batch_size, epochs):
        yield reader.materialize(item)


def make_input_pipeline(root: str, batch_size: int, *, mesh=None,
                        crop: Optional[int] = None, train: bool = True,
                        epochs: Optional[int] = None,
                        prefetch: int = 2, num_workers: int = 2,
                        seed: int = 0) -> PrefetchLoader:
    """The full input path: disk shards -> worker-thread gather/augment
    (the loader's ``map_fn``, OUTSIDE the shared source lock, so
    ``num_workers`` buys real parallelism) -> C++ token queue ->
    ``jax.device_put`` one batch ahead. With ``mesh`` the put shards the
    batch dim over the ``data`` axis."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if mesh is not None:
        sharding = NamedSharding(mesh, PartitionSpec("data"))
        put = lambda b: (jax.device_put(b[0], sharding),
                         jax.device_put(b[1], sharding))
    else:
        put = jax.device_put
    reader = _ShardReader(root, crop, train, seed)
    return PrefetchLoader(
        lambda: reader.index_batches(batch_size, epochs),
        prefetch=prefetch, num_workers=num_workers,
        map_fn=reader.materialize, device_put=put)
