"""Fused dense layers.

Capability counterpart of ``apex/fused_dense/fused_dense.py:7-97`` +
``csrc/fused_dense_cuda.cu:173-260``: Linear+bias and
Linear+bias+GELU+Linear fused via cuBLASLt epilogues
(``CUBLASLT_EPILOGUE_{BIAS,GELU_AUX,BGRADB}``). XLA performs the same
epilogue fusion on TPU (bias add and GELU fuse into the matmul), so these
are thin functional modules with the reference's API and init semantics;
GELU uses the tanh approximation, matching the cuBLASLt epilogue.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

__all__ = [
    "FusedDense",
    "FusedDenseGeluDense",
    "fused_dense_function",
    "fused_dense_gelu_dense_function",
]


def fused_dense_function(x: jax.Array, weight: jax.Array,
                         bias: Optional[jax.Array] = None) -> jax.Array:
    """Reference ``_fused_dense``/``_dense_no_bias`` (``fused_dense.py:49-56``)."""
    out = x @ weight.T.astype(x.dtype)
    if bias is not None:
        out = out + bias.astype(out.dtype)
    return out


def fused_dense_gelu_dense_function(x, weight1, bias1, weight2, bias2):
    """Reference ``_fused_dense_gelu_dense`` (``fused_dense.py:59-61``)."""
    h = fused_dense_function(x, weight1, bias1)
    h = jax.nn.gelu(h, approximate=True)
    return fused_dense_function(h, weight2, bias2)


def _linear_init(key, out_features, in_features):
    # nn.Linear-style kaiming-uniform bound (reference modules allocate
    # empty params and reset like torch Linear)
    bound = 1.0 / (in_features ** 0.5)
    kw, kb = jax.random.split(key)
    w = jax.random.uniform(kw, (out_features, in_features),
                           minval=-bound, maxval=bound)
    b = jax.random.uniform(kb, (out_features,), minval=-bound, maxval=bound)
    return w, b


@dataclass
class FusedDense:
    """Reference ``FusedDense`` (``fused_dense.py:64-80``)."""

    in_features: int
    out_features: int
    bias: bool = True

    def init(self, key: jax.Array) -> Dict[str, jax.Array]:
        w, b = _linear_init(key, self.out_features, self.in_features)
        return {"weight": w, "bias": b} if self.bias else {"weight": w}

    def spec(self) -> Dict[str, PartitionSpec]:
        s = {"weight": PartitionSpec()}
        if self.bias:
            s["bias"] = PartitionSpec()
        return s

    def apply(self, params: Dict[str, jax.Array], x: jax.Array) -> jax.Array:
        return fused_dense_function(x, params["weight"], params.get("bias"))


@dataclass
class FusedDenseGeluDense:
    """Reference ``FusedDenseGeluDense`` (``fused_dense.py:82-95``)."""

    in_features: int
    intermediate_features: int
    out_features: int
    bias: bool = True

    def __post_init__(self):
        if not self.bias:
            # reference asserts bias=True (fused_dense.py:85-86)
            raise AssertionError(
                "DenseGeluDense module without bias is currently not supported")

    def init(self, key: jax.Array) -> Dict[str, jax.Array]:
        k1, k2 = jax.random.split(key)
        w1, b1 = _linear_init(k1, self.intermediate_features, self.in_features)
        w2, b2 = _linear_init(k2, self.out_features,
                              self.intermediate_features)
        return {"weight1": w1, "bias1": b1, "weight2": w2, "bias2": b2}

    def spec(self) -> Dict[str, PartitionSpec]:
        return {k: PartitionSpec()
                for k in ("weight1", "bias1", "weight2", "bias2")}

    def apply(self, params: Dict[str, jax.Array], x: jax.Array) -> jax.Array:
        return fused_dense_gelu_dense_function(
            x, params["weight1"], params["bias1"], params["weight2"],
            params["bias2"])
