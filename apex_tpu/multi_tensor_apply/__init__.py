from apex_tpu.multi_tensor_apply.multi_tensor_apply import (
    MultiTensorApply,
    multi_tensor_applier,
    BucketMeta,
    flatten_by_dtype,
    unflatten_by_dtype,
)

__all__ = [
    "MultiTensorApply",
    "multi_tensor_applier",
    "BucketMeta",
    "flatten_by_dtype",
    "unflatten_by_dtype",
]
