"""Batched whole-parameter-set elementwise dispatch.

The reference's ``multi_tensor_applier`` (``apex/multi_tensor_apply/
multi_tensor_apply.py:24-30`` + ``csrc/multi_tensor_apply.cuh:41-133``) exists
because CUDA pays per-kernel launch overhead: it chunks every tensor into
512-element blocks and batches ≤110 tensors / ≤320 blocks per launch.

Under XLA the whole step is one compiled program, so the launch-overhead
problem is gone — but the *capability* (apply one fused update across an
arbitrary list of differently-shaped tensors) remains useful for Pallas
kernels, which want a single large aligned buffer rather than hundreds of
oddly-shaped leaves. The TPU-native design flattens each tensor list into one
1-D buffer per dtype (padded to the 512-lane chunk multiple), applies ``op``
once per dtype bucket, and splits back.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Chunk granularity: keep buffers a multiple of (8 sublanes x 128 lanes) so a
# flat (N//1024, 1024) view is tile-aligned for fp32 Pallas kernels.
CHUNK = 1024


@dataclasses.dataclass(frozen=True)
class BucketMeta:
    """Shapes/sizes needed to split a flat bucket back into leaves."""

    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    dtype: Any
    padded_size: int

    @property
    def offsets(self) -> np.ndarray:
        return np.concatenate([[0], np.cumsum(self.sizes)])


def _flatten_list(tensors: Sequence[jax.Array]) -> Tuple[jax.Array, BucketMeta]:
    shapes = tuple(t.shape for t in tensors)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    total = sum(sizes)
    padded = ((total + CHUNK - 1) // CHUNK) * CHUNK
    dtype = tensors[0].dtype
    flat = jnp.concatenate([t.reshape(-1) for t in tensors])
    if padded != total:
        flat = jnp.pad(flat, (0, padded - total))
    return flat, BucketMeta(shapes, sizes, dtype, padded)


def _unflatten_list(flat: jax.Array, meta: BucketMeta) -> List[jax.Array]:
    out = []
    off = 0
    for shape, size in zip(meta.shapes, meta.sizes):
        out.append(jax.lax.dynamic_slice_in_dim(flat, off, size).reshape(shape))
        off += size
    return out


def flatten_by_dtype(tree: Any) -> Tuple[Dict[str, jax.Array], Dict[str, BucketMeta], Any]:
    """Flatten a pytree into one 1-D buffer per distinct leaf dtype."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups: Dict[str, List[int]] = {}
    for i, leaf in enumerate(leaves):
        groups.setdefault(str(jnp.asarray(leaf).dtype), []).append(i)
    buffers, metas = {}, {}
    for key, idxs in groups.items():
        flat, meta = _flatten_list([jnp.asarray(leaves[i]) for i in idxs])
        buffers[key] = flat
        metas[key] = dataclasses.replace(meta, shapes=meta.shapes, sizes=meta.sizes)
    index_map = {key: tuple(idxs) for key, idxs in groups.items()}
    return buffers, metas, (treedef, index_map, len(leaves))


def unflatten_by_dtype(buffers: Dict[str, jax.Array], metas: Dict[str, BucketMeta], aux: Any) -> Any:
    treedef, index_map, n_leaves = aux
    leaves: List[Any] = [None] * n_leaves
    for key, flat in buffers.items():
        parts = _unflatten_list(flat, metas[key])
        for leaf, i in zip(parts, index_map[key]):
            leaves[i] = leaf
    return jax.tree_util.tree_unflatten(treedef, leaves)


class MultiTensorApply:
    """Parity-API dispatcher: ``multi_tensor_applier(op, tensor_lists, *args)``.

    ``op`` receives one flat 1-D fp32-view buffer per tensor list (all lists
    flattened with identical layout) plus ``*args`` and returns the updated
    buffers (same arity). Chunking metadata handling — the job of
    ``multi_tensor_apply.cuh:19-26`` — reduces to a concat/pad here.
    """

    def __init__(self, chunk_size: int = CHUNK):
        self.chunk_size = chunk_size

    def __call__(
        self,
        op: Callable[..., Tuple[jax.Array, ...]],
        tensor_lists: Sequence[Sequence[jax.Array]],
        *args,
    ) -> List[List[jax.Array]]:
        flats, metas = [], None
        for lst in tensor_lists:
            flat, meta = _flatten_list(list(lst))
            flats.append(flat)
            metas = metas or meta
        outs = op(*flats, *args)
        if isinstance(outs, jax.Array):
            outs = (outs,)
        result = []
        for out in outs:
            m = dataclasses.replace(metas, dtype=out.dtype)
            result.append(_unflatten_list(out, m))
        return result


multi_tensor_applier = MultiTensorApply()
