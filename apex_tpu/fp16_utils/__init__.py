from apex_tpu.fp16_utils.fp16util import (
    convert_network,
    network_to_half,
    prep_param_lists,
    master_params_to_model_params,
    model_grads_to_master_grads,
)
from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer
from apex_tpu.fp16_utils.loss_scaler import LossScaler, DynamicLossScaler

__all__ = [
    "convert_network",
    "network_to_half",
    "prep_param_lists",
    "master_params_to_model_params",
    "model_grads_to_master_grads",
    "FP16_Optimizer",
    "LossScaler",
    "DynamicLossScaler",
]
