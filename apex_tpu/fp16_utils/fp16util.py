"""Manual master-weight mixed-precision utilities.

Functional counterparts of ``apex/fp16_utils/fp16util.py:22-176``. Parameters
are pytrees, not module attributes, so "convert network" means casting leaves —
with an optional predicate to keep normalization parameters in fp32
(``BN_convert_float`` capability, ``fp16util.py:60-71``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.utils.tree import tree_cast


def _default_keep_fp32(path: Tuple, leaf) -> bool:
    """Keep batchnorm/layernorm scale+bias in fp32 by path-name convention."""
    names = "/".join(str(getattr(p, "key", getattr(p, "name", p))) for p in path).lower()
    return any(k in names for k in ("batchnorm", "bn", "layernorm", "ln", "norm"))


def convert_network(
    params: Any,
    dtype=jnp.bfloat16,
    keep_fp32: Optional[Callable[[Tuple, Any], bool]] = _default_keep_fp32,
) -> Any:
    """Cast floating leaves to ``dtype``, keeping norm params fp32
    (reference: ``convert_network``, ``fp16util.py:44-58``)."""

    def _cast(path, x):
        if not (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)):
            return x
        if keep_fp32 is not None and keep_fp32(path, x):
            return x.astype(jnp.float32)
        return x.astype(dtype)

    return jax.tree_util.tree_map_with_path(_cast, params)


def network_to_half(params: Any, dtype=jnp.bfloat16) -> Any:
    """Cast every floating leaf (reference: ``network_to_half``, ``fp16util.py:22``)."""
    return tree_cast(params, dtype)


def prep_param_lists(params: Any) -> Tuple[Any, Any]:
    """Return ``(model_params, fp32_master_copy)``
    (reference: ``prep_param_lists``, ``fp16util.py:92-141``)."""
    return params, tree_cast(params, jnp.float32)


def model_grads_to_master_grads(model_grads: Any) -> Any:
    """``fp16util.py:143-160``."""
    return tree_cast(model_grads, jnp.float32)


def master_params_to_model_params(master_params: Any, model_params: Any) -> Any:
    """Cast fp32 master values back into the model params' dtypes
    (``fp16util.py:162-176``)."""
    return jax.tree_util.tree_map(
        lambda m, p: m.astype(p.dtype), master_params, model_params
    )
