"""Legacy loss scalers (``apex/fp16_utils/loss_scaler.py:10,49`` capability).

Thin aliases over the modern functional scaler in ``apex_tpu.amp.scaler``.
"""

from __future__ import annotations

from apex_tpu.amp.scaler import LossScaler as _ModernScaler


class LossScaler(_ModernScaler):
    """Static scaler (reference: ``loss_scaler.py:10``)."""

    def __init__(self, scale: float = 1.0):
        super().__init__(loss_scale=scale)


class DynamicLossScaler(_ModernScaler):
    """Dynamic scaler (reference: ``loss_scaler.py:49``; factor 2, window 1000)."""

    def __init__(self, init_scale: float = 2.0 ** 32, scale_factor: float = 2.0,
                 scale_window: int = 1000):
        super().__init__("dynamic", init_scale=init_scale,
                         scale_factor=scale_factor, scale_window=scale_window)
