"""Legacy ``FP16_Optimizer`` wrapper (``apex/fp16_utils/fp16_optimizer.py:13``).

Functional re-design: wraps any apex_tpu optimizer, holding fp32 master
params + a loss scaler, exposing ``backward``-less JAX flow:

    opt = FP16_Optimizer(FusedAdam(lr=1e-3), static_loss_scale="dynamic")
    state = opt.init(model_params_bf16)
    new_model_params, state = opt.step(grads_bf16, state)

On overflow the step is skipped on-device (reference: ``step``/``backward``,
``fp16_optimizer.py:275-436``).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, LossScalerState
from apex_tpu.utils.tree import tree_cast


class FP16OptimizerState(NamedTuple):
    master_params: Any
    inner_state: Any
    scaler_state: LossScalerState


class FP16_Optimizer:
    def __init__(self, inner, static_loss_scale: Any = 1.0, dynamic_loss_scale: bool = False,
                 dynamic_loss_args: dict = None):
        if dynamic_loss_scale:
            self.scaler = LossScaler("dynamic", **(dynamic_loss_args or {}))
        else:
            self.scaler = LossScaler(static_loss_scale)
        self.inner = inner

    def init(self, model_params: Any) -> FP16OptimizerState:
        master = tree_cast(model_params, jnp.float32)
        return FP16OptimizerState(
            master_params=master,
            inner_state=self.inner.init(master),
            scaler_state=self.scaler.init(),
        )

    def scale_loss(self, loss: jax.Array, state: FP16OptimizerState) -> jax.Array:
        return self.scaler.scale(loss, state.scaler_state)

    def step(self, model_grads: Any, state: FP16OptimizerState,
             model_params: Any) -> Tuple[Any, FP16OptimizerState]:
        grads, found_inf = self.scaler.unscale(
            tree_cast(model_grads, jnp.float32), state.scaler_state
        )
        # found_inf makes the inner step a no-op on device (base-class contract)
        new_master, new_inner = self.inner.step(
            grads, state.master_params, state.inner_state, found_inf=found_inf
        )
        new_scaler = self.scaler.update(state.scaler_state, found_inf)
        new_model = jax.tree_util.tree_map(
            lambda m, p: m.astype(p.dtype), new_master, model_params
        )
        return new_model, FP16OptimizerState(new_master, new_inner, new_scaler)
