"""FusedAdam / AdamW.

Hyperparameter semantics of ``apex.optimizers.FusedAdam``
(``apex/optimizers/fused_adam.py:68-305``; CUDA functor
``csrc/multi_tensor_adam.cu:23-127``): ``adam_w_mode`` selects decoupled
weight decay (MODE_ADAMW) vs L2 regularization (MODE_L2,
``multi_tensor_adam.cu:16-19``), ``bias_correction`` toggles the 1/(1-βᵗ)
factors, and the capturable-mode ``grad_scale``/``found_inf`` flow is the
base-class contract — on TPU every step is "capturable": no host syncs.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from apex_tpu.optimizers.base import FusedOptimizer, tree_map, tree_map_multi


class FusedAdam(FusedOptimizer):
    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 adam_w_mode: bool = True, weight_decay: float = 0.0,
                 amsgrad: bool = False, master_weights: bool = False,
                 capturable: bool = False, weight_decay_mask=None):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant "
                               "(parity with apex/optimizers/fused_adam.py:112-113)")
        super().__init__(lr, weight_decay, master_weights,
                         weight_decay_mask)
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.capturable = capturable  # kept for API parity; always true on TPU

    def _init_slots(self, params32):
        return {
            "exp_avg": tree_map(jnp.zeros_like, params32),
            "exp_avg_sq": tree_map(jnp.zeros_like, params32),
        }

    def _update(self, g32, p32, slots, step, lr, wds=None):
        b1, b2 = self.betas
        t = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** t if self.bias_correction else 1.0
        bc2 = 1.0 - b2 ** t if self.bias_correction else 1.0
        # ``wds`` override: the ZeRO flat-buffer subclass passes per-element
        # decay arrays (leaf masks flattened to buffer segments) instead of
        # the per-leaf floats
        wds = self._wd_leaves(p32) if wds is None else wds

        def upd(g, p, m, v, wd):
            apply_wd = not isinstance(wd, float) or wd != 0.0
            if not self.adam_w_mode and apply_wd:
                g = g + wd * p
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * g * g
            update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
            if self.adam_w_mode and apply_wd:
                update = update + wd * p
            return p - lr * update, m, v

        new_p, new_m, new_v = tree_map_multi(
            upd, 3, g32, p32, slots["exp_avg"], slots["exp_avg_sq"], wds)
        return new_p, {"exp_avg": new_m, "exp_avg_sq": new_v}


def FusedAdamW(lr: float = 1e-3, **kw) -> FusedAdam:
    kw.setdefault("adam_w_mode", True)
    return FusedAdam(lr=lr, **kw)
