"""Shared machinery for fused optimizers.

The reference's optimizers exist to collapse hundreds of per-tensor CUDA
launches into a few ``multi_tensor_*`` kernels (SURVEY.md §2.1). Under XLA the
whole ``step`` is one compiled program and elementwise pytree math fuses into
a handful of loops, so the *default* path here is plain fp32 tree math; the
``multi_tensor_apply`` flat-bucket path exists for Pallas-kernel dispatch on
very fragmented parameter sets.

Conventions shared with the reference:

- ``master_weights=True`` keeps an fp32 copy in optimizer state and writes
  params back in their own dtype (amp O2, ``fused_adam.py:68-126``).
- ``grad_scale`` / ``found_inf`` arguments mirror the capturable mode
  (``apex/optimizers/fused_adam.py:199-263``): unscaling happens inside the
  step and an overflow turns the whole update into a no-op **on device**.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


def tree_map(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def tree_map_multi(f, n_out: int, *trees):
    """``tree_map`` for a function returning an ``n_out``-tuple: returns
    ``n_out`` trees with the structure of ``trees[0]``."""
    leaves, treedef = jax.tree_util.tree_flatten(trees[0])
    rest = [jax.tree_util.tree_leaves(t) for t in trees[1:]]
    outs = [f(*args) for args in zip(leaves, *rest)]
    return tuple(
        jax.tree_util.tree_unflatten(treedef, [o[i] for o in outs])
        for i in range(n_out)
    )


def f32(tree):
    return tree_map(lambda x: x.astype(jnp.float32), tree)


def like(tree, ref):
    return tree_map(lambda x, r: x.astype(r.dtype), tree, ref)


def select_tree(pred, on_true, on_false):
    return tree_map(lambda a, b: jnp.where(pred, a, b), on_true, on_false)


class FusedOptimizer:
    """Base: ``init(params) -> state``; ``step(grads, params, state) -> (params, state)``.

    Subclasses implement ``_update(g32, p32, slots, step, lr) -> (new_p32, new_slots)``
    where ``slots`` is the subclass-specific moment pytree bundle.
    """

    def __init__(self, lr: float, weight_decay: float = 0.0,
                 master_weights: bool = False, weight_decay_mask=None):
        self.lr = lr
        self.weight_decay = weight_decay
        self.master_weights = master_weights
        # param-groups parity (torch optimizers put norm/bias params in a
        # wd=0 group): a pytree of bools matching params, or a callable
        # params -> bool pytree; True = decay this leaf
        self.weight_decay_mask = weight_decay_mask

    def _wd_leaves(self, params_tree):
        """Per-leaf weight decay: ``self.weight_decay`` where the mask keeps
        it, 0.0 elsewhere. Leaves are python floats so subclasses keep their
        trace-time ``wd != 0`` branches per leaf."""
        if self.weight_decay_mask is None:
            return tree_map(lambda _: self.weight_decay, params_tree)
        mask = (self.weight_decay_mask(params_tree)
                if callable(self.weight_decay_mask)
                else self.weight_decay_mask)
        # joint map: a mask whose structure mismatches params fails loudly
        return tree_map(
            lambda keep, _: self.weight_decay if keep else 0.0,
            mask, params_tree)

    # -- subclass API -----------------------------------------------------
    def _init_slots(self, params32) -> Any:
        raise NotImplementedError

    def _update(self, g32, p32, slots, step, lr) -> Tuple[Any, Any]:
        raise NotImplementedError

    # -- public API -------------------------------------------------------
    def init(self, params) -> dict:
        p32 = f32(params)
        state = {
            "step": jnp.zeros((), jnp.int32),
            "slots": self._init_slots(p32),
        }
        if self.master_weights:
            # force a distinct buffer even when params are already fp32
            # (f32() is a no-op then) so donating params + opt state together
            # never aliases the same buffer twice
            state["master"] = tree_map(
                lambda p: jnp.array(p, jnp.float32, copy=True), params)
        return state

    def step(self, grads, params, state, *, lr: Optional[Any] = None,
             grad_scale: Optional[jax.Array] = None,
             found_inf: Optional[jax.Array] = None) -> Tuple[Any, dict]:
        lr = self.lr if lr is None else lr
        step = state["step"] + 1
        g32 = f32(grads)
        if grad_scale is not None:
            g32 = tree_map(lambda g: g * (1.0 / grad_scale), g32)
        p32 = state.get("master", f32(params))
        new_p32, new_slots = self._update(g32, p32, state["slots"], step, lr)
        if found_inf is not None:
            new_p32 = select_tree(found_inf, p32, new_p32)
            new_slots = select_tree(found_inf, state["slots"], new_slots)
            step = jnp.where(found_inf, state["step"], step)
        new_state = {"step": step, "slots": new_slots}
        if self.master_weights:
            new_state["master"] = new_p32
        return like(new_p32, params), new_state

    def state_spec(self, params, param_spec):
        """PartitionSpec pytree for ``init(params)``'s state, derived from the
        params' spec: param-shaped slot leaves inherit the param's spec,
        per-tensor scalars (e.g. NovoGrad's second moments) and the step
        counter are replicated. Used to shard optimizer state under pjit/
        shard_map (and, over the data axis, for the ZeRO-sharded variants).
        """
        from jax.sharding import PartitionSpec

        shapes = jax.eval_shape(self.init, params)

        def sub(shape_tree):
            if shape_tree is None:
                return None
            return tree_map(
                lambda sh, sp: sp if sh.ndim > 0 else PartitionSpec(),
                shape_tree, param_spec)

        spec = {
            "step": PartitionSpec(),
            "slots": {k: sub(v) for k, v in shapes["slots"].items()},
        }
        if self.master_weights:
            spec["master"] = param_spec
        return spec

    # -- optax interop ----------------------------------------------------
    def as_gradient_transformation(self):
        """Expose as an ``optax.GradientTransformation`` (updates = new - old)."""
        import optax

        def init_fn(params):
            return self.init(params)

        def update_fn(grads, state, params=None):
            new_params, new_state = self.step(grads, params, state)
            updates = tree_map(lambda n, p: (n - p.astype(n.dtype)), new_params, params)
            return updates, new_state

        return optax.GradientTransformation(init_fn, update_fn)
