"""FusedAdagrad.

Semantics of ``apex.optimizers.FusedAdagrad``
(``apex/optimizers/fused_adagrad.py:43-121``; kernel
``csrc/multi_tensor_adagrad.cu``): ``h += g²; p -= lr * g / (sqrt(h) + eps)``
with "modern" decoupled weight decay ``adagrad_w_mode``.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.optimizers.base import FusedOptimizer, tree_map, tree_map_multi


class FusedAdagrad(FusedOptimizer):
    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0, adagrad_w_mode: bool = False,
                 master_weights: bool = False, weight_decay_mask=None):
        super().__init__(lr, weight_decay, master_weights,
                         weight_decay_mask)
        self.eps = eps
        self.adagrad_w_mode = adagrad_w_mode

    def _init_slots(self, params32):
        return {"sum": tree_map(jnp.zeros_like, params32)}

    def _update(self, g32, p32, slots, step, lr):
        wds = self._wd_leaves(p32)

        def upd(g, p, h, wd):
            if not self.adagrad_w_mode and wd != 0.0:
                g = g + wd * p
            h = h + g * g
            update = g / (jnp.sqrt(h) + self.eps)
            if self.adagrad_w_mode and wd != 0.0:
                update = update + wd * p
            return p - lr * update, h

        new_p, new_h = tree_map_multi(upd, 2, g32, p32, slots["sum"],
                                      wds)
        return new_p, {"sum": new_h}
