"""FusedLAMB — NVLAMB with global grad-norm pre-scaling.

Semantics of ``apex.optimizers.FusedLAMB`` (``apex/optimizers/fused_lamb.py:
96-215``): phase 1 computes the *global* L2 norm over all gradients
(``multi_tensor_l2norm``) and derives a clip factor from ``max_grad_norm``;
phase 2 (``csrc/multi_tensor_lamb.cu:413``) does the Adam-style moment update
followed by the per-tensor trust-ratio step
``p -= lr * (||p|| / ||update||) * update``.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from apex_tpu.optimizers.base import FusedOptimizer, tree_map, tree_map_multi
from apex_tpu.utils.tree import global_norm


class FusedLAMB(FusedOptimizer):
    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.01, amsgrad: bool = False,
                 adam_w_mode: bool = True, grad_averaging: bool = True,
                 max_grad_norm: float = 1.0, trust_clip: bool = False,
                 always_adapt: bool = False, master_weights: bool = False,
                 weight_decay_mask=None):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant")
        super().__init__(lr, weight_decay, master_weights,
                         weight_decay_mask)
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.trust_clip = trust_clip
        self.always_adapt = always_adapt

    def _init_slots(self, params32):
        return {
            "exp_avg": tree_map(jnp.zeros_like, params32),
            "exp_avg_sq": tree_map(jnp.zeros_like, params32),
        }

    def _update(self, g32, p32, slots, step, lr):
        b1, b2 = self.betas
        t = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** t if self.bias_correction else 1.0
        bc2 = 1.0 - b2 ** t if self.bias_correction else 1.0
        beta3 = 1.0 - b1 if self.grad_averaging else 1.0
        wds = self._wd_leaves(p32)

        # phase 1: global grad norm → clip factor (fused_lamb.py:167-185)
        gnorm = global_norm(g32)
        clip = jnp.where(
            (self.max_grad_norm > 0.0) & (gnorm > self.max_grad_norm),
            gnorm / self.max_grad_norm, 1.0)

        def upd(g, p, m, v, wd):
            g = g / clip
            if not self.adam_w_mode and wd != 0.0:
                g = g + wd * p
            m = b1 * m + beta3 * g
            v = b2 * v + (1.0 - b2) * g * g
            update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
            if self.adam_w_mode and wd != 0.0:
                update = update + wd * p
            # trust ratio (multi_tensor_lamb.cu stage 2)
            if wd != 0.0 or self.always_adapt:
                w_norm = jnp.sqrt(jnp.sum(p * p))
                u_norm = jnp.sqrt(jnp.sum(update * update))
                ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
                if self.trust_clip:
                    ratio = jnp.minimum(ratio, 1.0)
            else:
                ratio = 1.0
            return p - lr * ratio * update, m, v

        new_p, new_m, new_v = tree_map_multi(
            upd, 3, g32, p32, slots["exp_avg"], slots["exp_avg_sq"], wds)
        return new_p, {"exp_avg": new_m, "exp_avg_sq": new_v}
