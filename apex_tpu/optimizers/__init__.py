"""Fused optimizers (capability of ``apex/optimizers``)."""

from apex_tpu.optimizers.base import FusedOptimizer
from apex_tpu.optimizers.fused_adam import FusedAdam, FusedAdamW
from apex_tpu.optimizers.fused_lamb import FusedLAMB
from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad
from apex_tpu.optimizers.fused_adagrad import FusedAdagrad
from apex_tpu.optimizers.fused_mixed_precision_lamb import FusedMixedPrecisionLamb
from apex_tpu.optimizers.distributed_fused_adam import DistributedFusedAdam
from apex_tpu.optimizers.distributed_fused_lamb import DistributedFusedLAMB

__all__ = [
    "DistributedFusedAdam",
    "DistributedFusedLAMB",
    "FusedOptimizer",
    "FusedAdam",
    "FusedAdamW",
    "FusedLAMB",
    "FusedSGD",
    "FusedNovoGrad",
    "FusedAdagrad",
    "FusedMixedPrecisionLamb",
]
