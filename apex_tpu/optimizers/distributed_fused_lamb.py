"""ZeRO-sharded LAMB.

TPU-native counterpart of ``apex/contrib/optimizers/distributed_fused_lamb.py``
(``DistributedFusedLAMB`` at ``:24-108``): NVLAMB with reduce-scattered
gradients, sharded fp32 master/moment state, and an all-gather of updated
params over the data mesh axis. Gather precision is the optimizer's choice,
not the transfer layer's (XLA does not compress collectives): the inherited
``gather_dtype`` moves params in the 16-bit param dtype by default when the
leaves allow it, and ``gather_dtype=jnp.float8_e5m2`` is the analog of the
reference's ``e5m2_allgather=True`` compressed all-gather
(``distributed_fused_lamb.py:105,340,389``).

What makes sharded LAMB harder than sharded Adam: the trust ratio needs
*per-parameter-tensor* norms ``||p|| / ||update||``, but each rank holds only
a slice of the flat buffer, and leaf boundaries do not align with shard
boundaries. Solution: a static segment-id map over the flat layout
(``jax.ops.segment_sum`` of the local partial sums of squares, one ``psum``
over the data axis), mirroring how the reference's
``multi_tensor_distopt_lamb`` kernels accumulate per-tensor partials across
chunks before the global reduction.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.optimizers.distributed_fused_adam import DistributedFusedAdam
from apex_tpu.transformer.parallel_state import DATA_AXIS

__all__ = ["DistributedFusedLAMB"]


class DistributedFusedLAMB(DistributedFusedAdam):
    """LAMB with data-parallel-sharded state.

    Hyperparameters mirror :class:`apex_tpu.optimizers.FusedLAMB` (NVLAMB:
    global grad-norm clip factor, Adam moments, per-tensor trust ratio);
    state layout, ``init`` and ``state_spec`` are inherited from
    :class:`DistributedFusedAdam` (same three fp32 slots).
    """

    def __init__(self, lr: float = 1e-3, *, num_shards: Optional[int] = None,
                 axis_name: str = DATA_AXIS, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.01, adam_w_mode: bool = True,
                 grad_averaging: bool = True, max_grad_norm: float = 1.0,
                 trust_clip: bool = False, always_adapt: bool = False,
                 weight_decay_mask=None, gather_dtype=None):
        super().__init__(lr=lr, num_shards=num_shards, axis_name=axis_name,
                         bias_correction=bias_correction, betas=betas,
                         eps=eps, adam_w_mode=adam_w_mode,
                         weight_decay=weight_decay,
                         weight_decay_mask=weight_decay_mask,
                         gather_dtype=gather_dtype)
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.trust_clip = trust_clip
        self.always_adapt = always_adapt

    # -- step ----------------------------------------------------------------

    def step(self, grads, params, state, *, lr: Optional[Any] = None,
             grad_scale: Optional[jax.Array] = None,
             found_inf: Optional[jax.Array] = None) -> Tuple[Any, dict]:
        lr = self.lr if lr is None else lr
        g, sharded = self._sync_grads(grads, grad_scale)
        chunk = g.shape[0]

        # phase 1: global grad norm of the synced grad -> clip factor
        # (reference two-phase NVLAMB, fused_lamb.py:167-185)
        gsumsq = jnp.sum(g * g)
        if sharded:
            gsumsq = lax.psum(gsumsq, self.axis_name)
        gnorm = jnp.sqrt(gsumsq)
        clip = jnp.where(
            (self.max_grad_norm > 0.0) & (gnorm > self.max_grad_norm),
            gnorm / self.max_grad_norm, 1.0)
        g = g / clip

        ids, n_leaves = self._local_segment_ids(params, chunk, sharded)

        # phase 2: Adam moments + per-tensor trust-ratio step on the shard
        b1, b2 = self.betas
        step_c = state["step"] + 1
        t = step_c.astype(jnp.float32)
        bc1 = 1.0 - b1 ** t if self.bias_correction else 1.0
        bc2 = 1.0 - b2 ** t if self.bias_correction else 1.0
        beta3 = 1.0 - b1 if self.grad_averaging else 1.0
        # a mask with wd=0 decays nothing — skip the per-element machinery
        masked = self.weight_decay_mask is not None and self.weight_decay != 0.0
        if masked:
            wd_vals = self._wd_segment_values(params, n_leaves)  # [nseg]
            wd = wd_vals[ids]                # per-element decay multipliers
            apply_wd = True
        else:
            wd = self.weight_decay
            apply_wd = wd != 0.0

        shard_shape = state["master"].shape
        p = state["master"].reshape(-1)
        m = state["exp_avg"].reshape(-1)
        v = state["exp_avg_sq"].reshape(-1)

        if not self.adam_w_mode and apply_wd:
            g = g + wd * p
        m = b1 * m + beta3 * g
        v = b2 * v + (1.0 - b2) * g * g
        update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
        if self.adam_w_mode and apply_wd:
            update = update + wd * p

        if apply_wd or self.always_adapt:
            nseg = n_leaves + 1          # +1 dead segment for padding
            w_sumsq = jax.ops.segment_sum(p * p, ids, num_segments=nseg)
            u_sumsq = jax.ops.segment_sum(update * update, ids,
                                          num_segments=nseg)
            if sharded:
                w_sumsq = lax.psum(w_sumsq, self.axis_name)
                u_sumsq = lax.psum(u_sumsq, self.axis_name)
            w_norm = jnp.sqrt(w_sumsq)
            u_norm = jnp.sqrt(u_sumsq)
            ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                              w_norm / jnp.maximum(u_norm, 1e-30), 1.0)
            if self.trust_clip:
                ratio = jnp.minimum(ratio, 1.0)
            if masked and not self.always_adapt:
                # per-leaf parity: undecayed leaves skip trust adaptation
                # (FusedLAMB's ``wd != 0 or always_adapt`` branch per leaf)
                ratio = jnp.where(wd_vals != 0.0, ratio, 1.0)
            scale_e = ratio[ids]
        else:
            scale_e = 1.0

        new_p = p - lr * scale_e * update
        new_m, new_v = m, v
        if found_inf is not None:
            new_p = jnp.where(found_inf, p, new_p)
            new_m = jnp.where(found_inf, state["exp_avg"].reshape(-1), new_m)
            new_v = jnp.where(found_inf, state["exp_avg_sq"].reshape(-1),
                              new_v)
            step_c = jnp.where(found_inf, state["step"], step_c)

        new_params = self._gather_params(new_p, params, sharded)
        new_state = {
            "step": step_c,
            "master": new_p.reshape(shard_shape),
            "exp_avg": new_m.reshape(shard_shape),
            "exp_avg_sq": new_v.reshape(shard_shape),
        }
        return new_params, new_state
