"""FusedMixedPrecisionLamb.

Semantics of ``apex.optimizers.FusedMixedPrecisionLamb``
(``apex/optimizers/fused_mixed_precision_lamb.py:10-260``): LAMB with fp32
master weights held by the optimizer, traced-tensor ``lr``/``step`` (the
reference keeps them as device tensors for CUDA-graph capture; here every
hyperparameter is already traceable), and in-step grad unscaling via
``grad_scale``/``found_inf`` (kernel ``multi_tensor_lamb_mp``,
``csrc/multi_tensor_lamb_mp.cu:496``).
"""

from __future__ import annotations

from apex_tpu.optimizers.fused_lamb import FusedLAMB


class FusedMixedPrecisionLamb(FusedLAMB):
    def __init__(self, lr: float = 1e-3, step: int = 0, bias_correction: bool = True,
                 betas=(0.9, 0.999), eps: float = 1e-6, weight_decay: float = 0.01,
                 amsgrad: bool = False, grad_averaging: bool = True,
                 max_grad_norm: float = 1.0, use_nvlamb: bool = False,
                 reduced_precision_dtype=None):
        super().__init__(
            lr=lr, bias_correction=bias_correction, betas=betas, eps=eps,
            weight_decay=weight_decay, amsgrad=amsgrad,
            grad_averaging=grad_averaging, max_grad_norm=max_grad_norm,
            always_adapt=use_nvlamb, master_weights=True)
        self.reduced_precision_dtype = reduced_precision_dtype
