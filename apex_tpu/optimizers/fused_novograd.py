"""FusedNovoGrad — per-tensor second-moment norms.

Semantics of ``apex.optimizers.FusedNovoGrad`` (``apex/optimizers/
fused_novograd.py:67-214``; kernel ``csrc/multi_tensor_novograd.cu:188``):
the second moment is a *scalar per tensor* (norm of the gradient), options
``reg_inside_moment``, ``grad_averaging``, ``norm_type`` (0 = inf, 2 = L2),
``init_zero``.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from apex_tpu.optimizers.base import FusedOptimizer, tree_map, tree_map_multi


class FusedNovoGrad(FusedOptimizer):
    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.95, 0.98), eps: float = 1e-8,
                 weight_decay: float = 0.0, grad_averaging: bool = False,
                 amsgrad: bool = False, reg_inside_moment: bool = False,
                 norm_type: int = 2, init_zero: bool = False,
                 master_weights: bool = False,
                 weight_decay_mask=None):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad variant")
        if norm_type not in (0, 2):
            raise RuntimeError(f"FusedNovoGrad only supports l2/inf norm now, got {norm_type}")
        super().__init__(lr, weight_decay, master_weights,
                         weight_decay_mask)
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.grad_averaging = grad_averaging
        self.reg_inside_moment = reg_inside_moment
        self.norm_type = norm_type
        self.init_zero = init_zero

    def _norm(self, g):
        if self.norm_type == 2:
            return jnp.sqrt(jnp.sum(g * g))
        return jnp.max(jnp.abs(g))

    def _init_slots(self, params32):
        return {
            "exp_avg": tree_map(jnp.zeros_like, params32),
            # per-tensor scalar second moment (fused_novograd.py:188-200)
            "exp_avg_sq": tree_map(lambda p: jnp.zeros((), jnp.float32), params32),
        }

    def _update(self, g32, p32, slots, step, lr):
        b1, b2 = self.betas
        t = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** t if self.bias_correction else 1.0
        bc2 = 1.0 - b2 ** t if self.bias_correction else 1.0
        beta3 = 1.0 - b1 if self.grad_averaging else 1.0
        wds = self._wd_leaves(p32)
        first = step == 1

        def upd(g, p, m, v, wd):
            if wd != 0.0 and self.reg_inside_moment:
                g = g + wd * p
            gnorm = self._norm(g)
            stat = gnorm * gnorm if self.norm_type == 2 else gnorm
            ema = b2 * v + (1.0 - b2) * stat
            # first step: v <- stat, unless init_zero keeps the EMA form
            v_new = jnp.where(first & (not self.init_zero), stat, ema)
            vhat = v_new / bc2
            denom = (jnp.sqrt(vhat) if self.norm_type == 2 else vhat) + self.eps
            scaled = g / denom
            if wd != 0.0 and not self.reg_inside_moment:
                scaled = scaled + wd * p
            m_new = b1 * m + beta3 * scaled
            return p - lr * (m_new / bc1), m_new, v_new

        new_p, new_m, new_v = tree_map_multi(
            upd, 3, g32, p32, slots["exp_avg"], slots["exp_avg_sq"], wds)
        return new_p, {"exp_avg": new_m, "exp_avg_sq": new_v}
