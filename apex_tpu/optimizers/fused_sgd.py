"""FusedSGD with momentum/nesterov/weight-decay variants.

Semantics of ``apex.optimizers.FusedSGD`` (``apex/optimizers/fused_sgd.py:
76-227``; kernel ``csrc/multi_tensor_sgd_kernel.cu``): first-step momentum
buffers initialized to the gradient, ``wd_after_momentum`` ordering option,
and the fp16-model + fp32-master copy flow handled by the base class.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.optimizers.base import FusedOptimizer, tree_map, tree_map_multi


class FusedSGD(FusedOptimizer):
    def __init__(self, lr: float, momentum: float = 0.0, dampening: float = 0.0,
                 weight_decay: float = 0.0, nesterov: bool = False,
                 wd_after_momentum: bool = False,
                 materialize_master_grads: bool = True,
                 master_weights: bool = False,
                 weight_decay_mask=None):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError("Nesterov momentum requires a momentum and zero dampening")
        super().__init__(lr, weight_decay, master_weights,
                         weight_decay_mask)
        self.momentum = momentum
        self.dampening = dampening
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum

    def _init_slots(self, params32):
        if self.momentum == 0.0:
            return {"momentum_buffer": None}
        return {"momentum_buffer": tree_map(jnp.zeros_like, params32)}

    def _update(self, g32, p32, slots, step, lr):
        wds = self._wd_leaves(p32)
        mom = self.momentum
        first = step == 1

        def upd(g, p, buf, wd):
            d_p = g
            if wd != 0.0 and not self.wd_after_momentum:
                d_p = d_p + wd * p
            if mom != 0.0:
                # first step: buf <- d_p (reference initializes buf to grad)
                buf = jnp.where(first, d_p, mom * buf + (1.0 - self.dampening) * d_p)
                d_p = d_p + mom * buf if self.nesterov else buf
            if wd != 0.0 and self.wd_after_momentum:
                d_p = d_p + wd * p
            return p - lr * d_p, buf

        if mom == 0.0:
            new_p = tree_map(
                lambda g, p, wd: upd(g, p, jnp.zeros(()), wd)[0],
                g32, p32, wds)
            return new_p, {"momentum_buffer": None}
        new_p, new_buf = tree_map_multi(
            upd, 2, g32, p32, slots["momentum_buffer"], wds)
        return new_p, {"momentum_buffer": new_buf}
