"""ZeRO-2 sharded Adam.

TPU-native counterpart of ``apex/contrib/optimizers/distributed_fused_adam.py``
(``DistributedFusedAdam``, class at ``:272``, docstring ``:273-287``:
"distributes ... optimizer state ... sharded"): gradients are reduce-scattered
across the data-parallel group, each rank updates only its shard of the fp32
master params and Adam moments, and updated params are all-gathered back —
overlapping comm with backward is XLA's latency-hiding scheduler's job rather
than the reference's per-param grad hooks (``:811-885``).

Design: each rank's parameter pytree is flattened into ONE padded fp32 buffer
(the same move as the reference's bucket views over ``apex_C`` flattening,
``parallel/distributed.py:15-35``), split ``[dp, chunk]`` over the data axis:

- ``step`` (per-rank, inside ``shard_map``): flat grads ->
  ``lax.psum_scatter`` (mean) over the data axis -> local ``[chunk]`` shard ->
  Adam update against local master/moment shards -> tiled ``lax.all_gather``
  of the new params -> unflatten, cast back to param dtypes. The
  reduce-scatter IS the data-parallel gradient sync (``handles_grad_sync``),
  so the train step skips its grad ``pmean``.
- state is globally ``[dp, *model_axes, chunk]`` sharded over every mesh
  axis: the data axis carries the ZeRO shards; the model axes (pipeline/
  context/tensor) exist because TP/PP-sharded layers give each model-parallel
  rank a *different* local parameter set, each with its own ZeRO shards —
  the mesh-wide statement of the reference's "one optimizer instance per
  model-parallel rank, sharded over its DP group". Optimizer memory per
  device is ``3 * N_local/dp * 4`` bytes, the ZeRO-2 figure.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec

from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.transformer.parallel_state import (
    CONTEXT_AXIS,
    DATA_AXIS,
    PIPELINE_AXIS,
    TENSOR_AXIS,
)
from apex_tpu.transformer.tensor_parallel.mappings import axis_bound, axis_size

__all__ = ["DistributedFusedAdam"]

_MODEL_AXES = (PIPELINE_AXIS, CONTEXT_AXIS, TENSOR_AXIS)


def _spec_axes(entry) -> Tuple[str, ...]:
    """Mesh axis names a PartitionSpec entry binds to one array dim."""
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def _dim_factor_offset(entry, coords: dict):
    """(shard count, shard index) one PartitionSpec entry induces on a dim
    under the model-parallel coordinate ``coords`` (axis name -> (rank, size))."""
    factor, offset_units = 1, 0
    for ax in _spec_axes(entry):
        if ax not in coords:
            continue
        rank, size = coords[ax]
        factor *= size
        offset_units = offset_units * size + rank
    return factor, offset_units


def _local_leaf(leaf, spec, coords: dict):
    """Slice one globally-shaped leaf (numpy or jax) down to the local shard
    owned by the model-parallel coordinate ``coords``."""
    if spec is None:
        return leaf
    out = leaf
    for dim, entry in enumerate(tuple(spec)):
        factor, offset_units = _dim_factor_offset(entry, coords)
        if factor == 1:
            continue
        if out.shape[dim] % factor:
            raise ValueError(
                f"dim {dim} of shape {leaf.shape} not divisible by mesh "
                f"axes {entry} (size {factor})")
        block = out.shape[dim] // factor
        idx = [slice(None)] * out.ndim
        idx[dim] = slice(offset_units * block, (offset_units + 1) * block)
        out = out[tuple(idx)]
    return out


def _local_numel(shape, spec, axis_sizes: dict) -> int:
    """Element count of one model-parallel rank's shard of a leaf."""
    n = int(np.prod(shape, dtype=np.int64))
    if spec is None:
        return n
    for dim, entry in enumerate(tuple(spec)):
        for ax in _spec_axes(entry):
            if ax in axis_sizes:
                n //= axis_sizes[ax]
    return n


class DistributedFusedAdam(FusedAdam):
    """Adam with data-parallel-sharded state (ZeRO-2).

    Args mirror :class:`FusedAdam`. fp32 master weights are always kept
    (sharded) — that is the point of the exercise, matching the reference
    which materializes fp32 state shards regardless of param dtype.

    ``init`` wants ``param_spec`` whenever the model itself is mesh-sharded
    (TP/PP); without it params are assumed replicated across model axes.
    """

    handles_grad_sync = True

    def __init__(self, lr: float = 1e-3, *, num_shards: Optional[int] = None,
                 axis_name: str = DATA_AXIS, gather_dtype=None,
                 store_param_remainders: bool = False, **adam_kw):
        adam_kw.pop("master_weights", None)
        super().__init__(lr=lr, master_weights=True, **adam_kw)
        if num_shards is None:
            from apex_tpu.transformer import parallel_state
            num_shards = (parallel_state.get_data_parallel_world_size()
                          if parallel_state.model_parallel_is_initialized()
                          else 1)
        self.num_shards = num_shards
        self.axis_name = axis_name
        # all-gather precision (reference: params move fp16 by default,
        # e5m2 uint8 with e5m2_allgather=True —
        # distributed_fused_lamb.py:105,340,389; XLA does NOT compress
        # collectives, so gathering the fp32 master shard doubles the
        # reference's gather bytes). None = automatic: when every param
        # leaf is a 16-bit float, gather in that dtype — lossless
        # end-to-end because the gathered values are cast to the leaf
        # dtype anyway and the cast commutes with all_gather; mixed or
        # fp32 leaves keep the fp32 gather. Pass jnp.float8_e5m2 for the
        # reference's compressed-allgather analog (lossy, opt-in).
        self.gather_dtype = gather_dtype
        # store fp32 masters as (bf16 param image + signed 16-bit
        # remainder): halves resident master bytes when params are bf16
        # (reference distributed_fused_adam.py:251-267,429-458)
        self.store_param_remainders = store_param_remainders
        if (store_param_remainders and gather_dtype is not None
                and jnp.dtype(gather_dtype) != jnp.dtype(jnp.bfloat16)):
            # a lossy gather would hand the next step a param image that
            # is NOT the one the stored remainder was split against —
            # the reconstructed master's top 16 bits would be silently
            # wrong every step
            raise ValueError(
                "store_param_remainders requires the bf16 param image to "
                "round-trip through the all-gather exactly; "
                f"gather_dtype={jnp.dtype(gather_dtype).name} would "
                "degrade it (leave gather_dtype unset — it resolves to "
                "bfloat16 for all-bf16 params)")
        self._segment_cache: dict = {}

    # -- flat buffer layout --------------------------------------------------

    def _segment_ids(self, params) -> Tuple[jax.Array, int]:
        """int32 ``[num_shards * chunk]`` mapping each flat-buffer slot to its
        leaf index; padding maps to a dead segment ``n_leaves``. Shared by the
        per-element weight-decay masks (here) and the LAMB subclass's
        per-tensor trust-ratio norms."""
        leaves = jax.tree_util.tree_leaves(params)
        sizes = tuple(int(np.prod(l.shape, dtype=np.int64)) for l in leaves)
        if sizes not in self._segment_cache:
            total = sum(sizes)
            chunk = self._chunk_size(total)
            padded = chunk * self.num_shards
            ids = np.full((padded,), len(sizes), dtype=np.int32)
            off = 0
            for i, n in enumerate(sizes):
                ids[off:off + n] = i
                off += n
            self._segment_cache[sizes] = ids      # numpy: safe across traces
        return jnp.asarray(self._segment_cache[sizes]), len(sizes)

    def _local_segment_ids(self, params, chunk: int,
                           sharded: bool) -> Tuple[jax.Array, int]:
        """This rank's slice of the segment-id map (full map unsharded)."""
        ids_full, n_leaves = self._segment_ids(params)
        if sharded:
            ids = lax.dynamic_slice(
                ids_full, (lax.axis_index(self.axis_name) * chunk,), (chunk,))
        else:
            ids = ids_full
        return ids, n_leaves

    def _wd_segment_values(self, params, n_leaves: int) -> jax.Array:
        """fp32 ``[n_leaves + 1]`` weight-decay value per leaf segment (mask
        applied; the dead padding segment decays 0)."""
        wd_tree = self._wd_leaves(params)
        vals = [float(w) for w in jax.tree_util.tree_leaves(wd_tree)] + [0.0]
        return jnp.asarray(vals, jnp.float32)

    def _flat_wd_local(self, params, chunk: int, sharded: bool) -> jax.Array:
        """Per-element decay multipliers for this rank's flat shard — the
        flat-buffer translation of the per-leaf ``weight_decay_mask``
        (param-groups parity the reference keeps via torch param_groups)."""
        ids, n_leaves = self._local_segment_ids(params, chunk, sharded)
        return self._wd_segment_values(params, n_leaves)[ids]

    def _model_axis_sizes(self):
        from apex_tpu.transformer import parallel_state
        if not parallel_state.model_parallel_is_initialized():
            return {}
        mesh = parallel_state.get_mesh()
        return {a: mesh.shape[a] for a in _MODEL_AXES if a in mesh.shape}

    def _chunk_size(self, local_numel: int) -> int:
        return -(-local_numel // self.num_shards)  # ceil

    def _flatten_local(self, tree) -> jax.Array:
        leaves = jax.tree_util.tree_leaves(tree)
        flat = jnp.concatenate(
            [l.astype(jnp.float32).reshape(-1) for l in leaves])
        chunk = self._chunk_size(flat.shape[0])
        return jnp.pad(flat, (0, chunk * self.num_shards - flat.shape[0]))

    def _unflatten_local(self, flat: jax.Array, params) -> Any:
        leaves, treedef = jax.tree_util.tree_flatten(params)
        out, off = [], 0
        for l in leaves:
            n = int(np.prod(l.shape, dtype=np.int64))
            out.append(flat[off:off + n].reshape(l.shape).astype(l.dtype))
            off += n
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- gather precision / master storage -----------------------------------

    def _resolve_gather_dtype(self, params):
        """See ``gather_dtype`` in ``__init__``."""
        if self.gather_dtype is not None:
            return jnp.dtype(self.gather_dtype)
        dts = {jnp.dtype(l.dtype) for l in jax.tree_util.tree_leaves(params)}
        if len(dts) == 1:
            (dt,) = dts
            if jnp.issubdtype(dt, jnp.floating) and dt.itemsize == 2:
                return dt
        return jnp.dtype(jnp.float32)

    def _gather_params(self, new_p, params, sharded):
        """All-gather the updated shard in the gather dtype, unflatten to
        param dtypes. ``new_p`` may already be the bf16 param image (the
        ``store_param_remainders`` path)."""
        if not sharded:
            return self._unflatten_local(new_p, params)
        gd = self._resolve_gather_dtype(params)
        # (the CPU backend legalizes bf16 collectives back to f32 in its
        # post-optimization HLO — a backend artifact; TPU gathers bf16
        # natively, which is the wire-bytes win this knob exists for)
        return self._unflatten_local(
            lax.all_gather(new_p.astype(gd), self.axis_name, tiled=True),
            params)

    def _param_shard_flat(self, params, chunk: int, sharded: bool):
        """This rank's [chunk] slice of the flattened (fp32) params."""
        flat = self._flatten_local(params)
        if sharded:
            flat = lax.dynamic_slice(
                flat, (lax.axis_index(self.axis_name) * chunk,), (chunk,))
        return flat

    @staticmethod
    def _master_from_remainder(p_img: jax.Array, rem_i16: jax.Array):
        """fp32 master = (bf16 param image bits << 16) + signed remainder
        (reference ``store_param_remainders``,
        distributed_fused_adam.py:251-267: the bf16-visible param supplies
        the top 16 bits, the optimizer state only the bottom 16)."""
        hi = lax.bitcast_convert_type(p_img.astype(jnp.bfloat16), jnp.uint16)
        bits = ((hi.astype(jnp.uint32) << 16)
                + rem_i16.astype(jnp.int32).astype(jnp.uint32))
        return lax.bitcast_convert_type(bits, jnp.float32)

    @staticmethod
    def _remainder_split(master: jax.Array):
        """(bf16 param image, int16 remainder). The image uses round-HALF-UP
        to bf16 (``(bits + 0x8000) >> 16``) so the remainder is always in
        [-32768, 32767] and round-trips through int16 exactly; NaN masters
        (inf grads) are not split faithfully — the ``found_inf`` guard keeps
        them out of state."""
        bits = lax.bitcast_convert_type(master, jnp.uint32)
        hi = ((bits + jnp.uint32(0x8000)) >> 16).astype(jnp.uint16)
        img = lax.bitcast_convert_type(hi, jnp.bfloat16)
        rem_u = (bits - (hi.astype(jnp.uint32) << 16)) & jnp.uint32(0xFFFF)
        rem = lax.bitcast_convert_type(rem_u.astype(jnp.uint16), jnp.int16)
        return img, rem

    # -- public API ----------------------------------------------------------

    def init(self, params, param_spec=None) -> dict:
        """Build the globally-shaped sharded state from global params.

        State shape is ``[dp, *model_axes, chunk]``: position ``[d, *coord]``
        holds segment ``d`` of the flattened local params of model-parallel
        rank ``coord``. Shards are materialized directly on their owning
        devices via ``jax.make_array_from_callback`` — no full fp32 copy of
        the state is ever resident on one device (the distributed-init analog
        of the reference initializing each rank's shard in place)."""
        axes = self._model_axis_sizes()
        names, sizes = list(axes.keys()), list(axes.values())
        dp = self.num_shards
        if self.store_param_remainders:
            bad = [jnp.dtype(l.dtype)
                   for l in jax.tree_util.tree_leaves(params)
                   if jnp.dtype(l.dtype) != jnp.dtype(jnp.bfloat16)]
            if bad:
                raise ValueError(
                    "store_param_remainders needs every param leaf in "
                    f"bfloat16 (the params carry the master's top 16 bits); "
                    f"found {sorted(set(map(str, bad)))}")

        if not names:
            master = self._flatten_local(params).reshape(dp, -1)
            state = {
                "step": jnp.zeros((), jnp.int32),
                "exp_avg": jnp.zeros_like(master),
                "exp_avg_sq": jnp.zeros_like(master),
            }
            if self.store_param_remainders:
                # params ARE the initial masters (bf16-exact), so the
                # remainder starts at zero
                state["master_rem"] = jnp.zeros(master.shape, jnp.int16)
            else:
                state["master"] = master
            return state

        from apex_tpu.transformer import parallel_state
        from jax.sharding import NamedSharding

        mesh = parallel_state.get_mesh()
        if mesh.shape[DATA_AXIS] != dp:
            raise ValueError(
                f"num_shards ({dp}) must equal the mesh data-axis size "
                f"({mesh.shape[DATA_AXIS]}) — construct the optimizer after "
                "initialize_model_parallel() or pass num_shards explicitly")
        leaves = jax.tree_util.tree_leaves(params)
        if param_spec is None:
            spec_leaves = [None] * len(leaves)
        else:
            spec_leaves = jax.tree_util.tree_structure(params).flatten_up_to(
                param_spec)
        from apex_tpu.utils.sharding import spec_axis_names
        for s in spec_leaves:
            if self.axis_name in spec_axis_names(s):
                raise NotImplementedError(
                    f"a parameter is sharded over the ZeRO axis "
                    f"'{self.axis_name}' (e.g. expert parallelism riding the "
                    "data axis): its per-rank values differ, which breaks "
                    "the flat-buffer reduce-scatter. Use the per-leaf "
                    "FusedAdam/FusedLAMB for such models, or put experts on "
                    "a different mesh axis.")
        local_numel = sum(
            _local_numel(l.shape, s, axes)
            for l, s in zip(leaves, spec_leaves))
        chunk = self._chunk_size(local_numel)
        shape = (dp, *sizes, chunk)
        sharding = NamedSharding(mesh, PartitionSpec(DATA_AXIS, *names, None))
        shard_cache: dict = {}

        def _coord_flat(coord):
            # slice only this model-parallel rank's param shards (no full
            # host gather — on multi-host meshes the callback is invoked for
            # addressable shards only, whose param slices are host-local)
            if coord not in shard_cache:
                coords = {n: (r, s) for n, r, s in zip(names, coord, sizes)}
                flat = np.concatenate([
                    np.asarray(_local_leaf(l, s, coords),
                               dtype=np.float32).reshape(-1)
                    for l, s in zip(leaves, spec_leaves)])
                shard_cache[coord] = np.pad(
                    flat, (0, chunk * dp - flat.shape[0]))
            return shard_cache[coord]

        def cb(index):
            d = index[0].start or 0
            coord = tuple((sl.start or 0) for sl in index[1:-1])
            seg = _coord_flat(coord)[d * chunk:(d + 1) * chunk]
            return seg.reshape((1,) + (1,) * len(sizes) + (chunk,))

        master = jax.make_array_from_callback(shape, sharding, cb)
        state = {
            "step": jnp.zeros((), jnp.int32),
            "exp_avg": jnp.zeros_like(master),      # sharding-preserving
            "exp_avg_sq": jnp.zeros_like(master),
        }
        if self.store_param_remainders:
            state["master_rem"] = jnp.zeros_like(master, dtype=jnp.int16)
        else:
            state["master"] = master
        return state

    def state_spec(self, params, param_spec=None):
        names = list(self._model_axis_sizes().keys())
        p = PartitionSpec(self.axis_name, *names, None)
        spec = {"step": PartitionSpec(), "exp_avg": p, "exp_avg_sq": p}
        spec["master_rem" if self.store_param_remainders else "master"] = p
        return spec

    def _sync_grads(self, grads, grad_scale) -> Tuple[jax.Array, bool]:
        """Shared sharded-gradient prologue: validate the bound axis,
        flatten + unscale local grads, reduce-scatter (mean) to this rank's
        shard. Returns ``(g_local, sharded)``."""
        if axis_bound(self.axis_name):
            bound_size = axis_size(self.axis_name)  # static at trace time
            if bound_size != self.num_shards:
                raise ValueError(
                    f"{type(self).__name__} was built with num_shards="
                    f"{self.num_shards} but the bound '{self.axis_name}' "
                    f"axis has size {bound_size}; gradients would silently "
                    "desynchronize. Construct the optimizer after "
                    "initialize_model_parallel() (or pass num_shards).")
        sharded = axis_bound(self.axis_name) and self.num_shards > 1

        g_flat = self._flatten_local(grads)
        if grad_scale is not None:
            g_flat = g_flat * (1.0 / grad_scale)
        if sharded:
            # reduce-scatter = grad sync + shard selection in one collective
            # (reference grad-sync pipeline, distributed_fused_adam.py:811-885)
            g_flat = lax.psum_scatter(g_flat, self.axis_name,
                                      scatter_dimension=0, tiled=True)
            g_flat = g_flat / self.num_shards
        return g_flat, sharded

    def step(self, grads, params, state, *, lr: Optional[Any] = None,
             grad_scale: Optional[jax.Array] = None,
             found_inf: Optional[jax.Array] = None) -> Tuple[Any, dict]:
        """Per-rank view inside ``shard_map``: ``grads``/``params`` are this
        rank's local pytrees, state leaves are ``[1, 1..., chunk]`` shards.
        Outside ``shard_map`` (world size 1) it degrades to FusedAdam on the
        flat buffer."""
        lr = self.lr if lr is None else lr
        g_local, sharded = self._sync_grads(grads, grad_scale)

        shard_shape = state["exp_avg"].shape
        if self.store_param_remainders:
            p_img = self._param_shard_flat(params, g_local.shape[0], sharded)
            rem_old = state["master_rem"].reshape(-1)
            p_local = self._master_from_remainder(p_img, rem_old)
        else:
            p_local = state["master"].reshape(-1)
        slots = {"exp_avg": state["exp_avg"].reshape(-1),
                 "exp_avg_sq": state["exp_avg_sq"].reshape(-1)}
        step = state["step"] + 1
        # always pass wds explicitly: the flat buffer is a single leaf, so
        # the base _wd_leaves (which maps a per-leaf mask over the params
        # tree) must never run here
        if self.weight_decay_mask is not None and self.weight_decay != 0.0:
            wds = [self._flat_wd_local(params, g_local.shape[0], sharded)]
        else:
            wds = [self.weight_decay]
        new_p, new_slots = self._update(g_local, p_local, slots, step, lr,
                                        wds=wds)
        if found_inf is not None:
            new_p = jnp.where(found_inf, p_local, new_p)
            new_slots = jax.tree.map(
                lambda n, o: jnp.where(found_inf, o, n), new_slots, slots)
            step = jnp.where(found_inf, state["step"], step)

        new_state = {
            "step": step,
            "exp_avg": new_slots["exp_avg"].reshape(shard_shape),
            "exp_avg_sq": new_slots["exp_avg_sq"].reshape(shard_shape),
        }
        if self.store_param_remainders:
            # split the updated master; the bf16 image is what gets
            # gathered (gathering a separately-rounded cast could disagree
            # with the stored remainder at round-to-nearest ties). No
            # extra found_inf guard needed: new_p was already reverted to
            # p_local above, and re-splitting the reverted master
            # reproduces (p_img, rem_old) bit-exactly (round-half-up is
            # the exact inverse of the reconstruction).
            img, rem = self._remainder_split(new_p)
            new_state["master_rem"] = rem.reshape(shard_shape)
            gather_src = img
        else:
            new_state["master"] = new_p.reshape(shard_shape)
            gather_src = new_p
        # params come back via all-gather in the gather dtype (reference:
        # fp16 / e5m2 all-gather after the sharded step)
        new_params = self._gather_params(gather_src, params, sharded)
        return new_params, new_state
