// apex_tpu native host runtime.
//
// TPU-native counterpart of the reference's C++ host-side plumbing:
//  - tensor-list flatten/unflatten (apex_C, csrc/flatten_unflatten.cpp:15-18)
//    as multithreaded memcpy into one contiguous staging buffer;
//  - gradient-bucket planning (the arrival-order, message-size-capped bucket
//    structure apex DDP learns during the first backward,
//    apex/parallel/distributed.py:366-390) as a host-side planner;
//  - an aligned host staging-buffer pool (the memory-management role of
//    contrib/csrc/nccl_allocator + peer_memory on GPU: reusable transfer
//    buffers, here feeding jax.device_put);
//  - a blocking MPMC token queue (condvar ring buffer) backing the C++
//    data-prefetch pipeline in apex_tpu.data.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// flatten / unflatten
// ---------------------------------------------------------------------------

// Parallel memcpy of n chunks into one destination. Threads are only spun up
// past a threshold so small trees stay cheap.
static void copy_chunks(const void** srcs, void** dsts,
                        const int64_t* nbytes, int n) {
  int64_t total = 0;
  for (int i = 0; i < n; ++i) total += nbytes[i];
  const int64_t kParallelThreshold = 8 << 20;  // 8 MiB
  unsigned hw = std::thread::hardware_concurrency();
  if (total < kParallelThreshold || hw <= 1) {
    for (int i = 0; i < n; ++i) std::memcpy(dsts[i], srcs[i], nbytes[i]);
    return;
  }
  int nthreads = std::min<unsigned>(hw, 8);
  std::vector<std::thread> workers;
  std::vector<int> order(n);
  for (int i = 0; i < n; ++i) order[i] = i;
  // largest-first round robin keeps per-thread byte counts balanced
  std::sort(order.begin(), order.end(),
            [&](int a, int b) { return nbytes[a] > nbytes[b]; });
  for (int t = 0; t < nthreads; ++t) {
    workers.emplace_back([=]() {
      for (int j = t; j < n; j += nthreads) {
        int i = order[j];
        std::memcpy(dsts[i], srcs[i], nbytes[i]);
      }
    });
  }
  for (auto& w : workers) w.join();
}

// dst: contiguous buffer of sum(nbytes); srcs: n source pointers.
void apex_flatten(const void** srcs, const int64_t* nbytes, int n,
                  void* dst) {
  std::vector<void*> dsts(n);
  char* out = static_cast<char*>(dst);
  for (int i = 0; i < n; ++i) {
    dsts[i] = out;
    out += nbytes[i];
  }
  copy_chunks(srcs, dsts.data(), nbytes, n);
}

// inverse: scatter one contiguous buffer back into n destinations.
void apex_unflatten(const void* src, const int64_t* nbytes, int n,
                    void** dsts) {
  std::vector<const void*> srcs(n);
  const char* in = static_cast<const char*>(src);
  for (int i = 0; i < n; ++i) {
    srcs[i] = in;
    in += nbytes[i];
  }
  copy_chunks(srcs.data(), dsts, nbytes, n);
}

// ---------------------------------------------------------------------------
// bucket planning
// ---------------------------------------------------------------------------

// Assign tensors (in arrival order) to buckets capped at `cap` bytes; a
// tensor larger than cap gets its own bucket. Returns the bucket count.
// Mirrors apex DDP's first-backward bucket learning
// (distributed.py:366-390): arrival order, ship when >= message_size.
int apex_bucket_plan(const int64_t* nbytes, int n, int64_t cap,
                     int32_t* bucket_ids) {
  int bucket = 0;
  int64_t fill = 0;
  for (int i = 0; i < n; ++i) {
    if (fill > 0 && fill + nbytes[i] > cap) {
      ++bucket;
      fill = 0;
    }
    bucket_ids[i] = bucket;
    fill += nbytes[i];
    if (fill >= cap) {
      ++bucket;
      fill = 0;
    }
  }
  return (fill > 0) ? bucket + 1 : bucket;
}

// ---------------------------------------------------------------------------
// staging buffer pool
// ---------------------------------------------------------------------------

namespace {
struct Pool {
  std::mutex mu;
  // size -> free buffers of exactly that size (sizes are page-rounded, so
  // reuse hits are the common case for steady-state training)
  std::multimap<int64_t, void*> free_list;
  int64_t outstanding = 0;
  int64_t pooled_bytes = 0;
  int64_t capacity = 1ll << 31;  // 2 GiB default cap on pooled bytes
};
Pool g_pool;
constexpr int64_t kAlign = 256;   // TPU-friendly host alignment
constexpr int64_t kPage = 4096;

int64_t round_size(int64_t n) { return ((n + kPage - 1) / kPage) * kPage; }
}  // namespace

void* apex_staging_alloc(int64_t nbytes) {
  int64_t want = round_size(nbytes < 1 ? 1 : nbytes);
  {
    std::lock_guard<std::mutex> lock(g_pool.mu);
    auto it = g_pool.free_list.find(want);
    if (it != g_pool.free_list.end()) {
      void* p = it->second;
      g_pool.free_list.erase(it);
      g_pool.pooled_bytes -= want;
      ++g_pool.outstanding;
      return p;
    }
  }
  void* p = ::operator new(static_cast<size_t>(want),
                           std::align_val_t(kAlign), std::nothrow);
  if (p) {
    std::lock_guard<std::mutex> lock(g_pool.mu);
    ++g_pool.outstanding;
  }
  return p;
}

void apex_staging_free(void* p, int64_t nbytes) {
  if (!p) return;
  int64_t want = round_size(nbytes < 1 ? 1 : nbytes);
  std::lock_guard<std::mutex> lock(g_pool.mu);
  --g_pool.outstanding;
  if (g_pool.pooled_bytes + want <= g_pool.capacity) {
    g_pool.free_list.emplace(want, p);
    g_pool.pooled_bytes += want;
  } else {
    ::operator delete(p, std::align_val_t(kAlign));
  }
}

void apex_staging_trim() {
  std::lock_guard<std::mutex> lock(g_pool.mu);
  for (auto& kv : g_pool.free_list)
    ::operator delete(kv.second, std::align_val_t(kAlign));
  g_pool.free_list.clear();
  g_pool.pooled_bytes = 0;
}

void apex_staging_stats(int64_t* outstanding, int64_t* pooled_bytes) {
  std::lock_guard<std::mutex> lock(g_pool.mu);
  *outstanding = g_pool.outstanding;
  *pooled_bytes = g_pool.pooled_bytes;
}

void apex_staging_set_capacity(int64_t cap) {
  std::lock_guard<std::mutex> lock(g_pool.mu);
  g_pool.capacity = cap;
}

// ---------------------------------------------------------------------------
// blocking MPMC token queue (prefetch pipeline backbone)
// ---------------------------------------------------------------------------

namespace {
struct TokenQueue {
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  std::deque<int64_t> items;
  size_t capacity;
  bool closed = false;
  explicit TokenQueue(size_t cap) : capacity(cap) {}
};
}  // namespace

void* apex_queue_create(int64_t capacity) {
  return new TokenQueue(static_cast<size_t>(capacity < 1 ? 1 : capacity));
}

void apex_queue_destroy(void* q) { delete static_cast<TokenQueue*>(q); }

// put blocks while full; returns 0 on success, -1 if the queue was closed.
int apex_queue_put(void* qp, int64_t token) {
  auto* q = static_cast<TokenQueue*>(qp);
  std::unique_lock<std::mutex> lock(q->mu);
  q->not_full.wait(lock, [&] { return q->items.size() < q->capacity
                                      || q->closed; });
  if (q->closed) return -1;
  q->items.push_back(token);
  q->not_empty.notify_one();
  return 0;
}

// get blocks while empty; returns 0 on success (token written), -1 when the
// queue is closed AND drained (end of stream), -2 on timeout.
int apex_queue_get(void* qp, int64_t timeout_ms, int64_t* token) {
  auto* q = static_cast<TokenQueue*>(qp);
  std::unique_lock<std::mutex> lock(q->mu);
  auto ready = [&] { return !q->items.empty() || q->closed; };
  if (timeout_ms < 0) {
    q->not_empty.wait(lock, ready);
  } else if (!q->not_empty.wait_for(
                 lock, std::chrono::milliseconds(timeout_ms), ready)) {
    return -2;
  }
  if (q->items.empty()) return -1;  // closed and drained
  *token = q->items.front();
  q->items.pop_front();
  q->not_full.notify_one();
  return 0;
}

// close wakes all waiters; pending items remain gettable.
void apex_queue_close(void* qp) {
  auto* q = static_cast<TokenQueue*>(qp);
  std::lock_guard<std::mutex> lock(q->mu);
  q->closed = true;
  q->not_empty.notify_all();
  q->not_full.notify_all();
}

int64_t apex_queue_size(void* qp) {
  auto* q = static_cast<TokenQueue*>(qp);
  std::lock_guard<std::mutex> lock(q->mu);
  return static_cast<int64_t>(q->items.size());
}

}  // extern "C"
