"""Fused normalization modules (flax.linen).

Module-level parity with ``apex/normalization/fused_layer_norm.py``:
``FusedLayerNorm`` (:230), ``FusedRMSNorm`` (:329), and the Megatron
mixed-dtype variants ``MixedFusedLayerNorm``/``MixedFusedRMSNorm`` (:430,455)
whose parameters live in fp32 while activations stay in the compute dtype.
The compute path dispatches to the Pallas kernels in
``apex_tpu.ops.layer_norm``.
"""

from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops.layer_norm import (
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)

Shape = Union[int, Sequence[int]]


def _shape(s: Shape):
    return (s,) if isinstance(s, int) else tuple(s)


class FusedLayerNorm(nn.Module):
    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: type = jnp.float32
    # Megatron SP: mark grads of these params for all-reduce over the TP group
    # (reference: apex/transformer/layers/layer_norm.py:26-99)
    sequence_parallel_enabled: bool = False

    @nn.compact
    def __call__(self, x):
        shape = _shape(self.normalized_shape)
        if not self.elementwise_affine:
            return fused_layer_norm(x, shape, self.eps, self.memory_efficient)
        weight = self.param(
            "scale", nn.initializers.ones, shape, self.param_dtype)
        bias = self.param(
            "bias", nn.initializers.zeros, shape, self.param_dtype)
        return fused_layer_norm_affine(
            x, weight, bias, shape, self.eps, self.memory_efficient)


class FusedRMSNorm(nn.Module):
    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: type = jnp.float32
    sequence_parallel_enabled: bool = False

    @nn.compact
    def __call__(self, x):
        shape = _shape(self.normalized_shape)
        if not self.elementwise_affine:
            return fused_rms_norm(x, shape, self.eps, self.memory_efficient)
        weight = self.param(
            "scale", nn.initializers.ones, shape, self.param_dtype)
        return fused_rms_norm_affine(
            x, weight, shape, self.eps, self.memory_efficient)


class MixedFusedLayerNorm(FusedLayerNorm):
    """fp32 params with half activations; output in activation dtype
    (Megatron semantics, ``fused_layer_norm.py:430-452``)."""

    @nn.compact
    def __call__(self, x):
        y = super().__call__(x)
        return y.astype(x.dtype)


class MixedFusedRMSNorm(FusedRMSNorm):
    @nn.compact
    def __call__(self, x):
        y = super().__call__(x)
        return y.astype(x.dtype)
