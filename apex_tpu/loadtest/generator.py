"""Seeded open-loop traffic generation from a scenario.

The generator is the single source of synthetic serving traffic — the
loadtest runner replays its schedule against the supervised engine, and
``benchmarks/generation_bench.py``'s serving mode draws its request set
from the same code path (mirroring how FLOP math was unified into
``apex_tpu/utils/flops.py``: one formula, many consumers).

**Open loop**: arrival times are drawn up front as a Poisson process
(exponential inter-arrival gaps at each phase's rate) and never react to
completions — the defining property of a capacity test. A closed loop
(submit-on-completion) self-throttles and hides saturation; an open
loop keeps offering load, so queueing, shedding, and deadline misses
become measurable instead of invisible.

**Determinism**: every draw — arrival gaps, prompt tokens, output
budgets, deadlines, sampling params — comes from ONE ``random.Random``
seeded with the scenario seed, consumed in a fixed order. Same seed +
same scenario => byte-identical schedule (asserted in tier-1), which is
what makes a committed SLO baseline meaningful: reruns measure the same
offered load. ``request_id`` is the only field that varies between runs
(it is process-global by design, for log correlation); compare
schedules with :meth:`ScheduledRequest.signature`.

Host-side only: imports :mod:`apex_tpu.serving.request` (plain
dataclasses), never the engine — generating a schedule touches no
device and no jit.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, List, Tuple

from apex_tpu.loadtest.scenario import LoadPhase, Scenario
from apex_tpu.serving.request import Request, SamplingParams

__all__ = ["ScheduledRequest", "TrafficGenerator"]


@dataclass(frozen=True)
class ScheduledRequest:
    """One arrival: the request plus its offset (seconds) from the run
    start and the phase that produced it."""

    at_s: float
    phase: str
    request: Request

    def signature(self) -> Tuple:
        """Everything that must be identical across same-seed runs —
        all sampled fields, excluding the process-global request_id."""
        r = self.request
        return (round(self.at_s, 9), self.phase, tuple(r.prompt),
                r.max_new_tokens, r.eos_token, r.deadline_s,
                r.sampling.temperature, r.sampling.top_k, r.sampling.seed,
                r.sampling.adapter_id, r.sampling.priority)


def _choose(rng: random.Random, mix: Dict[int, float]) -> int:
    values = sorted(mix)    # sorted: draw order independent of dict order
    return rng.choices(values, weights=[mix[v] for v in values])[0]


class TrafficGenerator:
    """Materializes a :class:`~apex_tpu.loadtest.scenario.Scenario`'s
    phases into one time-ordered arrival schedule."""

    def __init__(self, scenario: Scenario):
        self.scenario = scenario

    def schedule(self) -> List[ScheduledRequest]:
        """The full arrival schedule, time-ordered (phases are
        sequential: each phase's clock starts where the previous one's
        last arrival landed)."""
        rng = random.Random(self.scenario.seed)
        out: List[ScheduledRequest] = []
        t = 0.0
        for phase in self.scenario.phases:
            # the phase's shared prompt opening, drawn ONCE — when the
            # knob is 0 no draw happens at all, so schedules of
            # pre-existing scenarios stay byte-identical
            shared = [rng.randrange(self.scenario.model.vocab_size)
                      for _ in range(phase.shared_prefix_len)]
            for _ in range(phase.n_requests):
                t += rng.expovariate(phase.rate_rps)
                out.append(ScheduledRequest(
                    at_s=t, phase=phase.name,
                    request=self._request(phase, rng, shared)))
        return out

    def requests(self) -> List[Request]:
        """Just the requests, arrival order — what a lockstep consumer
        (the benchmark's ``generate()`` arm) needs."""
        return [s.request for s in self.schedule()]

    def _request(self, phase: LoadPhase, rng: random.Random,
                 shared: List[int]) -> Request:
        prompt_len = _choose(rng, phase.prompt_lens)
        # scenario validation caps shared_prefix_len at the shortest
        # prompt length, so the suffix draw count is never negative
        prompt = shared + [rng.randrange(self.scenario.model.vocab_size)
                           for _ in range(prompt_len - len(shared))]
        if phase.prompt_period > 0:
            # repeated-text shape: tile the prompt's first period across
            # its full length (period 0 draws nothing extra, so existing
            # scenarios keep byte-identical schedules)
            period = prompt[:phase.prompt_period]
            prompt = (period * (prompt_len // len(period) + 1))[:prompt_len]
        max_new = _choose(rng, phase.max_new_tokens)
        # draw order is fixed and unconditional draws come first, so a
        # mix change in one field cannot shift another field's stream
        # more than necessary
        deadline_draw = rng.random()
        deadline = None
        if phase.deadline_fraction > 0:
            d = rng.uniform(phase.deadline_min_s, phase.deadline_max_s)
            if deadline_draw < phase.deadline_fraction:
                deadline = d
        greedy_draw = rng.random()
        temp = rng.choice(phase.temperatures) if phase.temperatures \
            else 0.7
        top_k = rng.choice(phase.top_ks) if phase.top_ks else 0
        seed = rng.randrange(2 ** 31)
        # the adapter draw comes LAST and only for phases that declare a
        # mix, so adapter-free scenarios consume the exact same stream
        # as before multi-LoRA existed (byte-identical schedules)
        adapter_id = None
        if phase.adapter_mix:
            ids = sorted(phase.adapter_mix)
            drawn = rng.choices(
                ids, weights=[phase.adapter_mix[a] for a in ids])[0]
            adapter_id = None if drawn == "base" else drawn
        # priority is a FIXED per-phase knob, not a draw — stamping a
        # class consumes no randomness, so pre-priority scenarios keep
        # byte-identical schedules
        priority = phase.priority if phase.priority is not None \
            else SamplingParams().priority
        if greedy_draw < phase.greedy_fraction:
            sampling = SamplingParams(adapter_id=adapter_id,
                                      priority=priority)       # greedy
        else:
            sampling = SamplingParams(
                temperature=temp, top_k=top_k if top_k > 0 else None,
                seed=seed, adapter_id=adapter_id, priority=priority)
        return Request(prompt=prompt, max_new_tokens=max_new,
                       sampling=sampling, eos_token=phase.eos_token,
                       deadline_s=deadline)
