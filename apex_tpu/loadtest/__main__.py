"""``python -m apex_tpu.loadtest`` — run scenarios, score SLOs, gate.

Usage:

  python -m apex_tpu.loadtest scenario.json            # run + verdict
  python -m apex_tpu.loadtest --check scenario.json    # regression gate
  python -m apex_tpu.loadtest --check scenario.json --from-log run.jsonl
  python -m apex_tpu.loadtest scenario.json --update-baseline

Exit codes (gate semantics — wire them straight into CI):

  0  SLOs met; no baseline regression (or informational run)
  1  SLO violation (a declared objective failed)
  2  regression beyond tolerance against the committed baseline
  3  --check requested but the baseline has no entry for this scenario
     (run once with --update-baseline to set the bar)
  4  usage / IO / scenario-schema error

``--from-log`` re-scores an existing JSONL run log instead of running
the scenario — pure stdlib, no jax import, so a log written on a TPU
host gates anywhere. Without it the scenario is executed locally
(``--out`` keeps the run log for ``python -m apex_tpu.monitor``).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from apex_tpu.loadtest.gate import (
    DEFAULT_BASELINE,
    compare_to_baseline,
    load_baseline,
    update_baseline,
)
from apex_tpu.loadtest.scenario import Scenario
from apex_tpu.observability.report import read_records
from apex_tpu.observability.slo import (
    SLOSpec,
    evaluate_slos,
    measure_slo_metrics,
)
from apex_tpu.observability.trace import check_span_conservation

EXIT_OK = 0
EXIT_SLO_VIOLATION = 1
EXIT_REGRESSION = 2
EXIT_NO_BASELINE = 3
EXIT_ERROR = 4


def _fmt(value: Optional[float]) -> str:
    return "(no data)" if value is None else f"{value:.6g}"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.loadtest",
        description="Run a load-test scenario against the supervised "
                    "serving engine and score it against its declared "
                    "SLOs and the committed regression baseline "
                    "(docs/loadtest.md).")
    parser.add_argument("scenario", help="path to the scenario .json")
    parser.add_argument("--check", action="store_true",
                        help="gate mode: exit 1 on SLO violation, 2 on "
                             "baseline regression, 3 on missing baseline")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        metavar="PATH",
                        help=f"baseline file (default {DEFAULT_BASELINE})")
    parser.add_argument("--tolerance", type=float, default=None,
                        help="relative regression tolerance (default: the "
                             "scenario's own 'tolerance' field)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="write this run's measured metrics into the "
                             "baseline (skips the regression check)")
    parser.add_argument("--from-log", metavar="RUN.jsonl", default=None,
                        help="score an existing run log instead of "
                             "executing the scenario (no model run)")
    parser.add_argument("--out", metavar="RUN.jsonl", default=None,
                        help="write the run's JSONL log here (monitor-"
                             "compatible)")
    parser.add_argument("--json", action="store_true",
                        help="emit the verdict as JSON instead of text")
    args = parser.parse_args(argv)

    try:
        scenario = Scenario.load(args.scenario)
    except (OSError, ValueError, KeyError) as exc:
        print(f"apex_tpu.loadtest: bad scenario {args.scenario}: {exc}",
              file=sys.stderr)
        return EXIT_ERROR
    tolerance = args.tolerance if args.tolerance is not None \
        else scenario.tolerance

    run = None
    if args.from_log is not None:
        try:
            records = read_records(args.from_log)
        except OSError as exc:
            print(f"apex_tpu.loadtest: cannot read {args.from_log}: {exc}",
                  file=sys.stderr)
            return EXIT_ERROR
    else:
        # the only branch that touches jax — deferred so gating a log
        # works on hosts without an accelerator stack
        from apex_tpu.loadtest.runner import run_scenario

        run = run_scenario(scenario, log_path=args.out)
        records = run.records

    slo_report = (evaluate_slos(records, SLOSpec.from_dict(scenario.slo))
                  if scenario.slo else None)
    metrics = (dict(slo_report.metrics) if slo_report is not None
               else measure_slo_metrics(records))

    verdict = {
        "scenario": scenario.name,
        "seed": scenario.seed,
        "requests": sum(1 for r in records
                        if r.get("kind") == "request"),
        "slo": slo_report.as_dict() if slo_report else None,
        "metrics": metrics,
        "regressions": [],
        "exit": EXIT_OK,
    }
    if run is not None:
        verdict["wall_s"] = run.wall_s
        verdict["aborted"] = run.aborted
        verdict["engine_restarts"] = run.engine_restarts

    code = EXIT_OK
    if args.update_baseline:
        entry = update_baseline(args.baseline, scenario.name, metrics)
        verdict["baseline_written"] = entry
    elif args.check:
        try:
            baseline = load_baseline(args.baseline)
        except FileNotFoundError:
            baseline = {}
        except (OSError, ValueError) as exc:
            print(f"apex_tpu.loadtest: bad baseline {args.baseline}: "
                  f"{exc}", file=sys.stderr)
            return EXIT_ERROR
        entry = baseline.get(scenario.name)
        if entry is None:
            code = EXIT_NO_BASELINE
        else:
            regressions = compare_to_baseline(metrics, entry, tolerance)
            verdict["regressions"] = [r.describe() for r in regressions]
            if regressions:
                code = EXIT_REGRESSION
    # SLO violation outranks everything: a run that fails its declared
    # objectives is red regardless of baseline state
    if args.check and slo_report is not None and not slo_report.ok:
        code = EXIT_SLO_VIOLATION
    # ...except broken telemetry: a traced run whose span timelines do
    # not reconcile with its request records cannot be trusted to have
    # measured ANY of the above, so conservation failures outrank even
    # the SLO verdict. Vacuous on pre-tracing logs (no trace_id rows).
    if args.check:
        span_violations = check_span_conservation(records)
        verdict["span_violations"] = span_violations
        if span_violations:
            code = EXIT_ERROR
    verdict["exit"] = code

    if args.json:
        print(json.dumps(verdict, indent=2, default=str))
    else:
        _render(verdict, scenario, tolerance, args, code)
    return code


def _render(verdict: dict, scenario: Scenario, tolerance: float,
            args, code: int) -> None:
    print(f"== apex_tpu loadtest: {scenario.name} "
          f"(seed {scenario.seed}) ==")
    if "wall_s" in verdict:
        note = "  ABORTED (max_wall_s)" if verdict["aborted"] else ""
        print(f"requests: {verdict['requests']}  "
              f"wall: {verdict['wall_s']:.3f}s  "
              f"engine restarts: {verdict['engine_restarts']}{note}")
    else:
        print(f"requests: {verdict['requests']}  (scored from log)")
    slo = verdict["slo"]
    if slo:
        print(f"slo verdict: {'PASS' if slo['ok'] else 'FAIL'}")
        for o in slo["objectives"]:
            cmp_ = "<=" if o["direction"] == "max" else ">="
            print(f"  {'ok ' if o['ok'] else 'VIOLATED':<9}"
                  f"{o['name']:<16} measured={_fmt(o['measured']):<12} "
                  f"{cmp_} {o['threshold']:.6g}")
    else:
        print("slo verdict: (no objectives declared)")
        for name, value in sorted(verdict["metrics"].items()):
            print(f"  {name:<16} {_fmt(value)}")
    if "baseline_written" in verdict:
        print(f"baseline updated: {args.baseline} "
              f"[{scenario.name}] <- "
              f"{len(verdict['baseline_written'])} metrics")
    elif args.check:
        if code == EXIT_NO_BASELINE:
            print(f"baseline: {args.baseline} has no entry for "
                  f"{scenario.name!r} — run with --update-baseline "
                  f"to set the bar (exit {EXIT_NO_BASELINE})")
        elif verdict["regressions"]:
            print(f"regressions (tolerance {tolerance:.0%}):")
            for line in verdict["regressions"]:
                print(f"  {line}")
        else:
            print(f"baseline: no regression (tolerance {tolerance:.0%})")
    if verdict.get("span_violations"):
        print(f"span conservation: "
              f"{len(verdict['span_violations'])} violation(s):")
        for line in verdict["span_violations"][:10]:
            print(f"  {line}")
    elif args.check and "span_violations" in verdict:
        print("span conservation: OK")
    print(f"exit: {code}")


if __name__ == "__main__":
    sys.exit(main())
