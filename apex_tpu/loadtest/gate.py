"""The SLO regression gate: committed baseline, tolerance, verdict.

The second half of "make the serving claims measurable": a scenario run
that *passes its SLOs* can still be a regression — p99 TTFT doubling
from 5 ms to 10 ms is invisible to a 50 ms objective. The gate compares
the run's measured metrics against a **committed baseline**
(``SLO_BASELINE.json``, one entry per scenario name, written by
``python -m apex_tpu.loadtest --update-baseline``) and fails when any
metric moved the wrong way by more than the scenario's relative
``tolerance``:

- ``"max"``-direction metrics (latencies, error budget, recovery time —
  smaller is better) regress when
  ``measured > baseline * (1 + tolerance)``;
- ``"min"``-direction metrics (goodput) regress when
  ``measured < baseline * (1 - tolerance)``;
- a baselined metric the current run cannot measure at all (e.g.
  ``recovery_s`` with no disruption in the log) is a regression too —
  the scenario stopped exercising what the baseline recorded.

Improvements never fail the gate; re-commit them with
``--update-baseline`` so the bar ratchets. Wall-clock metrics are noisy
across machines — pick the tolerance for the machine class that runs
the gate (the committed scenarios use generous tolerances for shared
CI; tighten on dedicated hardware).

Pure stdlib, like the scorer: gating an existing log never imports jax.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional

from apex_tpu.observability.slo import SLO_METRICS

__all__ = ["DEFAULT_BASELINE", "Regression", "load_baseline",
           "update_baseline", "compare_to_baseline"]

#: repo-root default the CLI looks for (override with ``--baseline``)
DEFAULT_BASELINE = "SLO_BASELINE.json"


@dataclass(frozen=True)
class Regression:
    """One metric that moved past tolerance the wrong way."""

    metric: str
    direction: str              # from SLO_METRICS: "max" = lower-better
    baseline: float
    measured: Optional[float]   # None: the run could not measure it
    allowed: float              # the tolerance-adjusted bound crossed

    def describe(self) -> str:
        if self.measured is None:
            return (f"{self.metric}: baseline {self.baseline:.6g} but the "
                    f"run measured nothing (scenario no longer exercises "
                    f"this metric?)")
        worse = "above" if self.direction == "max" else "below"
        return (f"{self.metric}: measured {self.measured:.6g} is {worse} "
                f"the allowed {self.allowed:.6g} "
                f"(baseline {self.baseline:.6g})")


def load_baseline(path: str) -> Dict[str, Dict[str, float]]:
    """Read ``{scenario_name: {metric: value}}``; a malformed file is an
    error (a gate must not silently pass on a truncated baseline)."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or not all(
            isinstance(v, dict) for v in data.values()):
        raise ValueError(
            f"{path}: baseline must map scenario names to metric dicts")
    return data


def update_baseline(path: str, scenario_name: str,
                    metrics: Dict[str, Optional[float]]) -> Dict[str, float]:
    """Merge ``metrics`` (dropping unmeasured ``None`` and non-finite
    values — an unrecovered run must not become the bar) into the
    baseline file under ``scenario_name``; returns the entry written."""
    try:
        baseline = load_baseline(path)
    except FileNotFoundError:
        baseline = {}
    entry = {name: float(value) for name, value in sorted(metrics.items())
             if isinstance(value, (int, float))
             and value == value and value not in (float("inf"),
                                                  float("-inf"))}
    baseline[scenario_name] = entry
    with open(path, "w", encoding="utf-8") as f:
        json.dump(baseline, f, indent=2, sort_keys=True)
        f.write("\n")
    return entry


def compare_to_baseline(measured: Dict[str, Optional[float]],
                        baseline: Dict[str, float],
                        tolerance: float) -> List[Regression]:
    """Every baselined metric, checked directionally against its
    tolerance-adjusted bound. Metrics measured now but absent from the
    baseline are ignored (they join the bar at the next
    ``--update-baseline``)."""
    if tolerance < 0:
        raise ValueError(f"tolerance must be >= 0, got {tolerance}")
    regressions: List[Regression] = []
    for metric in sorted(baseline):
        base = baseline[metric]
        if metric not in SLO_METRICS:
            raise ValueError(
                f"baseline contains unknown metric {metric!r}; known: "
                f"{sorted(SLO_METRICS)}")
        direction = SLO_METRICS[metric][0]
        value = measured.get(metric)
        if direction == "max":
            allowed = base * (1.0 + tolerance)
            bad = value is None or value > allowed
        else:
            allowed = base * (1.0 - tolerance)
            bad = value is None or value < allowed
        if bad:
            regressions.append(Regression(
                metric=metric, direction=direction, baseline=float(base),
                measured=value, allowed=allowed))
    return regressions
