"""Declarative load-test scenarios: traffic shape, faults, SLOs.

A :class:`Scenario` is the unit the harness runs and the gate scores —
one JSON file describing *everything* a capacity/perf measurement needs
to be repeatable:

- the **model under test** (:class:`ModelSpec` — tiny dims for CI smoke,
  real dims on hardware; parameters are seeded so two runs serve the
  same weights);
- the **engine/supervisor sizing** (:class:`EngineKnobs` plus a
  validated passthrough dict for
  :class:`~apex_tpu.serving.SupervisorConfig`);
- the **traffic**, as ordered :class:`LoadPhase` segments — each an
  open-loop Poisson arrival process at its own rate with its own
  prompt-length / output-length / deadline / sampling mixes, so a
  scenario expresses warmup -> steady -> burst -> overload in one file;
- an optional **fault schedule** (:class:`FaultSchedule`) that drives
  :class:`~apex_tpu.testing_faults.ServingFaultInjector` — "inject an
  engine crash at decode call M, measure recovery" as data, not code;
- the declared **SLOs** (``{metric: threshold}`` over
  :data:`apex_tpu.observability.slo.SLO_METRICS`) and the regression
  ``tolerance`` the baseline gate applies.

This module is stdlib-only (the generator additionally needs just
:mod:`apex_tpu.serving.request`, which is host-side too): loading and
validating a scenario, or re-scoring an existing run log with
``python -m apex_tpu.loadtest --from-log``, runs no model code — jax
enters only through :mod:`~apex_tpu.loadtest.runner` when a scenario
actually executes.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from apex_tpu.observability.slo import SLO_METRICS

__all__ = ["ModelSpec", "EngineKnobs", "LoadPhase", "FaultSchedule",
           "FleetSpec", "AutoscaleSpec", "DeploySpec", "SentinelSpec",
           "RecorderSpec", "QuotaSpec", "BrownoutSpec", "Scenario"]

#: priority classes a phase may stamp on its traffic — mirrors
#: ``apex_tpu.serving.PRIORITIES`` (string literals here so scenario
#: loading stays jax-free, same pattern as ``OK_FINISH_REASONS``)
_PRIORITIES = ("interactive", "standard", "batch")

#: keys accepted in a scenario's ``"supervisor"`` section — mirrors the
#: :class:`~apex_tpu.serving.SupervisorConfig` fields so a typo fails at
#: scenario load, not deep in a run
_SUPERVISOR_KEYS = frozenset({
    "max_restarts_per_request", "max_engine_restarts", "breaker_threshold",
    "breaker_cooldown_s", "hung_tick_s", "shed_deadlines",
    "service_time_alpha"})


def _weighted(data: Dict[Any, Any], what: str) -> Dict[int, float]:
    """Normalize a ``{value: weight}`` mix (JSON keys arrive as strings)."""
    if not data:
        raise ValueError(f"{what} mix must be non-empty")
    out: Dict[int, float] = {}
    for key, weight in data.items():
        value = int(key)
        w = float(weight)
        if value < 1:
            raise ValueError(f"{what} values must be >= 1, got {value}")
        if w <= 0:
            raise ValueError(
                f"{what} weight for {value} must be > 0, got {w}")
        out[value] = w
    return out


@dataclass(frozen=True)
class ModelSpec:
    """The (seeded) model the scenario serves. Defaults are the tier-1
    smoke size — the same tiny GPT the serving tests use."""

    num_layers: int = 2
    hidden_size: int = 32
    num_attention_heads: int = 4
    vocab_size: int = 64
    max_position_embeddings: int = 64
    param_seed: int = 0

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "ModelSpec":
        return cls(**{k: int(v) for k, v in data.items()})

    def to_dict(self) -> Dict[str, int]:
        return {"num_layers": self.num_layers,
                "hidden_size": self.hidden_size,
                "num_attention_heads": self.num_attention_heads,
                "vocab_size": self.vocab_size,
                "max_position_embeddings": self.max_position_embeddings,
                "param_seed": self.param_seed}


@dataclass(frozen=True)
class EngineKnobs:
    """Engine/scheduler sizing — the subset of
    :class:`~apex_tpu.serving.EngineConfig` /
    :class:`~apex_tpu.serving.SchedulerConfig` a scenario varies.
    ``kv_layout``/``page_size``/``n_pages`` select and size the paged KV
    pool (docs/serving.md#paged-kv); ``n_pages=None`` fully backs every
    slot at ``max_len`` — set it lower to overcommit, which is how the
    ``long_context`` scenario expresses "this mix fits paged but could
    not fit dense rows in the same HBM". ``prefix_cache`` /
    ``prefix_lru_capacity`` drive the paged engine's shared-prefix
    interning (docs/serving.md#prefix-cache) — turning the cache off is
    how the ``shared_prefix`` scenario measures its own speedup.
    ``kv_dtype="int8"`` serves from the quantized pool
    (docs/serving.md#kv-quantization) and ``speculation=k`` turns on
    k-row speculative verify windows
    (docs/serving.md#speculative-decoding) — both paged-only, like the
    engine knobs they mirror. ``lora_adapters``/``lora_rank`` > 0 serve
    the traffic through a LoRA :class:`~apex_tpu.lora.AdapterStore` of
    that many seeded rank-``lora_rank`` adapters (ids ``"0"`` ..
    ``"n-1"``), which phases address via ``adapter_mix``
    (docs/serving.md#multi-lora)."""

    max_slots: int = 4
    max_len: int = 64
    max_queue: int = 64
    max_prefills_per_tick: int = 1
    kv_layout: str = "paged"
    page_size: int = 64
    n_pages: Optional[int] = None
    prefix_cache: bool = True
    prefix_lru_capacity: int = 32
    kv_dtype: str = "bf16"
    speculation: int = 0
    lora_rank: int = 0
    lora_adapters: int = 0
    #: chunked-prefill token budget per tick (docs/serving.md#chunked-
    #: prefill); None = monolithic prefill (the pre-PR-15 behavior)
    prefill_token_budget: Optional[int] = None

    def __post_init__(self):
        if self.kv_layout not in ("flat", "paged"):
            raise ValueError(
                f"kv_layout must be 'flat' or 'paged', got "
                f"{self.kv_layout!r}")
        if self.prefix_lru_capacity < 0:
            raise ValueError(
                f"prefix_lru_capacity must be >= 0, got "
                f"{self.prefix_lru_capacity}")
        # mirror EngineConfig's validation so a bad scenario fails at
        # parse time, not at engine construction mid-run
        if self.kv_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"kv_dtype must be 'bf16' or 'int8', got "
                f"{self.kv_dtype!r}")
        if self.kv_dtype == "int8" and self.kv_layout != "paged":
            raise ValueError(
                "kv_dtype='int8' needs kv_layout='paged' (scales are "
                "per-page)")
        if self.speculation < 0 or self.speculation == 1:
            raise ValueError(
                f"speculation must be 0 (off) or a window >= 2, got "
                f"{self.speculation}")
        if self.speculation and self.kv_layout != "paged":
            raise ValueError(
                "speculation needs kv_layout='paged' (the windowed "
                "verify rides the paged kernel)")
        if self.lora_rank < 0 or self.lora_adapters < 0:
            raise ValueError(
                f"lora_rank/lora_adapters must be >= 0, got "
                f"{self.lora_rank}/{self.lora_adapters}")
        if bool(self.lora_rank) != bool(self.lora_adapters):
            raise ValueError(
                f"lora_rank ({self.lora_rank}) and lora_adapters "
                f"({self.lora_adapters}) must be set together (both 0 "
                f"= no adapter store)")
        if self.prefill_token_budget is not None:
            if self.prefill_token_budget < 1:
                raise ValueError(
                    f"prefill_token_budget must be >= 1, got "
                    f"{self.prefill_token_budget}")
            if self.kv_layout == "paged" \
                    and self.prefill_token_budget < self.page_size:
                raise ValueError(
                    f"prefill_token_budget ({self.prefill_token_budget}) "
                    f"must be >= page_size ({self.page_size}) under the "
                    f"paged layout — chunk boundaries are page-aligned")

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "EngineKnobs":
        d = dict(data)
        kw: Dict[str, Any] = {}
        if "kv_layout" in d:
            kw["kv_layout"] = str(d.pop("kv_layout"))
        if "kv_dtype" in d:
            kw["kv_dtype"] = str(d.pop("kv_dtype"))
        if "n_pages" in d:
            n = d.pop("n_pages")
            kw["n_pages"] = int(n) if n is not None else None
        if "prefix_cache" in d:
            kw["prefix_cache"] = bool(d.pop("prefix_cache"))
        if "prefill_token_budget" in d:
            b = d.pop("prefill_token_budget")
            kw["prefill_token_budget"] = int(b) if b is not None else None
        kw.update({k: int(v) for k, v in d.items()})
        return cls(**kw)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "max_slots": self.max_slots, "max_len": self.max_len,
            "max_queue": self.max_queue,
            "max_prefills_per_tick": self.max_prefills_per_tick,
            "kv_layout": self.kv_layout, "page_size": self.page_size}
        if self.n_pages is not None:
            out["n_pages"] = self.n_pages
        if not self.prefix_cache:
            out["prefix_cache"] = False
        if self.prefix_lru_capacity != 32:
            out["prefix_lru_capacity"] = self.prefix_lru_capacity
        if self.kv_dtype != "bf16":
            out["kv_dtype"] = self.kv_dtype
        if self.speculation:
            out["speculation"] = self.speculation
        if self.lora_adapters:
            out["lora_rank"] = self.lora_rank
            out["lora_adapters"] = self.lora_adapters
        if self.prefill_token_budget is not None:
            out["prefill_token_budget"] = self.prefill_token_budget
        return out


@dataclass(frozen=True)
class LoadPhase:
    """One open-loop traffic segment.

    ``n_requests`` arrivals are generated with exponential inter-arrival
    gaps at ``rate_rps`` (a Poisson process — arrivals do NOT wait for
    completions; overload is expressed by a rate the engine cannot
    sustain). Prompt and output lengths draw from ``{value: weight}``
    mixes; ``deadline_fraction`` of requests carry a deadline uniform in
    ``[deadline_min_s, deadline_max_s]``; ``greedy_fraction`` decode
    greedily, the rest sample at a drawn temperature/top-k (``top_ks``
    entry ``0`` means untruncated). ``shared_prefix_len`` > 0 makes
    every prompt in the phase open with the SAME ``shared_prefix_len``
    seeded tokens (drawn once at phase start) — the multi-turn /
    system-prompt traffic shape the engine's prefix cache exists for.
    ``prompt_period`` > 0 makes each prompt PERIODIC (its tokens repeat
    with that period) — the repeated-text traffic shape whose n-gram
    structure the self-speculative drafter exploits
    (docs/serving.md#speculative-decoding). ``adapter_mix`` is a
    ``{adapter_id: weight}`` draw over the LoRA tenants each request
    serves under — the special id ``"base"`` means no adapter; every
    other id must name an adapter the engine's store holds (the runner
    loads ids ``"0"`` .. ``"lora_adapters-1"``). Empty = all-base
    traffic with NO extra generator draws, so pre-LoRA scenarios
    reproduce byte-identical schedules (docs/serving.md#multi-lora).
    """

    name: str
    n_requests: int
    rate_rps: float
    prompt_lens: Dict[int, float]
    max_new_tokens: Dict[int, float]
    deadline_fraction: float = 0.0
    deadline_min_s: float = 1.0
    deadline_max_s: float = 1.0
    greedy_fraction: float = 1.0
    temperatures: Tuple[float, ...] = (0.7,)
    top_ks: Tuple[int, ...] = (0,)
    eos_token: Optional[int] = None
    shared_prefix_len: int = 0
    prompt_period: int = 0
    adapter_mix: Dict[str, float] = field(default_factory=dict)
    #: priority class every request in this phase carries (a FIXED
    #: per-phase knob, deliberately not a random mix: no extra generator
    #: draw, so pre-priority scenarios reproduce byte-identical
    #: schedules). None = the engine default ("standard").
    priority: Optional[str] = None

    def __post_init__(self):
        if self.n_requests < 1:
            raise ValueError(
                f"phase {self.name!r}: n_requests must be >= 1, got "
                f"{self.n_requests}")
        if self.rate_rps <= 0:
            raise ValueError(
                f"phase {self.name!r}: rate_rps must be > 0, got "
                f"{self.rate_rps}")
        if not 0.0 <= self.deadline_fraction <= 1.0:
            raise ValueError(
                f"phase {self.name!r}: deadline_fraction must be in "
                f"[0, 1], got {self.deadline_fraction}")
        if self.deadline_fraction > 0 and not \
                0 < self.deadline_min_s <= self.deadline_max_s:
            raise ValueError(
                f"phase {self.name!r}: need 0 < deadline_min_s <= "
                f"deadline_max_s, got [{self.deadline_min_s}, "
                f"{self.deadline_max_s}]")
        if not 0.0 <= self.greedy_fraction <= 1.0:
            raise ValueError(
                f"phase {self.name!r}: greedy_fraction must be in [0, 1], "
                f"got {self.greedy_fraction}")
        if self.greedy_fraction < 1.0:
            if not self.temperatures or \
                    any(t <= 0 for t in self.temperatures):
                raise ValueError(
                    f"phase {self.name!r}: sampled traffic needs positive "
                    f"temperatures, got {self.temperatures}")
            if any(k < 0 for k in self.top_ks):
                raise ValueError(
                    f"phase {self.name!r}: top_ks must be >= 0 "
                    f"(0 = untruncated), got {self.top_ks}")
        if self.shared_prefix_len < 0:
            raise ValueError(
                f"phase {self.name!r}: shared_prefix_len must be >= 0, "
                f"got {self.shared_prefix_len}")
        if self.shared_prefix_len > min(self.prompt_lens):
            raise ValueError(
                f"phase {self.name!r}: shared_prefix_len "
                f"({self.shared_prefix_len}) exceeds the shortest "
                f"prompt length in the mix ({min(self.prompt_lens)})")
        if self.prompt_period < 0:
            raise ValueError(
                f"phase {self.name!r}: prompt_period must be >= 0, "
                f"got {self.prompt_period}")
        for aid, w in self.adapter_mix.items():
            if not isinstance(aid, str) or not aid:
                raise ValueError(
                    f"phase {self.name!r}: adapter_mix keys must be "
                    f"non-empty strings, got {aid!r}")
            if float(w) <= 0:
                raise ValueError(
                    f"phase {self.name!r}: adapter_mix weight for "
                    f"{aid!r} must be > 0, got {w}")
        if self.priority is not None and self.priority not in _PRIORITIES:
            raise ValueError(
                f"phase {self.name!r}: priority must be one of "
                f"{_PRIORITIES}, got {self.priority!r}")

    @property
    def max_total_len(self) -> int:
        return max(self.prompt_lens) + max(self.max_new_tokens)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "LoadPhase":
        d = dict(data)
        name = str(d.pop("name", "phase"))
        eos = d.pop("eos_token", None)
        prio = d.pop("priority", None)
        phase = cls(
            name=name,
            n_requests=int(d.pop("n_requests")),
            rate_rps=float(d.pop("rate_rps")),
            prompt_lens=_weighted(d.pop("prompt_lens"),
                                  f"phase {name!r} prompt_lens"),
            max_new_tokens=_weighted(d.pop("max_new_tokens"),
                                     f"phase {name!r} max_new_tokens"),
            deadline_fraction=float(d.pop("deadline_fraction", 0.0)),
            deadline_min_s=float(d.pop("deadline_min_s", 1.0)),
            deadline_max_s=float(d.pop("deadline_max_s", 1.0)),
            greedy_fraction=float(d.pop("greedy_fraction", 1.0)),
            temperatures=tuple(float(t)
                               for t in d.pop("temperatures", (0.7,))),
            top_ks=tuple(int(k) for k in d.pop("top_ks", (0,))),
            eos_token=int(eos) if eos is not None else None,
            shared_prefix_len=int(d.pop("shared_prefix_len", 0)),
            prompt_period=int(d.pop("prompt_period", 0)),
            adapter_mix={str(k): float(v)
                         for k, v in d.pop("adapter_mix", {}).items()},
            priority=str(prio) if prio is not None else None)
        if d:
            raise ValueError(
                f"phase {name!r}: unknown keys {sorted(d)}")
        return phase

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name, "n_requests": self.n_requests,
            "rate_rps": self.rate_rps,
            "prompt_lens": {str(k): v
                            for k, v in self.prompt_lens.items()},
            "max_new_tokens": {str(k): v
                               for k, v in self.max_new_tokens.items()}}
        if self.deadline_fraction > 0:
            out["deadline_fraction"] = self.deadline_fraction
            out["deadline_min_s"] = self.deadline_min_s
            out["deadline_max_s"] = self.deadline_max_s
        if self.greedy_fraction < 1.0:
            out["greedy_fraction"] = self.greedy_fraction
            out["temperatures"] = list(self.temperatures)
            out["top_ks"] = list(self.top_ks)
        if self.eos_token is not None:
            out["eos_token"] = self.eos_token
        if self.shared_prefix_len > 0:
            out["shared_prefix_len"] = self.shared_prefix_len
        if self.prompt_period > 0:
            out["prompt_period"] = self.prompt_period
        if self.adapter_mix:
            out["adapter_mix"] = dict(self.adapter_mix)
        if self.priority is not None:
            out["priority"] = self.priority
        return out


@dataclass(frozen=True)
class FaultSchedule:
    """Plain-data mirror of :class:`~apex_tpu.testing_faults.\
ServingFaultInjector`'s schedule (kept jax-free here; the runner builds
    the injector). Call indices are the INJECTOR's own monotonically
    advancing decode/prefill counters — they keep counting across engine
    rebuilds, so a scheduled fault fires exactly once."""

    decode_raise_calls: Tuple[int, ...] = ()
    prefill_raise_calls: Tuple[int, ...] = ()
    decode_hang: Dict[int, float] = field(default_factory=dict)
    poison_decode: Dict[int, Tuple[int, str]] = field(default_factory=dict)

    @property
    def empty(self) -> bool:
        return not (self.decode_raise_calls or self.prefill_raise_calls
                    or self.decode_hang or self.poison_decode)

    def injector_kwargs(self) -> Dict[str, Any]:
        """Constructor kwargs for ``ServingFaultInjector``."""
        return {"decode_raise_calls": self.decode_raise_calls,
                "prefill_raise_calls": self.prefill_raise_calls,
                "decode_hang": dict(self.decode_hang),
                "poison_decode": dict(self.poison_decode)}

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "FaultSchedule":
        return cls(
            decode_raise_calls=tuple(
                int(c) for c in data.get("decode_raise_calls", ())),
            prefill_raise_calls=tuple(
                int(c) for c in data.get("prefill_raise_calls", ())),
            decode_hang={int(k): float(v)
                         for k, v in data.get("decode_hang", {}).items()},
            poison_decode={int(k): (int(v[0]), str(v[1]))
                           for k, v in data.get("poison_decode",
                                                {}).items()})

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.decode_raise_calls:
            out["decode_raise_calls"] = list(self.decode_raise_calls)
        if self.prefill_raise_calls:
            out["prefill_raise_calls"] = list(self.prefill_raise_calls)
        if self.decode_hang:
            out["decode_hang"] = {str(k): v
                                  for k, v in self.decode_hang.items()}
        if self.poison_decode:
            out["poison_decode"] = {str(k): list(v)
                                    for k, v in self.poison_decode.items()}
        return out


@dataclass(frozen=True)
class FleetSpec:
    """Optional ``"fleet"`` scenario block: run the traffic against a
    :class:`~apex_tpu.serving.fleet.ReplicaFleet` of ``n_replicas``
    supervised engines instead of a single supervisor.

    ``drain_restarts`` is the fleet-level fault kind: each
    ``(at_s, replica)`` entry schedules a DRAINING restart of that
    replica at ``at_s`` seconds into the run — the runner quiesces it,
    migrates or finishes its in-flight work, rebuilds and health-probes
    it, all while the rest of the fleet keeps serving (capacity >= N-1).
    The scenario's regular ``faults`` schedule applies to replica 0.
    """

    n_replicas: int = 2
    migrate_on_drain: bool = True
    probe_on_rebuild: bool = True
    drain_restarts: Tuple[Tuple[float, int], ...] = ()

    def __post_init__(self):
        if self.n_replicas < 1:
            raise ValueError(
                f"fleet n_replicas must be >= 1, got {self.n_replicas}")
        for at_s, replica in self.drain_restarts:
            if at_s < 0:
                raise ValueError(
                    f"drain_restart at_s must be >= 0, got {at_s}")
            if not 0 <= replica < self.n_replicas:
                raise ValueError(
                    f"drain_restart replica {replica} out of range "
                    f"[0, {self.n_replicas})")

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "FleetSpec":
        d = dict(data)
        spec = cls(
            n_replicas=int(d.pop("n_replicas", 2)),
            migrate_on_drain=bool(d.pop("migrate_on_drain", True)),
            probe_on_rebuild=bool(d.pop("probe_on_rebuild", True)),
            drain_restarts=tuple(
                (float(e["at_s"]), int(e["replica"]))
                for e in d.pop("drain_restarts", ())))
        if d:
            raise ValueError(f"unknown fleet keys {sorted(d)}")
        return spec

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"n_replicas": self.n_replicas}
        if not self.migrate_on_drain:
            out["migrate_on_drain"] = False
        if not self.probe_on_rebuild:
            out["probe_on_rebuild"] = False
        if self.drain_restarts:
            out["drain_restarts"] = [
                {"at_s": at_s, "replica": replica}
                for at_s, replica in self.drain_restarts]
        return out


@dataclass(frozen=True)
class AutoscaleSpec:
    """Optional ``"autoscale"`` scenario block: run the fleet under an
    :class:`~apex_tpu.serving.fleet.Autoscaler` that grows/shrinks the
    replica count between ``min_replicas``/``max_replicas`` off the
    live :meth:`~apex_tpu.observability.FleetMetrics.signals` poll
    (docs/serving.md#autoscaling). Fields mirror
    :class:`~apex_tpu.serving.fleet.AutoscaleConfig` (kept jax-free
    here; the runner builds the config) so a typo fails at scenario
    load, not deep in a run. Requires a ``"fleet"`` block whose
    ``n_replicas`` lies inside the band."""

    min_replicas: int = 1
    max_replicas: int = 4
    poll_interval_s: float = 0.25
    cooldown_s: float = 2.0
    hysteresis_polls: int = 2
    scale_up_queue_per_replica: float = 4.0
    scale_up_queued_tokens_per_replica: float = 0.0
    scale_up_goodput: float = 0.0
    scale_up_ttft_p99_s: float = 0.0
    scale_down_queue_per_replica: float = 0.5
    scale_down_slot_occupancy: float = 0.25

    def __post_init__(self):
        # mirror AutoscaleConfig's validation so a bad scenario fails
        # at parse time, not at fleet construction mid-run
        if self.min_replicas < 1:
            raise ValueError(
                f"autoscale min_replicas must be >= 1, got "
                f"{self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"autoscale max_replicas ({self.max_replicas}) must be "
                f">= min_replicas ({self.min_replicas})")
        if self.poll_interval_s <= 0:
            raise ValueError(
                f"autoscale poll_interval_s must be > 0, got "
                f"{self.poll_interval_s}")
        if self.cooldown_s < 0:
            raise ValueError(
                f"autoscale cooldown_s must be >= 0, got "
                f"{self.cooldown_s}")
        if self.hysteresis_polls < 1:
            raise ValueError(
                f"autoscale hysteresis_polls must be >= 1, got "
                f"{self.hysteresis_polls}")

    def config_kwargs(self) -> Dict[str, Any]:
        """Constructor kwargs for ``AutoscaleConfig``."""
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "poll_interval_s": self.poll_interval_s,
            "cooldown_s": self.cooldown_s,
            "hysteresis_polls": self.hysteresis_polls,
            "scale_up_queue_per_replica": self.scale_up_queue_per_replica,
            "scale_up_queued_tokens_per_replica":
                self.scale_up_queued_tokens_per_replica,
            "scale_up_goodput": self.scale_up_goodput,
            "scale_up_ttft_p99_s": self.scale_up_ttft_p99_s,
            "scale_down_queue_per_replica":
                self.scale_down_queue_per_replica,
            "scale_down_slot_occupancy": self.scale_down_slot_occupancy,
        }

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "AutoscaleSpec":
        d = dict(data)
        kw: Dict[str, Any] = {}
        for key in ("min_replicas", "max_replicas", "hysteresis_polls"):
            if key in d:
                kw[key] = int(d.pop(key))
        for key in ("poll_interval_s", "cooldown_s",
                    "scale_up_queue_per_replica",
                    "scale_up_queued_tokens_per_replica",
                    "scale_up_goodput", "scale_up_ttft_p99_s",
                    "scale_down_queue_per_replica",
                    "scale_down_slot_occupancy"):
            if key in d:
                kw[key] = float(d.pop(key))
        if d:
            raise ValueError(f"unknown autoscale keys {sorted(d)}")
        return cls(**kw)

    def to_dict(self) -> Dict[str, Any]:
        defaults = AutoscaleSpec()
        out: Dict[str, Any] = {"min_replicas": self.min_replicas,
                               "max_replicas": self.max_replicas}
        out.update({k: v for k, v in self.config_kwargs().items()
                    if v != getattr(defaults, k)})
        return out


#: keys accepted in a deploy block's ``"canary"`` section — mirrors
#: :class:`~apex_tpu.serving.fleet.CanaryConfig`
_CANARY_KEYS = frozenset({
    "window_s", "min_requests", "max_window_s", "max_error_rate",
    "latency_ratio"})


@dataclass(frozen=True)
class DeploySpec:
    """Optional ``"deploy"`` scenario block: at ``at_s`` seconds into
    the run, fire a :meth:`~apex_tpu.serving.fleet.ReplicaFleet.deploy`
    — a rolling, canary-scored weight rollout
    (docs/serving.md#continuous-deployment).

    ``kind="checkpoint"`` saves the scenario's own (seeded) parameters
    through a :class:`~apex_tpu.checkpoint.ShardedCheckpointManager`
    into a scratch directory and deploys that step — a happy-path
    deploy is therefore weight-identical and must be token-exact.
    ``kind="adapter"`` hot-loads a seeded LoRA adapter ``adapter_id``
    as a canary tenant (needs ``engine.lora_adapters`` > 0).
    ``poison=true`` corrupts the artifact's values post-commit with
    non-finite weights (``corrupt_checkpoint_weights`` — manifest and
    checksums stay green) so the deploy must be caught by the live
    canary score and rolled back, not by fsck. ``canary`` is a
    validated passthrough for
    :class:`~apex_tpu.serving.fleet.CanaryConfig` kwargs."""

    at_s: float
    kind: str = "checkpoint"
    poison: bool = False
    adapter_id: str = "canary"
    canary: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.at_s < 0:
            raise ValueError(
                f"deploy at_s must be >= 0, got {self.at_s}")
        if self.kind not in ("checkpoint", "adapter"):
            raise ValueError(
                f"deploy kind must be 'checkpoint' or 'adapter', got "
                f"{self.kind!r}")
        if self.kind == "adapter" and not self.adapter_id:
            raise ValueError("deploy adapter_id must be non-empty")
        unknown = set(self.canary) - _CANARY_KEYS
        if unknown:
            raise ValueError(
                f"unknown deploy canary keys {sorted(unknown)}; known: "
                f"{sorted(_CANARY_KEYS)}")

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "DeploySpec":
        d = dict(data)
        spec = cls(
            at_s=float(d.pop("at_s")),
            kind=str(d.pop("kind", "checkpoint")),
            poison=bool(d.pop("poison", False)),
            adapter_id=str(d.pop("adapter_id", "canary")),
            canary=dict(d.pop("canary", {})))
        if d:
            raise ValueError(f"unknown deploy keys {sorted(d)}")
        return spec

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"at_s": self.at_s, "kind": self.kind}
        if self.poison:
            out["poison"] = True
        if self.kind == "adapter":
            out["adapter_id"] = self.adapter_id
        if self.canary:
            out["canary"] = dict(self.canary)
        return out


@dataclass(frozen=True)
class SentinelSpec:
    """Optional ``"sentinel"`` scenario block: run the fleet under a
    :class:`~apex_tpu.observability.DriftSentinel` polling
    ``FleetMetrics.signals()`` from the tick (docs/observability.md#
    drift-sentinel). Fields mirror
    :class:`~apex_tpu.observability.SentinelConfig` (kept jax-free
    here; the runner builds the config) so a typo fails at scenario
    load. Requires a ``"fleet"`` block — the sentinel rides the fleet
    tick."""

    poll_interval_s: float = 0.25
    warmup_polls: int = 8
    ewma_alpha: float = 0.2
    z_threshold: float = 4.0
    hysteresis_polls: int = 2
    cooldown_s: float = 10.0
    min_abs_dev: float = 1e-3
    snapshot_every_polls: int = 4
    signals: Tuple[str, ...] = ("ttft_p99_s", "tpot_p99_s",
                                "goodput_window", "queue_depth",
                                "spec_accept_rate")

    def __post_init__(self):
        # mirror SentinelConfig's validation so a bad scenario fails at
        # parse time, not at fleet construction mid-run
        if self.poll_interval_s <= 0:
            raise ValueError(
                f"sentinel poll_interval_s must be > 0, got "
                f"{self.poll_interval_s}")
        if self.warmup_polls < 1:
            raise ValueError(
                f"sentinel warmup_polls must be >= 1, got "
                f"{self.warmup_polls}")
        if not (0.0 < self.ewma_alpha <= 1.0):
            raise ValueError(
                f"sentinel ewma_alpha must be in (0, 1], got "
                f"{self.ewma_alpha}")
        if self.z_threshold <= 0:
            raise ValueError(
                f"sentinel z_threshold must be > 0, got "
                f"{self.z_threshold}")
        if self.hysteresis_polls < 1:
            raise ValueError(
                f"sentinel hysteresis_polls must be >= 1, got "
                f"{self.hysteresis_polls}")
        if self.cooldown_s < 0:
            raise ValueError(
                f"sentinel cooldown_s must be >= 0, got "
                f"{self.cooldown_s}")
        if self.min_abs_dev <= 0:
            raise ValueError(
                f"sentinel min_abs_dev must be > 0, got "
                f"{self.min_abs_dev}")
        if self.snapshot_every_polls < 0:
            raise ValueError(
                f"sentinel snapshot_every_polls must be >= 0, got "
                f"{self.snapshot_every_polls}")
        if not self.signals:
            raise ValueError(
                "sentinel signals must name at least one signal")

    def config_kwargs(self) -> Dict[str, Any]:
        """Constructor kwargs for ``SentinelConfig``."""
        return {
            "poll_interval_s": self.poll_interval_s,
            "warmup_polls": self.warmup_polls,
            "ewma_alpha": self.ewma_alpha,
            "z_threshold": self.z_threshold,
            "hysteresis_polls": self.hysteresis_polls,
            "cooldown_s": self.cooldown_s,
            "min_abs_dev": self.min_abs_dev,
            "snapshot_every_polls": self.snapshot_every_polls,
            "signals": self.signals,
        }

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "SentinelSpec":
        d = dict(data)
        kw: Dict[str, Any] = {}
        for key in ("warmup_polls", "hysteresis_polls",
                    "snapshot_every_polls"):
            if key in d:
                kw[key] = int(d.pop(key))
        for key in ("poll_interval_s", "ewma_alpha", "z_threshold",
                    "cooldown_s", "min_abs_dev"):
            if key in d:
                kw[key] = float(d.pop(key))
        if "signals" in d:
            kw["signals"] = tuple(str(s) for s in d.pop("signals"))
        if d:
            raise ValueError(f"unknown sentinel keys {sorted(d)}")
        return cls(**kw)

    def to_dict(self) -> Dict[str, Any]:
        defaults = SentinelSpec()
        out = {k: v for k, v in self.config_kwargs().items()
               if v != getattr(defaults, k)}
        if "signals" in out:
            out["signals"] = list(out["signals"])
        return out


@dataclass(frozen=True)
class RecorderSpec:
    """Optional ``"recorder"`` scenario block: attach a
    :class:`~apex_tpu.observability.FlightRecorder` to the run's
    registry so any incident-class event dumps a postmortem bundle next
    to the run log (docs/observability.md#flight-recorder). Fields
    mirror the recorder's constructor knobs."""

    events_capacity: int = 256
    records_capacity: int = 256
    gauges_capacity: int = 64
    max_bundles: int = 1

    def __post_init__(self):
        for knob in ("events_capacity", "records_capacity",
                     "gauges_capacity"):
            if getattr(self, knob) < 1:
                raise ValueError(
                    f"recorder {knob} must be >= 1, "
                    f"got {getattr(self, knob)}")
        if self.max_bundles < 0:
            raise ValueError(
                f"recorder max_bundles must be >= 0, got "
                f"{self.max_bundles}")

    def recorder_kwargs(self) -> Dict[str, Any]:
        """Constructor kwargs for ``FlightRecorder`` (the runner adds
        ``bundle_dir``/``bundle_prefix`` from the run-log path)."""
        return {
            "events_capacity": self.events_capacity,
            "records_capacity": self.records_capacity,
            "gauges_capacity": self.gauges_capacity,
            "max_bundles": self.max_bundles,
        }

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "RecorderSpec":
        d = dict(data)
        kw: Dict[str, Any] = {}
        for key in ("events_capacity", "records_capacity",
                    "gauges_capacity", "max_bundles"):
            if key in d:
                kw[key] = int(d.pop(key))
        if d:
            raise ValueError(f"unknown recorder keys {sorted(d)}")
        return cls(**kw)

    def to_dict(self) -> Dict[str, Any]:
        defaults = RecorderSpec()
        return {k: v for k, v in self.recorder_kwargs().items()
                if v != getattr(defaults, k)}


#: keys accepted in a quota tenant entry — mirrors
#: :class:`~apex_tpu.serving.fleet.TenantQuota`
_TENANT_QUOTA_KEYS = frozenset({
    "rate_rps", "burst", "max_inflight", "max_pages", "soft"})


def _tenant_quota_entry(data: Dict[str, Any], what: str) -> Dict[str, Any]:
    """Validate + coerce one tenant-quota dict (mirrors ``TenantQuota``
    validation so a bad scenario fails at parse time, jax-free)."""
    unknown = set(data) - _TENANT_QUOTA_KEYS
    if unknown:
        raise ValueError(
            f"unknown {what} keys {sorted(unknown)}; known: "
            f"{sorted(_TENANT_QUOTA_KEYS)}")
    entry: Dict[str, Any] = {}
    for key in ("rate_rps", "burst"):
        if key in data:
            entry[key] = float(data[key])
    for key in ("max_inflight", "max_pages"):
        if key in data:
            entry[key] = int(data[key])
    if "soft" in data:
        entry["soft"] = bool(data["soft"])
    if entry.get("rate_rps", 0.0) < 0:
        raise ValueError(
            f"{what}: rate_rps must be >= 0, got {entry['rate_rps']}")
    if entry.get("burst", 1.0) < 1.0:
        raise ValueError(
            f"{what}: burst must be >= 1, got {entry['burst']}")
    for key in ("max_inflight", "max_pages"):
        if entry.get(key, 0) < 0:
            raise ValueError(
                f"{what}: {key} must be >= 0, got {entry[key]}")
    return entry


@dataclass(frozen=True)
class QuotaSpec:
    """Optional ``"quotas"`` scenario block: run the fleet front door
    behind a per-tenant :class:`~apex_tpu.serving.fleet.QuotaLedger`
    (docs/serving.md#priority-preemption-and-quotas). ``tenants`` maps
    tenant keys (adapter ids, or ``"base"``) to
    :class:`~apex_tpu.serving.fleet.TenantQuota` kwargs; ``default``
    applies to tenants not named. Kept jax-free here — the runner
    builds the ledger. Requires a ``"fleet"`` block."""

    tenants: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    default: Optional[Dict[str, Any]] = None

    def __post_init__(self):
        for key, entry in self.tenants.items():
            if not isinstance(key, str) or not key:
                raise ValueError(
                    f"quota tenant keys must be non-empty strings, "
                    f"got {key!r}")
            _tenant_quota_entry(entry, f"quota tenant {key!r}")
        if self.default is not None:
            _tenant_quota_entry(self.default, "quota default")
        if not self.tenants and self.default is None:
            raise ValueError(
                "a 'quotas' block must name at least one tenant or a "
                "default")

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "QuotaSpec":
        d = dict(data)
        spec = cls(
            tenants={str(k): _tenant_quota_entry(
                dict(v), f"quota tenant {k!r}")
                for k, v in d.pop("tenants", {}).items()},
            default=(_tenant_quota_entry(dict(d.pop("default")),
                                         "quota default")
                     if d.get("default") is not None
                     else d.pop("default", None)))
        if d:
            raise ValueError(f"unknown quotas keys {sorted(d)}")
        return spec

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.tenants:
            out["tenants"] = {k: dict(v) for k, v in self.tenants.items()}
        if self.default is not None:
            out["default"] = dict(self.default)
        return out


@dataclass(frozen=True)
class BrownoutSpec:
    """Optional ``"brownout"`` scenario block: run the fleet under a
    :class:`~apex_tpu.serving.fleet.BrownoutController` that walks the
    staged-degradation ladder off the live signals poll
    (docs/serving.md#priority-preemption-and-quotas). Fields mirror
    :class:`~apex_tpu.serving.fleet.BrownoutConfig` (kept jax-free
    here; the runner builds the config) so a typo fails at scenario
    load. Requires a ``"fleet"`` block — the controller rides the
    fleet tick."""

    poll_interval_s: float = 0.25
    queue_depth_high: float = 8.0
    queue_depth_low: float = 2.0
    hot_polls: int = 2
    cool_polls: int = 2
    clamp_max_new_tokens: int = 32
    max_rung: int = 4

    def __post_init__(self):
        # mirror BrownoutConfig's validation so a bad scenario fails at
        # parse time, not at fleet construction mid-run
        if self.poll_interval_s <= 0:
            raise ValueError(
                f"brownout poll_interval_s must be > 0, got "
                f"{self.poll_interval_s}")
        if self.queue_depth_high <= 0:
            raise ValueError(
                f"brownout queue_depth_high must be > 0, got "
                f"{self.queue_depth_high}")
        if not 0 <= self.queue_depth_low < self.queue_depth_high:
            raise ValueError(
                f"brownout queue_depth_low ({self.queue_depth_low}) "
                f"must be in [0, queue_depth_high="
                f"{self.queue_depth_high})")
        if self.hot_polls < 1:
            raise ValueError(
                f"brownout hot_polls must be >= 1, got {self.hot_polls}")
        if self.cool_polls < 1:
            raise ValueError(
                f"brownout cool_polls must be >= 1, got "
                f"{self.cool_polls}")
        if self.clamp_max_new_tokens < 1:
            raise ValueError(
                f"brownout clamp_max_new_tokens must be >= 1, got "
                f"{self.clamp_max_new_tokens}")
        if not 0 <= self.max_rung <= 4:
            raise ValueError(
                f"brownout max_rung must be in [0, 4], got "
                f"{self.max_rung}")

    def config_kwargs(self) -> Dict[str, Any]:
        """Constructor kwargs for ``BrownoutConfig``."""
        return {
            "poll_interval_s": self.poll_interval_s,
            "queue_depth_high": self.queue_depth_high,
            "queue_depth_low": self.queue_depth_low,
            "hot_polls": self.hot_polls,
            "cool_polls": self.cool_polls,
            "clamp_max_new_tokens": self.clamp_max_new_tokens,
            "max_rung": self.max_rung,
        }

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "BrownoutSpec":
        d = dict(data)
        kw: Dict[str, Any] = {}
        for key in ("hot_polls", "cool_polls", "clamp_max_new_tokens",
                    "max_rung"):
            if key in d:
                kw[key] = int(d.pop(key))
        for key in ("poll_interval_s", "queue_depth_high",
                    "queue_depth_low"):
            if key in d:
                kw[key] = float(d.pop(key))
        if d:
            raise ValueError(f"unknown brownout keys {sorted(d)}")
        return cls(**kw)

    def to_dict(self) -> Dict[str, Any]:
        defaults = BrownoutSpec()
        return {k: v for k, v in self.config_kwargs().items()
                if v != getattr(defaults, k)}


@dataclass(frozen=True)
class Scenario:
    """One complete load-test description; see the module docstring.

    ``seed`` drives every random draw the traffic generator makes;
    ``slo`` declares the objectives the run is scored against;
    ``tolerance`` is the relative slack the regression gate allows
    against the committed baseline; ``max_wall_s`` is the harness's own
    runaway guard — a scenario that cannot finish inside it is aborted
    (remaining requests cancelled, recorded terminally, and the abort
    stamped into the log as an event).
    """

    name: str
    phases: Tuple[LoadPhase, ...]
    seed: int = 0
    description: str = ""
    model: ModelSpec = field(default_factory=ModelSpec)
    engine: EngineKnobs = field(default_factory=EngineKnobs)
    supervisor: Dict[str, Any] = field(default_factory=dict)
    faults: FaultSchedule = field(default_factory=FaultSchedule)
    fleet: Optional[FleetSpec] = None
    autoscale: Optional[AutoscaleSpec] = None
    deploy: Optional[DeploySpec] = None
    sentinel: Optional[SentinelSpec] = None
    recorder: Optional[RecorderSpec] = None
    quotas: Optional[QuotaSpec] = None
    brownout: Optional[BrownoutSpec] = None
    slo: Dict[str, float] = field(default_factory=dict)
    tolerance: float = 0.25
    max_wall_s: float = 300.0

    def __post_init__(self):
        if not self.name:
            raise ValueError("scenario name must be non-empty")
        if not self.phases:
            raise ValueError(f"scenario {self.name!r} needs >= 1 phase")
        if self.tolerance < 0:
            raise ValueError(
                f"tolerance must be >= 0, got {self.tolerance}")
        if self.max_wall_s <= 0:
            raise ValueError(
                f"max_wall_s must be > 0, got {self.max_wall_s}")
        unknown = set(self.supervisor) - _SUPERVISOR_KEYS
        if unknown:
            raise ValueError(
                f"unknown supervisor knobs {sorted(unknown)}; known: "
                f"{sorted(_SUPERVISOR_KEYS)}")
        for metric in self.slo:
            if metric not in SLO_METRICS:
                raise ValueError(
                    f"unknown SLO metric {metric!r}; known: "
                    f"{sorted(SLO_METRICS)}")
        for phase in self.phases:
            if phase.max_total_len > self.engine.max_len:
                raise ValueError(
                    f"phase {phase.name!r}: worst-case prompt + "
                    f"max_new_tokens ({phase.max_total_len}) exceeds "
                    f"engine max_len ({self.engine.max_len})")
            for k in phase.top_ks:
                if k > self.model.vocab_size:
                    raise ValueError(
                        f"phase {phase.name!r}: top_k {k} exceeds vocab "
                        f"size {self.model.vocab_size}")
            if phase.eos_token is not None and not \
                    0 <= phase.eos_token < self.model.vocab_size:
                raise ValueError(
                    f"phase {phase.name!r}: eos_token {phase.eos_token} "
                    f"out of vocab [0, {self.model.vocab_size})")
            deploy_aid = (self.deploy.adapter_id
                          if self.deploy is not None
                          and self.deploy.kind == "adapter" else None)
            for aid in phase.adapter_mix:
                # the deploy block's canary tenant may be addressed too
                # (requests before the deploy fires shed as unknown —
                # the tenant comes online mid-run, by design)
                if aid == "base" or aid == deploy_aid:
                    continue
                if not self.engine.lora_adapters:
                    raise ValueError(
                        f"phase {phase.name!r}: adapter_mix names "
                        f"adapter {aid!r} but the engine has no "
                        f"adapter store (set engine.lora_adapters/"
                        f"lora_rank)")
                if not (aid.isdigit()
                        and int(aid) < self.engine.lora_adapters):
                    raise ValueError(
                        f"phase {phase.name!r}: adapter_mix id {aid!r} "
                        f"is not one of the runner-loaded ids '0'..'"
                        f"{self.engine.lora_adapters - 1}' (or 'base')")
        if self.autoscale is not None:
            if self.fleet is None:
                raise ValueError(
                    "an 'autoscale' block needs a 'fleet' block")
            if not (self.autoscale.min_replicas <= self.fleet.n_replicas
                    <= self.autoscale.max_replicas):
                raise ValueError(
                    f"fleet n_replicas ({self.fleet.n_replicas}) must "
                    f"lie in the autoscale band "
                    f"[{self.autoscale.min_replicas}, "
                    f"{self.autoscale.max_replicas}]")
        if self.sentinel is not None and self.fleet is None:
            raise ValueError("a 'sentinel' block needs a 'fleet' block "
                             "(the sentinel rides the fleet tick)")
        if self.quotas is not None and self.fleet is None:
            raise ValueError("a 'quotas' block needs a 'fleet' block "
                             "(quotas gate the fleet front door)")
        if self.brownout is not None and self.fleet is None:
            raise ValueError("a 'brownout' block needs a 'fleet' block "
                             "(the controller rides the fleet tick)")
        if self.deploy is not None:
            if self.fleet is None:
                raise ValueError("a 'deploy' block needs a 'fleet' block")
            if self.deploy.kind == "adapter":
                if not self.engine.lora_adapters:
                    raise ValueError(
                        "deploy kind='adapter' needs an adapter store "
                        "(set engine.lora_adapters/lora_rank)")
                if self.deploy.adapter_id.isdigit() and int(
                        self.deploy.adapter_id) < self.engine.lora_adapters:
                    raise ValueError(
                        f"deploy adapter_id {self.deploy.adapter_id!r} "
                        f"collides with a runner-preloaded tenant id")
        if self.engine.max_len > self.model.max_position_embeddings:
            raise ValueError(
                f"engine max_len ({self.engine.max_len}) exceeds the "
                f"model's max_position_embeddings "
                f"({self.model.max_position_embeddings})")

    @property
    def total_requests(self) -> int:
        return sum(p.n_requests for p in self.phases)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Scenario":
        known = {"name", "seed", "description", "model", "engine",
                 "supervisor", "phases", "faults", "fleet", "autoscale",
                 "deploy", "sentinel", "recorder", "quotas", "brownout",
                 "slo", "tolerance", "max_wall_s"}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"unknown scenario keys {sorted(unknown)}; known: "
                f"{sorted(known)}")
        return cls(
            name=str(data["name"]),
            seed=int(data.get("seed", 0)),
            description=str(data.get("description", "")),
            model=ModelSpec.from_dict(data.get("model", {})),
            engine=EngineKnobs.from_dict(data.get("engine", {})),
            supervisor=dict(data.get("supervisor", {})),
            phases=tuple(LoadPhase.from_dict(p)
                         for p in data.get("phases", ())),
            faults=FaultSchedule.from_dict(data.get("faults", {})),
            fleet=(FleetSpec.from_dict(data["fleet"])
                   if data.get("fleet") is not None else None),
            autoscale=(AutoscaleSpec.from_dict(data["autoscale"])
                       if data.get("autoscale") is not None else None),
            deploy=(DeploySpec.from_dict(data["deploy"])
                    if data.get("deploy") is not None else None),
            sentinel=(SentinelSpec.from_dict(data["sentinel"])
                      if data.get("sentinel") is not None else None),
            recorder=(RecorderSpec.from_dict(data["recorder"])
                      if data.get("recorder") is not None else None),
            quotas=(QuotaSpec.from_dict(data["quotas"])
                    if data.get("quotas") is not None else None),
            brownout=(BrownoutSpec.from_dict(data["brownout"])
                      if data.get("brownout") is not None else None),
            slo={str(k): float(v)
                 for k, v in data.get("slo", {}).items()},
            tolerance=float(data.get("tolerance", 0.25)),
            max_wall_s=float(data.get("max_wall_s", 300.0)))

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name, "seed": self.seed,
            "model": self.model.to_dict(),
            "engine": self.engine.to_dict(),
            "phases": [p.to_dict() for p in self.phases],
            "tolerance": self.tolerance, "max_wall_s": self.max_wall_s}
        if self.description:
            out["description"] = self.description
        if self.supervisor:
            out["supervisor"] = dict(self.supervisor)
        if not self.faults.empty:
            out["faults"] = self.faults.to_dict()
        if self.fleet is not None:
            out["fleet"] = self.fleet.to_dict()
        if self.autoscale is not None:
            out["autoscale"] = self.autoscale.to_dict()
        if self.deploy is not None:
            out["deploy"] = self.deploy.to_dict()
        if self.sentinel is not None:
            out["sentinel"] = self.sentinel.to_dict()
        if self.recorder is not None:
            out["recorder"] = self.recorder.to_dict()
        if self.quotas is not None:
            out["quotas"] = self.quotas.to_dict()
        if self.brownout is not None:
            out["brownout"] = self.brownout.to_dict()
        if self.slo:
            out["slo"] = dict(self.slo)
        return out

    @classmethod
    def load(cls, path: str) -> "Scenario":
        """Parse and validate a scenario JSON file."""
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        if not isinstance(data, dict):
            raise ValueError(f"{path}: scenario must be a JSON object")
        return cls.from_dict(data)

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")
