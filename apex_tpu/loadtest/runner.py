"""Scenario execution: generator -> supervised engine -> JSONL -> SLOs.

:func:`run_scenario` is the harness's engine room. It materializes the
scenario's arrival schedule (:mod:`~apex_tpu.loadtest.generator`),
builds the model under test, wraps an
:class:`~apex_tpu.serving.InferenceEngine` in an
:class:`~apex_tpu.serving.EngineSupervisor` (with the scenario's fault
schedule driving a :class:`~apex_tpu.testing_faults.\
ServingFaultInjector`), and replays the schedule **open-loop** against
wall clock: a request is submitted the moment its arrival time passes,
whether or not the engine kept up — queue growth, shedding, and
deadline misses are the signal, not an error.

Everything observable flows through one
:class:`~apex_tpu.observability.MetricsRegistry`: the scenario record
(name, seed, declared SLOs — so the log scores itself in
``python -m apex_tpu.monitor``), every ``kind="request"`` row and
incident event the serving tier already emits, and the final counter
snapshot. The returned :class:`ScenarioRun` carries the in-memory
record stream plus the scored :class:`~apex_tpu.observability.slo.\
SLOReport`, and the same records land in ``log_path`` when given.
"""

from __future__ import annotations

import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from apex_tpu.loadtest.generator import ScheduledRequest, TrafficGenerator
from apex_tpu.loadtest.scenario import ModelSpec, Scenario
from apex_tpu.observability import (
    FleetMetrics,
    InMemorySink,
    JsonlSink,
    MetricsRegistry,
)
from apex_tpu.observability.slo import (
    SLOReport,
    SLOSpec,
    evaluate_slos,
    measure_slo_metrics,
)
from apex_tpu.serving import (
    DeadlineExpiredError,
    EngineConfig,
    EngineSupervisor,
    EngineUnavailableError,
    QueueFullError,
    RequestResult,
    SchedulerConfig,
    SupervisorConfig,
    UnknownAdapterError,
)
from apex_tpu.serving import clock
from apex_tpu.utils.logging import get_logger, log_event

__all__ = ["ScenarioRun", "build_model", "run_scenario"]

_LOG = get_logger(__name__)

#: while no arrival is due and nothing is in flight, sleep at most this
#: long per wait slice (keeps the loop responsive to the next arrival
#: without busy-spinning)
_IDLE_SLEEP_S = 0.005


def build_model(spec: ModelSpec):
    """Build the (seeded) model under test from its scenario spec —
    same construction the serving tests use, so a scenario's weights are
    reproducible across runs and machines."""
    import jax  # deferred: scenario loading/scoring stays jax-free

    from apex_tpu.models import GPTModel, TransformerConfig

    model = GPTModel(TransformerConfig(
        num_layers=spec.num_layers, hidden_size=spec.hidden_size,
        num_attention_heads=spec.num_attention_heads,
        vocab_size=spec.vocab_size,
        max_position_embeddings=spec.max_position_embeddings,
        hidden_dropout=0.0, attention_dropout=0.0))
    params = model.init(jax.random.PRNGKey(spec.param_seed))
    return model, params


@dataclass
class ScenarioRun:
    """Everything one scenario execution produced."""

    scenario: Scenario
    schedule: List[ScheduledRequest]
    results: Dict[int, RequestResult]     # request_id -> terminal result
    records: List[dict]                   # the full JSONL record stream
    counters: Dict[str, int]
    wall_s: float
    aborted: bool = False                 # hit the max_wall_s guard
    slo: Optional[SLOReport] = None
    log_path: Optional[str] = None
    ticks: int = 0
    engine_restarts: int = 0
    submitted: int = 0                    # arrivals actually offered
    metrics_by_name: Dict[str, Optional[float]] = field(
        default_factory=dict)
    #: per-tenant SLO attribution (adapter_id -> metrics dict); kept
    #: apart from metrics_by_name — never part of the baseline payload
    slo_by_adapter: Dict[str, Dict[str, Optional[float]]] = field(
        default_factory=dict)
    #: the final FleetMetrics.signals() poll (fleet scenarios only) —
    #: also stamped into the log as the kind="signals" record
    signals: Optional[dict] = None
    #: recompilations beyond the engines' expected warmup compiles, from
    #: the RetraceWatchdogs every engine wraps its step programs in —
    #: must be 0; a storm fails the run even when every SLO passes
    retraces: int = 0
    #: postmortem bundles the scenario's FlightRecorder dumped (empty
    #: when no ``recorder`` block, or when nothing incident-class fired)
    bundles: List[dict] = field(default_factory=list)
    #: where those bundles landed on disk — next to the run log (empty
    #: for in-memory runs with no ``log_path``)
    bundle_paths: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        """SLO verdict (vacuously true when no SLOs are declared) —
        AND'd with the retrace watchdogs: a recompilation storm is a
        perf bug even when the SLOs it hasn't yet sunk still pass."""
        slo_ok = self.slo.ok if self.slo is not None else True
        return slo_ok and self.retraces == 0


def _build_serving(scenario: Scenario, model, params,
                   metrics: MetricsRegistry):
    """The serving tier under test: a single
    :class:`~apex_tpu.serving.EngineSupervisor`, or — when the scenario
    declares a ``fleet`` block — a
    :class:`~apex_tpu.serving.fleet.ReplicaFleet` (the fault schedule
    then drives replica 0's injector). Both expose the same driving
    surface, so the replay loop below is tier-agnostic."""
    from apex_tpu.testing_faults import ServingFaultInjector

    knobs = scenario.engine
    adapters = None
    if knobs.lora_adapters:
        # seeded adapter store: ids "0".."n-1", each a random rank-r
        # adapter keyed by the scenario seed — reproducible per-tenant
        # weights, the same way build_model seeds the base model
        import jax

        from apex_tpu.lora import AdapterStore, random_adapter

        adapters = AdapterStore(model.config, knobs.lora_rank,
                                max_adapters=knobs.lora_adapters)
        keys = jax.random.split(jax.random.PRNGKey(scenario.seed),
                                knobs.lora_adapters)
        for ix in range(knobs.lora_adapters):
            adapters.load(str(ix), random_adapter(
                model.config, knobs.lora_rank, keys[ix]))
    engine_cfg = EngineConfig(
        max_slots=knobs.max_slots, max_len=knobs.max_len,
        kv_layout=knobs.kv_layout, page_size=knobs.page_size,
        n_pages=knobs.n_pages,
        prefix_cache=knobs.prefix_cache,
        prefix_lru_capacity=knobs.prefix_lru_capacity,
        kv_dtype=knobs.kv_dtype,
        speculation=knobs.speculation,
        prefill_token_budget=knobs.prefill_token_budget,
        scheduler=SchedulerConfig(
            max_queue=knobs.max_queue,
            max_prefills_per_tick=knobs.max_prefills_per_tick))
    sup_cfg = SupervisorConfig(**scenario.supervisor)
    faults = None
    if not scenario.faults.empty:
        faults = ServingFaultInjector(**scenario.faults.injector_kwargs())
    if scenario.fleet is not None:
        from apex_tpu.serving.fleet import (
            AutoscaleConfig,
            FleetConfig,
            ReplicaFleet,
        )

        fl = scenario.fleet
        autoscale = AutoscaleConfig(**scenario.autoscale.config_kwargs()) \
            if scenario.autoscale is not None else None
        sentinel = None
        if scenario.sentinel is not None:
            from apex_tpu.observability.sentinel import SentinelConfig

            sentinel = SentinelConfig(
                **scenario.sentinel.config_kwargs())
        quotas = None
        if scenario.quotas is not None:
            from apex_tpu.serving.fleet import QuotaConfig, TenantQuota

            quotas = QuotaConfig(
                tenants={k: TenantQuota(**v)
                         for k, v in scenario.quotas.tenants.items()},
                default=(TenantQuota(**scenario.quotas.default)
                         if scenario.quotas.default is not None else None))
        brownout = None
        if scenario.brownout is not None:
            from apex_tpu.serving.fleet import BrownoutConfig

            brownout = BrownoutConfig(
                **scenario.brownout.config_kwargs())
        return ReplicaFleet(
            model, params, engine_cfg, supervisor=sup_cfg,
            fleet=FleetConfig(n_replicas=fl.n_replicas,
                              migrate_on_drain=fl.migrate_on_drain,
                              probe_on_rebuild=fl.probe_on_rebuild),
            metrics=metrics, faults=faults, adapters=adapters,
            autoscale=autoscale, sentinel=sentinel,
            quotas=quotas, brownout=brownout)
    return EngineSupervisor(model, params, engine_cfg,
                            supervisor=sup_cfg, metrics=metrics,
                            faults=faults, adapters=adapters)


def _prepare_deploy(scenario: Scenario, model, params,
                    scratch: str) -> Dict[str, Any]:
    """Materialize the scenario's ``deploy`` artifact and return the
    kwargs for :meth:`~apex_tpu.serving.fleet.ReplicaFleet.deploy`.

    ``kind="checkpoint"`` saves the scenario's own seeded parameters at
    step 1 through a :class:`~apex_tpu.checkpoint.\
ShardedCheckpointManager` (a happy-path deploy is weight-identical, so
    it must be token-exact); ``poison=true`` then value-corrupts the
    committed step with :func:`~apex_tpu.testing_faults.\
corrupt_checkpoint_weights` — fsck stays green, the live canary score
    is the only detector. ``kind="adapter"`` builds a seeded LoRA
    canary tenant (NaN factors when poisoned)."""
    from apex_tpu.serving.fleet import CanaryConfig

    spec = scenario.deploy
    canary = CanaryConfig(**{
        k: (int(v) if k == "min_requests" else float(v))
        for k, v in spec.canary.items()})
    if spec.kind == "adapter":
        import jax

        from apex_tpu.lora import random_adapter

        factors = random_adapter(
            model.config, scenario.engine.lora_rank,
            # offset keeps the canary tenant's weights distinct from
            # the runner-preloaded ids "0".."n-1" (same seed stream)
            jax.random.PRNGKey(scenario.seed + 7919))
        if spec.poison:
            factors = jax.tree_util.tree_map(
                lambda a: a * float("nan"), factors)
        return {"adapter": (spec.adapter_id, factors), "canary": canary}
    from apex_tpu.checkpoint import ShardedCheckpointManager

    ShardedCheckpointManager(scratch, max_to_keep=1).save(1, params)
    if spec.poison:
        from apex_tpu.testing_faults import corrupt_checkpoint_weights

        corrupt_checkpoint_weights(scratch, 1)
    return {"checkpoint_dir": scratch, "step": 1, "canary": canary}


def run_scenario(scenario: Scenario, *, model=None, params=None,
                 metrics: Optional[MetricsRegistry] = None,
                 log_path: Optional[str] = None) -> ScenarioRun:
    """Execute ``scenario`` and score it against its declared SLOs.

    ``model``/``params`` default to :func:`build_model` of the
    scenario's model spec (pass them to reuse an already-built model,
    e.g. a test fixture). ``metrics`` defaults to a fresh registry;
    ``log_path`` attaches a JSONL sink so the run is
    ``python -m apex_tpu.monitor``-able afterwards. An
    :class:`~apex_tpu.observability.InMemorySink` is always attached:
    the SLO verdict is computed from the very records the sinks saw.
    """
    if (model is None) != (params is None):
        raise ValueError("pass both model and params, or neither")
    if model is None:
        model, params = build_model(scenario.model)
    registry = metrics if metrics is not None else MetricsRegistry()
    mem = InMemorySink()
    registry.add_sink(mem)
    if log_path is not None:
        registry.add_sink(JsonlSink(log_path))
    recorder = None
    if scenario.recorder is not None:
        import os

        from apex_tpu.observability.recorder import FlightRecorder

        # bundles land next to the run log, named after it; a run with
        # no log keeps them in memory (ScenarioRun.bundles)
        bundle_dir = bundle_prefix = None
        if log_path is not None:
            bundle_dir = os.path.dirname(os.path.abspath(log_path))
            bundle_prefix = os.path.splitext(
                os.path.basename(log_path))[0]
        recorder = FlightRecorder(
            bundle_dir=bundle_dir,
            bundle_prefix=bundle_prefix or scenario.name,
            **scenario.recorder.recorder_kwargs())
        # attached before the scenario record so the rings hold the
        # run's self-description too
        registry.add_sink(recorder)
    # the log's self-description: name + seed for provenance, the SLO
    # spec so the monitor (and --from-log re-scoring) can render a
    # verdict without the scenario file at hand
    registry.emit_record({
        "kind": "scenario", "name": scenario.name, "seed": scenario.seed,
        "total_requests": scenario.total_requests,
        "slo": dict(scenario.slo), "wall": clock.wall()})

    schedule = TrafficGenerator(scenario).schedule()
    sup = _build_serving(scenario, model, params, registry)
    if recorder is not None:
        recorder.attach(sup, registry)
    run = ScenarioRun(scenario=scenario, schedule=schedule, results={},
                      records=mem.records, counters={}, wall_s=0.0,
                      log_path=log_path)
    # the fleet-level fault schedule: draining restarts at fixed offsets
    drains = sorted(scenario.fleet.drain_restarts) \
        if scenario.fleet is not None else []
    d = 0
    # the continuous-deployment schedule: one rollout at a fixed offset
    # (artifact materialized up front — a poisoned checkpoint must be
    # committed and fsck-green BEFORE the first drain)
    deploy_fired = scenario.deploy is None
    deploy_kwargs: Optional[Dict[str, Any]] = None
    scratch = None
    if scenario.deploy is not None:
        scratch = tempfile.TemporaryDirectory(prefix="apex-deploy-")
        deploy_kwargs = _prepare_deploy(scenario, model, params,
                                        scratch.name)

    def _deploy_active() -> bool:
        dep = getattr(sup, "deployment", None)
        return dep is not None and not dep.done

    def _autoscale_settling() -> bool:
        # after traffic drains, keep polling until the autoscaler has
        # retired back to min_replicas — an idle fleet always meets the
        # scale-down bands, so this converges (max_wall_s still guards)
        scaler = getattr(sup, "autoscaler", None)
        return (scaler is not None
                and len(sup.replicas) > scaler.config.min_replicas)

    autoscaling = getattr(sup, "autoscaler", None) is not None
    t0 = clock.now()
    i = 0
    try:
        while (i < len(schedule) or sup.inflight_count or d < len(drains)
               or not deploy_fired or _deploy_active()
               or _autoscale_settling()):
            now = clock.now() - t0
            if now > scenario.max_wall_s:
                run.aborted = True
                _abort(sup, scenario, registry, now)
                break
            while d < len(drains) and drains[d][0] <= now:
                at_s, replica = drains[d]
                d += 1
                try:
                    sup.drain_restart(replica)
                except RuntimeError as exc:
                    # another drain still in progress (or replica not
                    # active): skip rather than stack — N-1 capacity is
                    # the invariant; the skip is stamped into the log
                    log_event(_LOG, "drain_restart_skipped",
                              replica_id=replica, at_s=at_s,
                              reason=str(exc))
                    registry.event("drain_restart_skipped",
                                   replica_id=replica, at_s=at_s,
                                   reason=str(exc))
            if not deploy_fired and scenario.deploy.at_s <= now:
                deploy_fired = True
                try:
                    sup.deploy(**deploy_kwargs)
                except Exception as exc:
                    # pre-flight rejection (fsck failure) or a topology
                    # race: the fleet already stamped deploy_rejected
                    # when it could; the skip itself is logged too
                    log_event(_LOG, "deploy_skipped",
                              at_s=scenario.deploy.at_s, reason=str(exc))
                    registry.event("deploy_skipped",
                                   at_s=scenario.deploy.at_s,
                                   reason=str(exc))
            while i < len(schedule) and schedule[i].at_s <= now:
                req = schedule[i].request
                # open-loop contract: the deadline clock starts at the
                # SCHEDULED arrival, not whenever the loop got to it
                req.arrival_ts = t0 + schedule[i].at_s
                i += 1
                run.submitted += 1
                try:
                    sup.submit(req)
                except (EngineUnavailableError, QueueFullError,
                        DeadlineExpiredError, UnknownAdapterError):
                    pass        # recorded terminally by the supervisor
            if sup.inflight_count or _deploy_active():
                sup.tick()
                run.ticks += 1
            elif i < len(schedule):
                gap = (t0 + schedule[i].at_s) - clock.now()
                if gap > 0:
                    clock.sleep(min(gap, _IDLE_SLEEP_S))
                if autoscaling:
                    # idle ticks keep the autoscaler's poll clock alive
                    # through traffic gaps (scale-down happens here)
                    sup.tick()
                    run.ticks += 1
            elif d < len(drains) or not deploy_fired \
                    or _autoscale_settling():
                # waiting on a scheduled drain/deploy, or for the
                # autoscaler to retire back to min_replicas
                clock.sleep(_IDLE_SLEEP_S)
                if autoscaling:
                    sup.tick()
                    run.ticks += 1
    finally:
        run.wall_s = clock.now() - t0
        if hasattr(sup, "replica_metrics"):
            # final autoscaler poll, stamped into the log before the
            # close-time snapshots so signals precede the counters they
            # must reconcile with
            run.signals = FleetMetrics(sup).signals()
            registry.emit_record({"kind": "signals", "wall": clock.wall(),
                                  "values": run.signals})
        sup.close()             # flushes the final counter snapshot
        if scratch is not None:
            scratch.cleanup()   # the deployed weights live in the fleet
    run.results = dict(sup.completed)
    run.counters = registry.counters()
    if recorder is not None:
        run.bundles = list(recorder.bundles)
        run.bundle_paths = list(recorder.bundle_paths)
    run.engine_restarts = sup.restarts
    # the engines' RetraceWatchdogs mirror every counted recompile into
    # the shared registry; surface the total and fail loudly — a storm
    # that the resilience layer papered over (restart + re-warm) must
    # not pass a load test silently
    run.retraces = int(run.counters.get("retraces", 0))
    if run.retraces:
        log_event(_LOG, "scenario_retraces", scenario=scenario.name,
                  retraces=run.retraces, level="error")
        registry.event("scenario_retraces", scenario=scenario.name,
                       retraces=run.retraces)
    if scenario.slo:
        run.slo = evaluate_slos(mem.records,
                                SLOSpec.from_dict(scenario.slo))
        run.metrics_by_name = dict(run.slo.metrics)
    else:
        run.metrics_by_name = measure_slo_metrics(mem.records)
    run.slo_by_adapter = measure_slo_metrics(mem.records,
                                             by_adapter=True)
    return run


def _abort(sup: EngineSupervisor, scenario: Scenario,
           registry: MetricsRegistry, now_s: float) -> None:
    """Wall-budget breach: cancel every non-terminal request (each still
    reaches exactly one terminal record — conservation holds even for an
    aborted run) and stamp the abort into the log."""
    log_event(_LOG, "loadtest_aborted", scenario=scenario.name,
              wall_s=now_s, budget_s=scenario.max_wall_s,
              inflight=sup.inflight_count)
    registry.event("loadtest_aborted", scenario=scenario.name,
                   wall_s=now_s, budget_s=scenario.max_wall_s,
                   inflight=sup.inflight_count)
    for rid in sup.inflight_ids:
        sup.cancel(rid)
    # in-flight cancellations retire at the start of the next tick
    guard = 0
    while sup.inflight_count and guard < scenario.engine.max_slots + 2:
        sup.tick()
        guard += 1
