"""apex_tpu.loadtest — scenario-driven load testing and the SLO gate.

The measurement leg that closes the serving loop: PR 4 built the
continuous-batching engine, PR 5 made it survive faults, and this
package makes both claims *numbers* — a declarative
:class:`Scenario` (traffic phases, mixes, deadlines, fault schedule,
declared SLOs) is materialized by a seeded open-loop
:class:`TrafficGenerator`, replayed by :func:`run_scenario` against the
engine-under-:class:`~apex_tpu.serving.EngineSupervisor`, scored by
:mod:`apex_tpu.observability.slo`, and gated against a committed
baseline (:mod:`~apex_tpu.loadtest.gate`).

CLI: ``python -m apex_tpu.loadtest scenario.json`` runs and prints the
verdict; ``--check`` turns it into a regression gate (nonzero exit on
SLO violation or baseline regression); ``--from-log`` re-scores an
existing run log without running anything. See docs/loadtest.md.
"""

from apex_tpu.loadtest.gate import (
    DEFAULT_BASELINE,
    Regression,
    compare_to_baseline,
    load_baseline,
    update_baseline,
)
from apex_tpu.loadtest.generator import ScheduledRequest, TrafficGenerator
from apex_tpu.loadtest.runner import ScenarioRun, build_model, run_scenario
from apex_tpu.loadtest.scenario import (
    EngineKnobs,
    FaultSchedule,
    FleetSpec,
    LoadPhase,
    ModelSpec,
    RecorderSpec,
    Scenario,
    SentinelSpec,
)

__all__ = [
    "Scenario",
    "LoadPhase",
    "ModelSpec",
    "EngineKnobs",
    "FaultSchedule",
    "FleetSpec",
    "SentinelSpec",
    "RecorderSpec",
    "TrafficGenerator",
    "ScheduledRequest",
    "ScenarioRun",
    "build_model",
    "run_scenario",
    "DEFAULT_BASELINE",
    "Regression",
    "load_baseline",
    "update_baseline",
    "compare_to_baseline",
]
