"""``python -m apex_tpu.monitor <run.jsonl>`` — the run-report CLI.

Reads the JSONL metric log a
:class:`apex_tpu.observability.JsonlSink`-equipped run wrote and prints
the report: telemetry counter totals (reconciling exactly with the
run's ``TrainingResult.telemetry``), step-time p50/p95, throughput/MFU
trajectory, the serving-request section (per-request latency quantiles
and finish-reason counts from an ``InferenceEngine``'s
``kind="request"`` rows, reconciling with its ``requests_*`` counters),
the serving-incidents section (engine restarts, recovered requests,
quarantined slots, breaker transitions, shed requests — reconciling
key-for-key with the supervisor's counters), and the incident timeline
(skips, rollbacks, retraces, preemptions). ``--json`` emits the raw
report dict instead.

Thin shim over :mod:`apex_tpu.observability.report` so the command
reads ``apex_tpu.monitor`` while the logic lives with the subsystem.
"""

from apex_tpu.observability.report import (  # noqa: F401
    build_report,
    read_records,
    render_report,
    main,
)

__all__ = ["build_report", "read_records", "render_report", "main"]

if __name__ == "__main__":
    import sys

    sys.exit(main())
