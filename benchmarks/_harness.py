"""Shared benchmark harness: time a jitted train step, print ONE JSON line
(same contract as the repo-root ``bench.py``). All configs from BASELINE.md
live here as scripts; absolute numbers are self-measured (the reference
publishes none — BASELINE.md)."""

from __future__ import annotations

import json
import time
from typing import Callable, Optional

# FLOP accounting lives in the library (apex_tpu.utils.flops) — the same
# peak table and estimators drive the observability layer's MFU metric,
# so benchmark MFU and in-run MFU can never drift apart. Re-exported here
# because every benchmark script imports them from the harness.
from apex_tpu.utils.flops import (  # noqa: F401
    peak_flops_per_chip,
    resnet50_train_flops,
    transformer_train_flops,
)


def run(metric: str, unit: str, step_fn: Callable, *state,
        work_per_step: float, steps: int = 10, windows: int = 3,
        baseline_fn=None,
        model_flops_per_step: Optional[float] = None,
        consume_state: bool = False):
    """``step_fn(*state) -> (*new_state, loss)``; prints the JSON line.

    ``baseline_fn``: optional same-signature unoptimized step; when given,
    ``vs_baseline`` reports measured speedup, else 1.0.
    ``model_flops_per_step``: when given, the line carries ``mfu`` (model-
    FLOPs utilization vs the chip's bf16 peak).
    ``consume_state``: skip the defensive state copy — required when state
    is a large fraction of HBM (the copy doubles residency and OOMs);
    incompatible with ``baseline_fn``.
    """
    import jax
    import numpy as _np

    if consume_state and baseline_fn is not None:
        raise ValueError("consume_state does not compose with baseline_fn "
                         "(the baseline needs the same initial state)")

    def _fetch(x):
        # hard device->host fetch: through tunneled PJRT backends (axon)
        # block_until_ready can return before execution finishes, inflating
        # throughput ~10x; np.asarray cannot lie
        return _np.asarray(x)

    def _time(fn, state):
        # fresh copies per timing run: a donating step consumes its input
        # buffers, and the baseline run must reuse the same initial state
        if not consume_state:
            state = [jax.tree.map(
                lambda a: a.copy() if hasattr(a, "copy") else a,
                s) for s in state]
        else:
            state = list(state)
        out = fn(*state)
        _fetch(out[-1])
        state = list(out[:-1])
        # best-of-N windows: the tunneled backend has multi-second transient
        # stalls that a single window folds into the mean
        best = float("inf")
        for _w in range(windows):
            t0 = time.perf_counter()
            for _ in range(steps):
                out = fn(*state)
                state = list(out[:-1])
            _fetch(out[-1])
            best = min(best, (time.perf_counter() - t0) / steps)
        return best

    dt = _time(step_fn, state)
    value = work_per_step / dt
    vs = 1.0
    if baseline_fn is not None:
        vs = _time(baseline_fn, state) * value / work_per_step
    line = {"metric": metric, "value": round(value, 1),
            "unit": unit, "vs_baseline": round(vs, 3)}
    if model_flops_per_step is not None:
        peak = peak_flops_per_chip()
        if peak is not None:
            line["mfu"] = round(model_flops_per_step / dt / peak, 4)
            line["model_tflops"] = round(model_flops_per_step / dt / 1e12, 1)
    print(json.dumps(line))
    return line
