"""Shared benchmark harness: time a jitted train step, print ONE JSON line
(same contract as the repo-root ``bench.py``). All configs from BASELINE.md
live here as scripts; absolute numbers are self-measured (the reference
publishes none — BASELINE.md)."""

from __future__ import annotations

import json
import time
from typing import Callable


def run(metric: str, unit: str, step_fn: Callable, *state,
        work_per_step: float, steps: int = 10, baseline_fn=None):
    """``step_fn(*state) -> (*new_state, loss)``; prints the JSON line.

    ``baseline_fn``: optional same-signature unoptimized step; when given,
    ``vs_baseline`` reports measured speedup, else 1.0.
    """
    import jax
    import numpy as _np

    def _fetch(x):
        # hard device->host fetch: through tunneled PJRT backends (axon)
        # block_until_ready can return before execution finishes, inflating
        # throughput ~10x; np.asarray cannot lie
        return _np.asarray(x)

    def _time(fn, state):
        # fresh copies per timing run: a donating step consumes its input
        # buffers, and the baseline run must reuse the same initial state
        state = [jax.tree.map(lambda a: a.copy() if hasattr(a, "copy") else a,
                              s) for s in state]
        out = fn(*state)
        _fetch(out[-1])
        state = list(out[:-1])
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(*state)
            state = list(out[:-1])
        _fetch(out[-1])
        return (time.perf_counter() - t0) / steps

    dt = _time(step_fn, state)
    value = work_per_step / dt
    vs = 1.0
    if baseline_fn is not None:
        vs = _time(baseline_fn, state) * value / work_per_step
    print(json.dumps({"metric": metric, "value": round(value, 1),
                      "unit": unit, "vs_baseline": round(vs, 3)}))
    return value
