"""BASELINE large-GEMM config: GPT-2 355M (Megatron 'medium') training MFU.

The largest standard GPT-2 config that fits one 16 GB v5e chip with Adam
state. hidden 1024 puts the MXU on [8192, 1024] x [1024, 4096]-class GEMMs
— the evidence that the framework's transformer MFU scales with model
size rather than stopping at the 124M small-GEMM regime (VERDICT r2 item
2). Tuned settings measured on-chip (PERF.md): no activation recompute
(fits at bs8), fully unrolled layer scan (kills while-loop + stacked-save
overhead), donated buffers.

Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/gpt_large.py``
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from functools import partial

import jax
import jax.numpy as jnp

from benchmarks._harness import run, transformer_train_flops
from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.optimizers import FusedAdam

LAYERS, HIDDEN, HEADS = 24, 1024, 16


def main(batch=8, seq=1024):
    cfg = TransformerConfig(
        num_layers=LAYERS, hidden_size=HIDDEN, num_attention_heads=HEADS,
        vocab_size=50304, max_position_embeddings=seq,
        hidden_dropout=0.0, attention_dropout=0.0,
        recompute=False, scan_unroll=LAYERS,
        compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                50304)
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0,
                                50304)
    n_params = sum(x.size for x in jax.tree.leaves(params))

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: model.apply(p, tokens, labels))(params)
        params, opt_state = opt.step(grads, params, opt_state)
        return params, opt_state, loss

    return run("gpt2_355m_train_tokens_per_sec_per_chip", "tokens/sec",
               step, params, opt_state,
               work_per_step=batch * seq, consume_state=True,
               model_flops_per_step=transformer_train_flops(
                   n_params, batch * seq, LAYERS, HIDDEN, seq, causal=True))


if __name__ == "__main__":
    main()
