"""BASELINE config 4: GPT Megatron-style TP train step.

With one real chip this measures the TP=1 path; on a mesh (or the virtual
CPU mesh: ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
env ``JAX_PLATFORMS=cpu``) it shards TP over all devices and reports
tokens/sec/chip.
Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/gpt_tp.py``
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from benchmarks._harness import run, transformer_train_flops
from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.optimizers import FusedAdam
from apex_tpu.training import make_train_step
from apex_tpu.transformer import parallel_state
from jax.sharding import PartitionSpec as P


def main(batch=8, seq=1024):
    ndev = len(jax.devices())
    tp = ndev  # all devices on the tensor axis
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=tp)
    cfg = TransformerConfig(
        num_layers=12, hidden_size=768, num_attention_heads=12,
        vocab_size=50304, max_position_embeddings=1024,
        hidden_dropout=0.0, attention_dropout=0.0,
        sequence_parallel=(tp > 1),
        # r3 tuning: recompute-free + unrolled scan (memory fits at bs8)
        recompute=False, scan_unroll=12,
        compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                50304)
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0,
                                50304)

    def loss_fn(p, b, rng):
        return model.apply(p, b["tokens"], b["labels"], rng=rng)

    step_fn = make_train_step(
        loss_fn, opt, mesh, model.spec(),
        {"tokens": P("data"), "labels": P("data")},
        opt_state_spec=opt.state_spec(params, model.spec()))
    batch_dict = {"tokens": tokens, "labels": labels}

    def step(params, opt_state):
        p, o, loss = step_fn(params, opt_state, batch_dict, None)
        return p, o, loss

    n_params = sum(x.size for x in jax.tree.leaves(params))
    out = run(f"gpt2_124m_tp{tp}_train_tokens_per_sec_per_chip", "tokens/sec",
              step, params, opt_state, work_per_step=batch * seq / ndev,
              model_flops_per_step=transformer_train_flops(
                  n_params, batch * seq, 12, 768, seq, causal=True) / ndev)
    parallel_state.destroy_model_parallel()
    return out


if __name__ == "__main__":
    main()
