"""BASELINE config 2: DCGAN bf16 mixed-precision G+D train step; imgs/sec.

The capability under test is the reference's second example — multiple
models/optimizers/losses with per-loss dynamic scaling
(``/root/reference/examples/dcgan/main_amp.py``); the full flow lives in
``examples/dcgan_amp.py``. This benchmark times the combined D+G step.
Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/dcgan_bf16.py``
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from benchmarks._harness import run
from apex_tpu.models import DCGANConfig, Discriminator, Generator
from apex_tpu.optimizers import FusedAdam


def _bce(logit, target):
    return jnp.mean(jnp.maximum(logit, 0) - logit * target
                    + jnp.log1p(jnp.exp(-jnp.abs(logit))))


def main(batch=256, nz=100):
    cfg = DCGANConfig(latent_dim=nz, compute_dtype=jnp.bfloat16)
    gen, disc = Generator(cfg), Discriminator(cfg)
    gp, gs = gen.init(jax.random.PRNGKey(0))
    dp_, ds = disc.init(jax.random.PRNGKey(1))
    g_opt = FusedAdam(lr=2e-4, betas=(0.5, 0.999), master_weights=True)
    d_opt = FusedAdam(lr=2e-4, betas=(0.5, 0.999), master_weights=True)
    g_os, d_os = g_opt.init(gp), d_opt.init(dp_)
    real = jnp.tanh(jax.random.normal(jax.random.PRNGKey(2),
                                      (batch, 64, 64, 3)))
    z = jax.random.normal(jax.random.PRNGKey(3), (batch, nz))

    @jax.jit
    def step(gp, gs, dp_, ds, g_os, d_os):
        def d_loss(p):
            lr_, _ = disc.apply(p, ds, real, train=True)
            fake, _ = gen.apply(gp, gs, z, train=True)
            lf, new_ds = disc.apply(p, ds, fake, train=True)
            return (_bce(lr_, jnp.ones(batch))
                    + _bce(lf, jnp.zeros(batch))), new_ds

        (errD, new_ds), d_g = jax.value_and_grad(d_loss, has_aux=True)(dp_)
        new_dp, new_d_os = d_opt.step(d_g, dp_, d_os)

        def g_loss(p):
            fake, new_gs = gen.apply(p, gs, z, train=True)
            logit, _ = disc.apply(new_dp, ds, fake, train=True)
            return _bce(logit, jnp.ones(batch)), new_gs

        (errG, new_gs), g_g = jax.value_and_grad(g_loss, has_aux=True)(gp)
        new_gp, new_g_os = g_opt.step(g_g, gp, g_os)
        return new_gp, new_gs, new_dp, new_ds, new_g_os, new_d_os, errD + errG

    # model flops from the compiled program (G/D conv stacks have no simple
    # closed form); cost_analysis counts executed flops ~= model flops here
    # (no activation recompute in this step)
    flops = None
    try:
        ca = step.lower(gp, gs, dp_, ds, g_os, d_os).compile().cost_analysis()
        if ca and "flops" in ca:
            flops = float(ca["flops"])
    except Exception:
        pass
    # the G+D step is short (~17 ms); longer windows + more of them pin
    # the tunnel's run-to-run spread (was 12-21% MFU in round 2)
    return run("dcgan_bf16_imgs_per_sec_per_chip", "imgs/sec",
               step, gp, gs, dp_, ds, g_os, d_os, work_per_step=batch,
               steps=40, windows=5, model_flops_per_step=flops)


if __name__ == "__main__":
    main()
