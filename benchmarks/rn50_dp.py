"""BASELINE config 1: ResNet-50 "ImageNet", amp O2-equivalent + DP.

Measures imgs/sec/chip on whatever devices exist (the north-star config;
reference ``examples/imagenet/main_amp.py`` Speed printout).
Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/rn50_dp.py``
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from benchmarks._harness import resnet50_train_flops, run
from apex_tpu.models import ResNet, ResNetConfig
from apex_tpu.optimizers import FusedSGD


def main(batch=256, image=224):
    devices = jax.devices()
    ndev = len(devices)
    model = ResNet(ResNetConfig(
        depth=50, num_classes=1000, compute_dtype=jnp.bfloat16,
        axis_name="data" if ndev > 1 else None))
    params, bn_state = model.init(jax.random.PRNGKey(0))
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4,
                   master_weights=True)
    opt_state = opt.init(params)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, image, image, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)

    def per_rank(params, bn_state, opt_state, x, y):
        def loss_fn(p):
            logits, new_bn = model.apply(p, bn_state, x, train=True)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(logp[jnp.arange(x.shape[0]), y]), new_bn
        (loss, new_bn), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        if ndev > 1:
            grads = jax.lax.pmean(grads, "data")
            loss = jax.lax.pmean(loss, "data")
        params, opt_state = opt.step(grads, params, opt_state)
        return params, new_bn, opt_state, loss

    if ndev > 1:
        mesh = Mesh(np.array(devices), ("data",))
        fn = jax.jit(jax.shard_map(
            per_rank, mesh=mesh,
            in_specs=(P(), P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P(), P()), check_vma=False))
    else:
        fn = jax.jit(per_rank)

    def step(params, bn_state, opt_state):
        p, b, o, loss = fn(params, bn_state, opt_state, x, y)
        return p, b, o, loss

    return run(f"rn50_{image}px_amp_o2_dp_imgs_per_sec_per_chip", "imgs/sec",
               step, params, bn_state, opt_state,
               work_per_step=batch / ndev,
               model_flops_per_step=resnet50_train_flops(batch / ndev, image))


if __name__ == "__main__":
    main()
