"""Per-op device profiler — the measurement tool behind PERF.md's profiles.

Runs a jitted step a few times under ``jax.profiler.trace`` and aggregates
per-op device time from the captured xplane proto (the same data the
TensorBoard profiler renders). This is the TPU counterpart of profiling a
CUDA step with Nsight and reading the kernel summary: op names carry the
HLO metadata (which includes the ``jax.named_scope``/source annotations),
so Pallas kernels, fusions, copies and convert/transpose traffic are
separable.

Usage (as a library — the round-5 profiles in PERF.md were taken this way):

    from benchmarks.profile_step import profile_op_table
    rows = profile_op_table(lambda: step(params, opt_state))
    # rows: [(total_us_across_steps, count, op_name), ...] sorted desc

or standalone against the 355M trainer:

    PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/profile_step.py
"""

from __future__ import annotations

import glob
import os
import re
import tempfile
from collections import defaultdict

import jax

__all__ = ["profile_op_table", "print_op_table", "group_rows"]


def _load_xplanes(log_dir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"),
                      recursive=True)
    spaces = []
    for p in paths:
        xs = xplane_pb2.XSpace()
        with open(p, "rb") as f:
            xs.ParseFromString(f.read())
        spaces.append(xs)
    return spaces


def profile_op_table(run_once, *, iters=3, device_substr="TPU",
                     line_name="XLA Ops"):
    """Run ``run_once()`` ``iters`` times under the profiler; return
    ``[(total_us, count, name), ...]`` (device-time sum over all iters,
    descending). ``run_once`` must block (e.g. end with
    ``jax.block_until_ready``)."""
    run_once()                                   # compile outside the trace
    with tempfile.TemporaryDirectory() as d:
        with jax.profiler.trace(d):
            for _ in range(iters):
                run_once()
        acc = defaultdict(lambda: [0.0, 0])
        for xs in _load_xplanes(d):
            for plane in xs.planes:
                if device_substr not in plane.name:
                    continue
                meta = plane.event_metadata
                for line in plane.lines:
                    if line_name and line.name != line_name:
                        continue
                    for ev in line.events:
                        name = meta[ev.metadata_id].name
                        acc[name][0] += ev.duration_ps / 1e6
                        acc[name][1] += 1
    return sorted(((v[0], v[1], k) for k, v in acc.items()), reverse=True)


# Buckets keyed on the HLO INSTRUCTION NAME (the `%name =` token — XLA
# names instructions after their opcode / fused pattern) plus the
# custom_call_target marker for Pallas: the xplane op text is the FULL
# instruction, where `%` prefixes instruction and operand NAMES, not
# opcodes, so matching the whole text would hit operand names like
# `%copy` inside unrelated instructions. The Python kernel function name
# never appears — per-kernel attribution needs output-shape signatures,
# as the PERF.md round-5 analyses do.
_GROUPS = [
    ("gemm+epilogue", re.compile(r"^(convolution|dot)|"
                                 r"(convolution|dot)[a-z_]*_fusion",
                                 re.I)),
    ("fusion", re.compile(r"fusion", re.I)),
    ("copy/transpose/reshape", re.compile(
        r"^(copy|transpose|bitcast|reshape|slice)", re.I)),
    ("other", re.compile(r".")),
]


def group_rows(rows):
    """Bucket an op table into coarse classes -> {class: total_us}."""
    out = defaultdict(float)
    for us, _, name in rows:
        iname = name.split(" = ")[0].lstrip("%")
        if ('custom_call_target="tpu_custom_call"' in name
                or " custom-call(" in name
                or iname.startswith("closed_call")):
            out["pallas-kernel"] += us
            continue
        for gname, pat in _GROUPS:
            if pat.search(iname):
                out[gname] += us
                break
    return dict(sorted(out.items(), key=lambda kv: -kv[1]))


def print_op_table(rows, *, iters=3, top=40):
    total = sum(r[0] for r in rows)
    print(f"total device time: {total / iters / 1000:.2f} ms/step "
          f"({iters} steps)")
    for us, n, name in rows[:top]:
        print(f"{us / iters / 1000:9.3f} ms  x{n:<4d} {name[:110]}")
    print("-- grouped --")
    for g, us in group_rows(rows).items():
        print(f"{us / iters / 1000:9.3f} ms  {g}")


if __name__ == "__main__":
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from functools import partial

    import jax.numpy as jnp

    from apex_tpu.models import GPTModel, TransformerConfig
    from apex_tpu.optimizers import FusedAdam

    cfg = TransformerConfig(
        num_layers=24, hidden_size=1024, num_attention_heads=16,
        vocab_size=50304, max_position_embeddings=1024,
        hidden_dropout=0.0, attention_dropout=0.0,
        recompute=False, scan_unroll=24, compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 1024), 0, 50304)
    labels = jax.random.randint(jax.random.PRNGKey(2), (8, 1024), 0, 50304)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(p, s):
        loss, grads = jax.value_and_grad(
            lambda q: model.apply(q, tokens, labels))(p)
        p, s = opt.step(grads, p, s)
        return p, s, loss

    state = [params, opt_state]

    def once():
        p, s, loss = step(state[0], state[1])
        state[0], state[1] = p, s
        jax.block_until_ready(loss)

    rows = profile_op_table(once)
    print_op_table(rows)
