"""Generation benchmark: prefill and jitted KV-cache decode throughput.

The generation capability exceeds the reference (which ships no inference
utilities); VERDICT r2 item 8 asked for perf evidence to match. Measures,
on GPT-2 124M:

  * prefill tokens/sec — one cached forward over a 1024-token prompt
    (batch 8), the compute-bound phase;
  * decode tokens/sec at batch 1 and 8 — `generate()`'s one-token-per-step
    `lax.scan`, the latency/bandwidth-bound phase (each step reads all
    params + the KV cache).

Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/generation_bench.py``
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import generate, init_kv_caches
from apex_tpu.models.generation import _cached_forward  # prefill phase


def _model():
    cfg = TransformerConfig(
        num_layers=12, hidden_size=768, num_attention_heads=12,
        vocab_size=50304, max_position_embeddings=2048,
        hidden_dropout=0.0, attention_dropout=0.0,
        compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _time(fn, *args, steps=5):
    out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[0]
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0]).ravel()[0]
        best = min(best, (time.perf_counter() - t0) / steps)
    return best


def bench_prefill(model, params, batch=8, prompt_len=1024):
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len),
                                0, 50304)
    caches = init_kv_caches(model, batch, prompt_len + 1)

    @jax.jit
    def prefill(params, caches, prompt):
        logits, caches = _cached_forward(model, params, caches, prompt, 0)
        return logits[-1], caches

    dt = _time(prefill, params, caches, prompt, steps=10)
    tps = batch * prompt_len / dt
    print(json.dumps({
        "metric": f"gpt2_124m_prefill_bs{batch}_tokens_per_sec_per_chip",
        "value": round(tps, 1), "unit": "tokens/sec", "vs_baseline": 1.0,
        "config": {"prompt_len": prompt_len}}))
    return tps


def bench_decode(model, params, batch, new_tokens=128, prompt_len=128):
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len),
                                0, 50304)

    gen = jax.jit(lambda p, pr: generate(model, p, pr, new_tokens))
    dt = _time(gen, params, prompt, steps=3)
    # generate() = one prefill + new_tokens decode steps; report generated
    # tokens/sec (the user-visible rate), prefill share disclosed in config
    tps = batch * new_tokens / dt
    print(json.dumps({
        "metric": f"gpt2_124m_decode_bs{batch}_tokens_per_sec_per_chip",
        "value": round(tps, 1), "unit": "tokens/sec", "vs_baseline": 1.0,
        "config": {"new_tokens": new_tokens, "prompt_len": prompt_len,
                   "includes_prefill": True}}))
    return tps


def main():
    model, params = _model()
    bench_prefill(model, params)
    bench_decode(model, params, batch=1)
    bench_decode(model, params, batch=8)


if __name__ == "__main__":
    main()
