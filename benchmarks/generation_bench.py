"""Generation benchmark: prefill and decode-ONLY throughput + roofline.

The generation capability exceeds the reference (which ships no inference
utilities); the perf evidence matches (VERDICT r3 item 3). Measures, on
GPT-2 124M:

  * prefill tokens/sec — an in-jit chain of data-dependent cached
    forwards over 1024-token prompts (batch 8; chaining amortizes the
    5-20 ms per-call tunnel dispatch that made per-call timing wander
    25%), the compute-bound phase;
  * decode-only tokens/sec at batch 1 / 8 / 32 — ONE jitted scan of
    pure decode steps over a cache prefilled outside the timed region
    (round 4 differenced two separately-dispatched generate() calls;
    dispatch noise ADDS in a difference and inflated bs1 past the
    physical bound — see bench_decode); each row carries its fraction
    of the weight+KV read-bandwidth bound (decode reads every
    parameter once per token), with the bound dtype- and page-aware —
    paged rows count only the pages the layout streams, and the int8
    rows (``kv_dtype="int8"``) count the quantized pool + scale
    sidecar, not the bf16 stream they replaced;
  * serving mode — mixed prompt lengths through the continuous-batching
    InferenceEngine vs. lockstep generate() at matched load: tokens/sec
    plus p50/p95 per-request latency (lockstep has one latency — every
    request waits for the longest; continuous batching retires short
    requests as they finish).

Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/generation_bench.py``
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# peak HBM bandwidth per chip (public Cloud TPU specs), for the decode
# read-bound roofline; recorded in each decode row's JSON config
_HBM_BW_BY_KIND = {"TPU v4": 1228e9, "TPU v5 lite": 819e9,
                   "TPU v5e": 819e9, "TPU v5p": 2765e9, "TPU v6e": 1640e9}


def _hbm_bw():
    kind = jax.devices()[0].device_kind
    for name, bw in _HBM_BW_BY_KIND.items():
        if kind.startswith(name):
            return bw
    return None

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.generation import (
    cast_decode_params, decode_step, flatten_decode_caches, generate,
    init_kv_caches, preslice_layer_params)
from apex_tpu.models.generation import _cached_forward  # prefill phase


def _model():
    cfg = TransformerConfig(
        num_layers=12, hidden_size=768, num_attention_heads=12,
        vocab_size=50304, max_position_embeddings=2048,
        hidden_dropout=0.0, attention_dropout=0.0,
        compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _time(fn, *args, steps=5):
    out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[0]
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0]).ravel()[0]
        best = min(best, (time.perf_counter() - t0) / steps)
    return best


def bench_prefill(model, params, batch=8, prompt_len=1024, chain=10):
    """``chain`` prefills inside ONE jit, each data-dependent on the last
    (its argmax token overwrites the next prompt's first slot): a single
    ~40 ms prefill pays 5-20 ms of tunnel dispatch per call, which is why
    per-call timing wandered 150-229k tok/s across round-4 runs; the
    in-jit chain amortizes dispatch to noise."""
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len),
                                0, 50304)
    # per-layer LIST caches + pre-sliced params: generate()'s prefill form
    # (the stacked scan re-slices/restacks the whole cache every layer)
    caches = init_kv_caches(model, batch, prompt_len + 1, stacked=False)
    params = preslice_layer_params(params, model.config.num_layers)

    @jax.jit
    def prefill_chain(params, caches, prompt):
        # caches ride the carry so the KV writes stay live (discarding
        # them would let XLA DCE ~300 MB of per-prefill cache stores)
        def body(carry, _):
            pr, caches = carry
            logits, caches = _cached_forward(model, params, caches, pr, 0,
                                             last_only=True)
            tok = jnp.argmax(logits[-1], axis=-1).astype(pr.dtype)
            return (pr.at[:, 0].set(tok % 50304), caches), None
        (pr, caches), _ = jax.lax.scan(body, (prompt, caches), None,
                                       length=chain)
        return pr

    dt = _time(prefill_chain, params, caches, prompt, steps=1) / chain
    tps = batch * prompt_len / dt
    print(json.dumps({
        "metric": f"gpt2_124m_prefill_bs{batch}_tokens_per_sec_per_chip",
        "value": round(tps, 1), "unit": "tokens/sec", "vs_baseline": 1.0,
        "config": {"prompt_len": prompt_len,
                   "method": f"in-jit chain of {chain} data-dependent "
                             f"prefills (dispatch amortized)"}}))
    return tps


def _decode_read_bytes(model, batch, cache_tokens):
    """HBM bytes one decode step MUST read: every parameter (the weights
    are touched once per token) plus the populated K/V cache slots. This
    is the decode roofline numerator — at bs1 decode is weight-read bound
    (124M bf16 params = 0.25 GB/step => ~3.3k steps/s ceiling at 819
    GB/s); the KV term grows with batch and context."""
    c = model.config
    itemsize = jnp.dtype(c.compute_dtype).itemsize
    n_params = sum(
        np.prod(s.shape) for s in jax.tree.leaves(
            jax.eval_shape(model.init, jax.random.PRNGKey(0))))
    param_bytes = n_params * itemsize
    kv_bytes = (c.num_layers * 2 * batch * c.kv_heads * cache_tokens
                * c.head_dim * itemsize)
    return param_bytes + kv_bytes


def bench_decode(model, params, batch, prompt_len=128, chain=None):
    """Decode-only tokens/sec from ONE jitted ``lax.scan`` of pure decode
    steps over an already-prefilled cache.

    Round 4 differenced two separately-dispatched ``generate()`` calls; the
    5-20 ms per-dispatch tunnel noise does not cancel in a difference — it
    adds — and the driver's bs1 capture came out at 104.6% of the physical
    read bound (VERDICT r4). Here the prefill runs once OUTSIDE the timed
    region, and the timed program is a single dispatch scanning ``chain``
    data-dependent decode steps (each argmax token feeds the next step).
    Dispatch overhead is amortized over the whole chain and biases the
    throughput LOW, so the reported pct_of_read_bw_bound cannot exceed 1 by
    construction. Write positions cycle inside the cache's decode window so
    the chain length (dispatch amortization) is independent of the cache
    size (kept at round 4's S=288 for row comparability); every step does
    identical work — one dynamic_update_slice + attention over the full
    static cache per layer."""
    c = model.config
    S = prompt_len + 160                     # same allocation as round 4
    chain = chain or {1: 2048, 8: 1024}.get(batch, 512)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len),
                                0, 50304)
    # serving precision: generate()'s own one-time pre-cast (keeps MoE
    # routers fp32), materialized outside the timed jit
    if c.compute_dtype != jnp.float32:
        params = cast_decode_params(params, c.compute_dtype)

    @jax.jit
    def prefill(params, caches, prompt):
        logits, caches = _cached_forward(model, params, caches, prompt, 0,
                                         last_only=True)
        first = jnp.argmax(logits[-1], axis=-1).astype(prompt.dtype)
        return caches, first

    caches, first = prefill(params, init_kv_caches(model, batch, S), prompt)
    # generate()'s decode form: FLAT per-layer caches + pre-sliced layer
    # params (the SAME helpers generate() uses, materialized outside the
    # timed jit)
    caches = flatten_decode_caches(caches, c.num_layers)
    params = preslice_layer_params(params, c.num_layers)
    # write indices cycle through [prompt_len, S): after one pass the cache
    # is fully occupied, so steady-state steps read the full S-slot buffer
    idx = prompt_len + (jnp.arange(chain) % (S - prompt_len))

    @jax.jit
    def decode_chain(params, caches, tok):
        def body(carry, i):
            caches, tok = carry
            logits, caches = decode_step(model, params, caches, tok, i)
            return (caches, jnp.argmax(logits, -1).astype(tok.dtype)), None
        (caches, tok), _ = jax.lax.scan(body, (caches, tok), idx)
        return tok, caches                   # tok first: cheap sync fetch

    dt = _time(decode_chain, params, caches, first, steps=2) / chain
    tps = batch / dt
    bw = _hbm_bw()
    step_bytes = _decode_read_bytes(model, batch, S)
    row = {
        "metric": f"gpt2_124m_decode_bs{batch}_tokens_per_sec_per_chip",
        "value": round(tps, 1), "unit": "tokens/sec", "vs_baseline": 1.0,
        "config": {"prompt_len": prompt_len, "decode_only": True,
                   "cache_len": S,
                   "kv_dtype": str(jnp.dtype(c.compute_dtype)),
                   "read_bytes_per_step": int(step_bytes),
                   "method": f"in-jit scan of {chain} decode steps over a "
                             f"prefilled cache (single dispatch; overhead "
                             f"biases tok/s low => pct_of_bound <= 1 by "
                             f"construction)"}}
    if bw is not None:
        # the attention physically reads all S cache slots every step (full
        # static buffer + mask), so the bound counts the full cache
        bound_steps = bw / step_bytes
        row["pct_of_read_bw_bound"] = round(tps / (batch * bound_steps), 3)
        row["config"]["hbm_bw_gbps"] = round(bw / 1e9)
    print(json.dumps(row))
    return tps


def _paged_read_bytes(model, batch, tokens_streamed, *, page_size,
                      kv_dtype=None):
    """HBM bytes one PAGED decode step must read: every parameter plus
    only the pages actually streamed (``pages_for(pos+1)`` per slot —
    the kernel skips pages past each slot's valid length, where the flat
    layout always reads the full static ``S`` window). This is the paged
    roofline numerator: the bound counts the bytes the layout makes
    mandatory, so flat and paged rows are held to their OWN floor.

    Dtype-aware: the KV term uses the POOL's itemsize, not the compute
    dtype's — ``kv_dtype="int8"`` halves the mandatory stream vs bf16 —
    plus, when quantized, the per-(page, kv-head) float32 scale sidecar
    the kernel reads to dequantize (4 bytes per kv head per streamed
    page, for each of k and v, per layer)."""
    c = model.config
    itemsize = jnp.dtype(c.compute_dtype).itemsize
    n_params = sum(
        np.prod(s.shape) for s in jax.tree.leaves(
            jax.eval_shape(model.init, jax.random.PRNGKey(0))))
    kv_itemsize = jnp.dtype(kv_dtype or c.compute_dtype).itemsize
    kv_bytes = (c.num_layers * 2 * batch * c.kv_heads * tokens_streamed
                * c.head_dim * kv_itemsize)
    if kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8:
        pages_streamed = tokens_streamed / page_size
        kv_bytes += c.num_layers * 2 * batch * c.kv_heads * pages_streamed * 4
    return n_params * itemsize + kv_bytes


def bench_decode_paged(model, params, batch, prompt_len=128, page_size=32,
                       mode="fused", chain=None, unroll=8, flat_tps=None,
                       kv_dtype=None):
    """Decode-only tokens/sec over the PAGED KV pool, fused vs unfused.

    Same instrument philosophy as :func:`bench_decode` — prefill outside
    the timed region, data-dependent steps, dispatch bias LOW — but the
    chain is a host loop of jitted programs each UNROLLING ``unroll``
    decode steps (never ``lax.scan``: the fused path's ``pallas_call``
    inside a scan body is exactly the APX007 interpret-mode partitioner
    trap, and on hardware the unrolled form is what the serving engine
    dispatches anyway — one program per tick). ``mode="fused"`` is the
    shipped dispatch (the Pallas append+attend kernel on TPU);
    ``mode="unfused"`` forces ``APEX_TPU_FORCE_PALLAS=off`` so the same
    paged layout runs the XLA reference — separate append scatter plus a
    gather that materializes the ``[b, S, f]`` temporary. The delta
    between the two rows is the fusion win at identical bytes-mandatory.

    ``pct_of_read_bw_bound`` divides by the paged layout's ACTUAL
    mandatory bytes (:func:`_paged_read_bytes`): pages holding
    ``pos + 1`` tokens per slot, averaged over the cycled write
    positions — not the flat path's full static window.

    ``kv_dtype="int8"`` runs the quantized pool (``(pages, scales)``
    per side, the engine's ``kv_dtype`` knob): the dense prefill is
    whole-page-quantized outside the timed region and the bound is
    recomputed against the int8 stream + scale sidecar, so the row
    shows whether the kernel converts the smaller mandatory stream
    into steps/sec rather than being flattered by a bf16 denominator."""
    from apex_tpu.models.generation import init_paged_kv_caches
    from apex_tpu.ops import _support

    c = model.config
    S = prompt_len + 160                     # match bench_decode rows
    assert S % page_size == 0 and (S - prompt_len) % unroll == 0
    pps = S // page_size
    n_pages = batch * pps
    chain = chain or {1: 512, 8: 256}.get(batch, 160)
    chain -= chain % unroll
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len),
                                0, 50304)
    if c.compute_dtype != jnp.float32:
        params = cast_decode_params(params, c.compute_dtype)

    @jax.jit
    def prefill(params, caches, prompt):
        logits, caches = _cached_forward(model, params, caches, prompt, 0,
                                         last_only=True)
        first = jnp.argmax(logits[-1], axis=-1).astype(prompt.dtype)
        return caches, first

    dense, first = prefill(params, init_kv_caches(model, batch, S), prompt)
    # dense prefill rows -> fully-mapped pages: slot r's logical page j
    # is pool row r*pps + j (identity mapping; the engine's on-demand
    # table is host state the instrument doesn't need)
    caches = []
    for k, v in flatten_decode_caches(dense, c.num_layers):
        caches.append(tuple(
            x.reshape(batch * pps, page_size, x.shape[-1]) for x in (k, v)))
    del dense
    quant = kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8
    if quant:
        # whole-page quantize the prefilled pool (the engine's prefill
        # chunk path), outside the timed region: int8 pages + per-(page,
        # kv-head) float32 scale sidecar per side
        from apex_tpu.ops.decode_attention import paged_quant_fill
        dest = jnp.arange(n_pages, dtype=jnp.int32)
        caches = [
            tuple(paged_quant_fill(jnp.zeros(x.shape, jnp.int8),
                                   jnp.zeros((n_pages, c.kv_heads),
                                             jnp.float32), x, dest)
                  for x in (k, v))
            for k, v in caches]
    page_table = jnp.arange(n_pages, dtype=jnp.int32).reshape(batch, pps)
    params = preslice_layer_params(params, c.num_layers)

    prev = os.environ.get("APEX_TPU_FORCE_PALLAS")
    try:
        if mode == "unfused":
            os.environ["APEX_TPU_FORCE_PALLAS"] = "off"
        _support.pallas_mode.cache_clear()

        @functools.partial(jax.jit, donate_argnums=(1,))
        def paged_chain(params, caches, tok, pos):
            for t in range(unroll):
                logits, caches = decode_step(model, params, caches, tok,
                                             pos + t,
                                             paged_state=page_table)
                tok = jnp.argmax(logits, -1).astype(tok.dtype)
            return tok, caches

        # write positions cycle in [prompt_len, S): steady-state streams
        # a nearly-full pool, chain length stays dispatch-amortization
        bases = prompt_len + (np.arange(chain // unroll) * unroll) \
            % (S - prompt_len)
        pos0 = jnp.full((batch,), int(bases[0]), jnp.int32)
        tok, caches = paged_chain(params, caches, first, pos0)  # compile
        jax.block_until_ready(tok)
        t0 = time.perf_counter()
        for base in bases:
            tok, caches = paged_chain(
                params, caches, tok, jnp.full((batch,), int(base),
                                              jnp.int32))
        jax.block_until_ready(tok)
        dt = (time.perf_counter() - t0) / chain
    finally:
        if mode == "unfused":
            if prev is None:
                os.environ.pop("APEX_TPU_FORCE_PALLAS", None)
            else:
                os.environ["APEX_TPU_FORCE_PALLAS"] = prev
        _support.pallas_mode.cache_clear()

    tps = batch / dt
    # mandatory stream per step, averaged over the cycled positions:
    # pages_for(pos+1) pages of page_size rows each
    all_pos = (bases[:, None] + np.arange(unroll)[None, :]).ravel()
    tokens_streamed = float(np.mean(
        (all_pos // page_size + 1) * page_size))
    tag = "_int8" if quant else ""
    # bytes one step MUST stream under THIS pool dtype — the row's own
    # roofline denominator, and (sans params) the kv_bytes_per_step
    # gauge the serving engine exports for the same layout
    step_bytes = _paged_read_bytes(model, batch, tokens_streamed,
                                   page_size=page_size, kv_dtype=kv_dtype)
    row = {
        "metric": f"gpt2_124m_decode_paged_{mode}{tag}_bs{batch}"
                  f"_tokens_per_sec_per_chip",
        "value": round(tps, 1), "unit": "tokens/sec",
        "vs_baseline": round(tps / flat_tps, 3) if flat_tps else 1.0,
        "config": {"prompt_len": prompt_len, "decode_only": True,
                   "kv_layout": "paged", "mode": mode,
                   "kv_dtype": str(jnp.dtype(kv_dtype or c.compute_dtype)),
                   "page_size": page_size, "pages_per_slot": pps,
                   "n_pages": n_pages, "cache_len": S,
                   "avg_tokens_streamed": round(tokens_streamed, 1),
                   "read_bytes_per_step": int(step_bytes),
                   "method": f"host loop of jitted {unroll}-step unrolled "
                             f"paged decode programs, {chain} steps total "
                             f"(prefill untimed; dispatch biases tok/s "
                             f"low); vs_baseline = vs the flat-layout "
                             f"bench_decode row"}}
    bw = _hbm_bw()
    if bw is not None:
        bound_steps = bw / step_bytes
        row["pct_of_read_bw_bound"] = round(tps / (batch * bound_steps), 3)
        row["config"]["hbm_bw_gbps"] = round(bw / 1e9)
    print(json.dumps(row))
    return tps


def _pctl(values, p):
    values = sorted(values)
    return values[max(0, min(len(values) - 1,
                             -(-int(p * len(values)) // 100) - 1))]


def bench_serving(model, params, n_requests=32, max_new=32, max_slots=8,
                  prompt_lens=(64, 128, 256, 512)):
    """Serving-mode row: the SAME mixed-length request set through (a)
    lockstep ``generate()`` — every prompt padded into one batch, every
    request finishing with the longest — and (b) the continuous-batching
    engine, which retires each request on ITS OWN last token and refills
    the slot mid-flight. Matched load: identical prompts, identical
    per-request token budgets. Lockstep's per-request latency is one
    number (the whole batch), so the interesting deltas are the p50
    request latency and aggregate tokens/s.

    The request set comes from the loadtest traffic generator (one
    seeded source of synthetic serving traffic — the same code path
    ``python -m apex_tpu.loadtest`` scenarios replay — mirroring how
    FLOP math was unified into ``apex_tpu/utils/flops.py``): a single
    phase with a uniform mix over ``prompt_lens``, greedy, arrival
    times unused (both arms consume the whole set at once)."""
    from apex_tpu.loadtest import (
        EngineKnobs, LoadPhase, ModelSpec, Scenario, TrafficGenerator)
    from apex_tpu.serving import EngineConfig, InferenceEngine

    c = model.config
    max_len = max(prompt_lens) + max_new
    scenario = Scenario(
        name="bench_serving", seed=0,
        model=ModelSpec(
            num_layers=c.num_layers, hidden_size=c.hidden_size,
            num_attention_heads=c.num_attention_heads,
            vocab_size=c.vocab_size,
            max_position_embeddings=c.max_position_embeddings),
        engine=EngineKnobs(max_slots=max_slots, max_len=max_len,
                           max_queue=n_requests),
        phases=(LoadPhase(
            name="bench", n_requests=n_requests, rate_rps=1e6,
            prompt_lens={n: 1.0 for n in prompt_lens},
            max_new_tokens={max_new: 1.0}),))
    reqs = TrafficGenerator(scenario).requests()
    prompts = [list(r.prompt) for r in reqs]

    # -- lockstep generate(): slots = batch rows for comparability; each
    # sub-batch is padded to ITS longest prompt and nobody retires early
    t0 = time.perf_counter()
    for i in range(0, n_requests, max_slots):
        group = prompts[i:i + max_slots]
        width = max(len(p) for p in group)
        batch = np.zeros((len(group), width), np.int32)
        for r, p in enumerate(group):
            batch[r, :len(p)] = p
        out = generate(model, params, jnp.asarray(batch), max_new,
                       max_len=width + max_new)
        np.asarray(out)
    dt_lock = time.perf_counter() - t0
    total_new = n_requests * max_new
    print(json.dumps({
        "metric": "gpt2_124m_serving_lockstep_tokens_per_sec",
        "value": round(total_new / dt_lock, 1), "unit": "tokens/sec",
        "vs_baseline": 1.0,
        "config": {"n_requests": n_requests, "max_new": max_new,
                   "prompt_lens": list(prompt_lens),
                   "p50_request_latency_s": round(dt_lock, 3),
                   "p95_request_latency_s": round(dt_lock, 3),
                   "method": "batched generate(), zero-padded prompts "
                             "from the loadtest traffic generator; "
                             "every request waits for the whole batch"}}))

    # -- continuous batching: the SAME generated requests, per-request
    # retirement
    engine = InferenceEngine(model, params, EngineConfig(
        max_slots=max_slots, max_len=max_len))
    t0 = time.perf_counter()
    results = engine.serve(reqs)
    dt_engine = time.perf_counter() - t0
    lat = [r.total_s for r in results]
    generated = sum(r.new_tokens for r in results)
    print(json.dumps({
        "metric": "gpt2_124m_serving_engine_tokens_per_sec",
        "value": round(generated / dt_engine, 1), "unit": "tokens/sec",
        "vs_baseline": round((generated / dt_engine)
                             / (total_new / dt_lock), 3),
        "config": {"n_requests": n_requests, "max_new": max_new,
                   "max_slots": max_slots,
                   "prompt_lens": list(prompt_lens),
                   "p50_request_latency_s": round(_pctl(lat, 50), 3),
                   "p95_request_latency_s": round(_pctl(lat, 95), 3),
                   "decode_retraces": engine.decode_retraces,
                   "prefill_compiles": engine.prefill_compiles,
                   "method": "continuous batching (InferenceEngine), "
                             "same generated request set: per-step "
                             "admission/retirement, bucketed prefill, "
                             "one jitted decode program"}}))


def bench_serving_prefix(model, params, n_requests=16, max_new=16,
                         max_slots=8, shared_len=384, prompt_len=512,
                         page_size=32):
    """Prefix-cache row pair: the SAME shared-prefix request set through
    the paged engine cold (``prefix_cache=False``) and hot (the
    default). Traffic comes from the loadtest generator's
    ``shared_prefix_len`` knob — every prompt opens with one 384-token
    prefix (12 full pages at ``page_size=32``) and a unique 128-token
    tail, the system-prompt shape the ``shared_prefix`` scenario gates in
    CI. Cold prefills all 512 tokens per request; hot interns the prefix
    on the first miss and every later admit maps the shared pages and
    computes only its 128-token suffix bucket, so the interesting deltas
    are prefill p50 (per-request prefill wall) and aggregate tokens/s.
    ``vs_baseline`` on the cached row is hot/cold tokens-per-sec."""
    from apex_tpu.loadtest import (
        EngineKnobs, LoadPhase, ModelSpec, Scenario, TrafficGenerator)
    from apex_tpu.serving import EngineConfig, InferenceEngine

    c = model.config
    max_len = prompt_len + max_new
    scenario = Scenario(
        name="bench_prefix", seed=0,
        model=ModelSpec(
            num_layers=c.num_layers, hidden_size=c.hidden_size,
            num_attention_heads=c.num_attention_heads,
            vocab_size=c.vocab_size,
            max_position_embeddings=c.max_position_embeddings),
        engine=EngineKnobs(max_slots=max_slots, max_len=max_len,
                           max_queue=n_requests, page_size=page_size),
        phases=(LoadPhase(
            name="bench", n_requests=n_requests, rate_rps=1e6,
            prompt_lens={prompt_len: 1.0},
            max_new_tokens={max_new: 1.0},
            shared_prefix_len=shared_len),))
    cold_tps = None
    for label, cache_on in (("cold", False), ("cached", True)):
        reqs = TrafficGenerator(scenario).requests()
        engine = InferenceEngine(model, params, EngineConfig(
            max_slots=max_slots, max_len=max_len, page_size=page_size,
            prefix_cache=cache_on))
        with engine:
            t0 = time.perf_counter()
            results = engine.serve(reqs)
            dt = time.perf_counter() - t0
            counters = engine.metrics.counters()
        generated = sum(r.new_tokens for r in results)
        tps = generated / dt
        prefill = [r.prefill_s for r in results]
        ttft = [r.ttft_s for r in results if r.ttft_s is not None]
        # prefill tokens the engine actually computed: every prompt
        # token, minus the rows backed by mapped shared pages (a fully
        # page-aligned hit re-computes its boundary row, masked)
        computed = (sum(r.prompt_len for r in results)
                    - counters.get("prefix_pages_shared", 0) * page_size)
        row = {
            "metric": f"gpt2_124m_serving_prefix_{label}_tokens_per_sec",
            "value": round(tps, 1), "unit": "tokens/sec",
            "vs_baseline": round(tps / cold_tps, 3) if cold_tps else 1.0,
            "config": {
                "n_requests": n_requests, "max_new": max_new,
                "max_slots": max_slots, "prompt_len": prompt_len,
                "shared_prefix_len": shared_len, "page_size": page_size,
                "prefix_cache": cache_on,
                "prefill_tokens_computed": computed,
                "p50_prefill_s": round(_pctl(prefill, 50), 4),
                "p95_prefill_s": round(_pctl(prefill, 95), 4),
                "p50_ttft_s": round(_pctl(ttft, 50), 4) if ttft else None,
                "prefix_hits": counters.get("prefix_hits", 0),
                "prefix_misses": counters.get("prefix_misses", 0),
                "decode_retraces": engine.decode_retraces,
                "method": "identical shared-prefix request set "
                          "(loadtest generator, shared_prefix_len knob); "
                          "vs_baseline on the cached row = cached/cold "
                          "tokens-per-sec at matched load"}}
        print(json.dumps(row))
        if not cache_on:
            cold_tps = tps


def bench_serving_interference(model, params, max_slots=4, co_prompt=32,
                               co_new=32, long_prompt=1536, n_long=2,
                               long_new=8, budget=128):
    """Prefill-interference row pair: one short greedy co-tenant decoding
    while ``n_long`` 1536-token prompts arrive, through (a) monolithic
    admission and (b) ``prefill_token_budget``-chunked admission, on a
    compile-warmed flat engine. The statistic is the co-tenant's
    worst inter-token gap, NOT its mean TPOT: under monolithic admission
    the co-tenant still decodes every tick (tick = admit+prefill, then
    batched decode), so the stall shows up as ONE tick whose wall time
    includes the whole 1536-token prefill program — a spike the mean
    dilutes across 32 tokens. Each arm serves a warmup set first so every
    prefill/chunk bucket and the decode program are compiled before the
    timed window; the gap then measures scheduling, not retracing.
    ``vs_baseline`` on the chunked row is monolithic/chunked max gap
    (>1 means chunking bounded the stall).

    This pair runs the forward in float32: CPU emulates bf16, which puts
    a ~5 s FIXED cost on every prefill program regardless of token count
    — a 64-token chunk cost as much as a 512-token monolithic prefill,
    compressing the gap ratio toward 1 no matter the budget. f32 on CPU
    is token-proportional (the regime every TPU dtype is in), so the
    ratio measures scheduling rather than the emulation floor."""
    import dataclasses
    from apex_tpu.models import GPTModel
    from apex_tpu.serving import EngineConfig, InferenceEngine, Request

    model = GPTModel(dataclasses.replace(model.config,
                                         compute_dtype=jnp.float32))
    max_len = long_prompt + long_new
    rng = np.random.RandomState(7)
    co_tokens = rng.randint(1, model.config.vocab_size,
                            size=co_prompt).tolist()
    long_tokens = [rng.randint(1, model.config.vocab_size,
                               size=long_prompt).tolist()
                   for _ in range(n_long)]
    warm_tokens = [rng.randint(1, model.config.vocab_size,
                               size=n).tolist()
                   for n in (co_prompt, long_prompt)]
    mono_max = None
    for label, arm_budget in (("monolithic", None), ("chunked", budget)):
        # flat layout, prefix_cache off: both are orthogonal to admission
        # scheduling (the paged composition is gated by the bimodal_burst
        # loadtest scenario), and a warmup-interned prefix would let the
        # measured long prompts skip their prefill entirely, hiding the
        # stall both arms measure
        engine = InferenceEngine(model, params, EngineConfig(
            max_slots=max_slots, max_len=max_len,
            prefill_token_budget=arm_budget, prefix_cache=False))
        with engine:
            # warm every program the timed window uses: the co-tenant's
            # prefill bucket, the long prompt's prefill (or chunk)
            # buckets, and the batched decode step
            engine.serve([
                Request(prompt=list(warm_tokens[0]), max_new_tokens=2),
                Request(prompt=list(warm_tokens[1]), max_new_tokens=2)])
            co = Request(prompt=list(co_tokens), max_new_tokens=co_new)
            engine.submit(co)
            engine.tick()  # co admitted + prefilled; decoding from here
            for toks in long_tokens:
                engine.submit(Request(prompt=list(toks),
                                      max_new_tokens=long_new))
            gaps = []
            t_prev = time.perf_counter()
            for _ in range(co_new + 64):
                finished = engine.tick()
                t = time.perf_counter()
                gaps.append(t - t_prev)
                t_prev = t
                if any(r.request_id == co.request_id for r in finished):
                    break
            else:
                raise RuntimeError("co-tenant never finished")
            while engine.tick() or engine._active or engine._prefilling:
                pass  # drain the long requests off the timed path
            counters = engine.metrics.counters()
            retraces = engine.decode_retraces
        row = {
            "metric": f"gpt2_124m_serving_interference_{label}_max_gap_s",
            "value": round(max(gaps), 4), "unit": "seconds",
            "vs_baseline": (round(mono_max / max(gaps), 3)
                            if mono_max else 1.0),
            "config": {
                "max_slots": max_slots, "co_prompt": co_prompt,
                "co_new": co_new, "long_prompt": long_prompt,
                "n_long": n_long, "compute_dtype": "float32",
                "prefill_token_budget": arm_budget,
                "p99_gap_s": round(_pctl(gaps, 99), 4),
                "p50_gap_s": round(_pctl(gaps, 50), 4),
                "mean_tpot_s": round(sum(gaps) / len(gaps), 4),
                "prefill_chunks": counters.get("prefill_chunks", 0),
                "decode_retraces": retraces,
                "method": "co-tenant inter-token gap = per-tick wall "
                          "while it decodes through a long-prompt "
                          "burst, compile-warmed flat engine, f32 "
                          "forward (CPU bf16 emulation has a fixed "
                          "per-program cost that masks scheduling); "
                          "vs_baseline on the chunked row = "
                          "monolithic/chunked max gap. CPU rows are "
                          "correctness-only — the TPOT bar is a "
                          "hardware (TPU) measurement"}}
        print(json.dumps(row))
        if arm_budget is None:
            mono_max = max(gaps)


def main():
    model, params = _model()
    bench_prefill(model, params)
    for b in (1, 8, 32):
        flat = bench_decode(model, params, batch=b)
        for mode in ("fused", "unfused"):
            bench_decode_paged(model, params, batch=b, mode=mode,
                               flat_tps=flat)
        # int8 pool: same fused dispatch, roughly half the mandatory
        # stream — the quantization win at identical layout
        bench_decode_paged(model, params, batch=b, mode="fused",
                           kv_dtype="int8", flat_tps=flat)
    bench_serving(model, params)
    bench_serving_prefix(model, params)
    bench_serving_interference(model, params)


if __name__ == "__main__":
    main()
