"""BASELINE config 3: BERT-base pretrain step, FusedLAMB + Pallas LayerNorm.

Measures tokens/sec/chip.
Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/bert_lamb.py``
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from benchmarks._harness import run, transformer_train_flops
from apex_tpu.models import BertModel, TransformerConfig
from apex_tpu.optimizers import FusedLAMB
from apex_tpu.transformer.enums import AttnMaskType


def main(batch=16, seq=512):
    cfg = TransformerConfig(
        num_layers=12, hidden_size=768, num_attention_heads=12,
        vocab_size=30528, max_position_embeddings=512,
        hidden_dropout=0.0, attention_dropout=0.0,
        attn_mask_type=AttnMaskType.padding,
        # r3 tuning: activations fit without recompute at this size; the
        # unrolled layer scan removes while-loop + stacked-save overhead
        recompute=False, scan_unroll=12, compute_dtype=jnp.bfloat16)
    model = BertModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedLAMB(lr=1e-3, weight_decay=0.01)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, 30528)
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0, 30528)

    from functools import partial

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state):
        def loss_fn(p):
            lm_loss, _ = model.apply(p, tokens, lm_labels=labels)
            return lm_loss
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.step(grads, params, opt_state)
        return params, opt_state, loss

    n_params = sum(x.size for x in jax.tree.leaves(params))
    return run("bert_base_lamb_train_tokens_per_sec_per_chip", "tokens/sec",
               step, params, opt_state, work_per_step=batch * seq,
               consume_state=True,
               model_flops_per_step=transformer_train_flops(
                   n_params, batch * seq, 12, 768, seq, causal=False))


if __name__ == "__main__":
    main()
