"""fp8 dense benchmark: native-fp8 dot vs the bf16 MXU path.

VERDICT r3 item 8: record the platform verdict with a measured row.
``native_fp8_dot_supported()`` returns True on this v5e — fp8 operands
compile and run — but v5e's MXU has no fp8 execution units (those arrive
with v6e/Trillium), so the interesting question is whether native-fp8
storage costs or saves time vs bf16. One delayed-scaling ``fp8_dense``
fwd+bwd over a GPT-355M-sized GEMM, chained in-jit (the dispatch-overhead
methodology of PERF.md), against the same matmul in bf16.

Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/fp8_bench.py``
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.amp import fp8

M, K, N = 8192, 1024, 4096
ITERS = 150   # ~400 ms/chain: 5-20 ms tunnel dispatch amortizes to <5%
              # per endpoint; interleaved windows tighten the RATIO to ~3%
              # (round 4's 50-iter chain had +-15% noise and a verdict
              # range that excluded the driver's own capture — VERDICT r4)


def main():
    x = jax.random.normal(jax.random.PRNGKey(0), (M, K), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (K, N), jnp.bfloat16)
    state = fp8.init_fp8_state(("x", "w"))
    # probe, don't assume (ADVICE r4: the row hardcoded True; on a backend
    # without the native dot the bench just crashed and the recorded claim
    # would be wrong if copied to another platform)
    native = bool(fp8.native_fp8_dot_supported())

    # sum(y^2): the cotangent is 2y, a real data-dependent matrix — a
    # plain sum(y) makes dL/dy all-ones, which XLA folds into reductions
    # and the "GEMM" backward vanishes. BOTH grads and the fp8 state feed
    # the scan carry so nothing is dead-code-eliminated or hoisted: dw
    # stays live (all 3 GEMMs execute), w changes per step (weights are
    # re-quantized each iteration, as in real training), and the
    # delayed-scaling amax updates remain in the timed program.
    def fp8_loss(x, w, state):
        y, state = fp8.fp8_dense(x, w, state, native=native)
        y32 = y.astype(jnp.float32)
        return jnp.sum(y32 * y32), state

    g8 = jax.value_and_grad(fp8_loss, argnums=(0, 1), has_aux=True)

    @jax.jit
    def run_fp8(x, w, state):
        def body(carry, _):
            c, w, state = carry
            (_, state), (dx, dw) = g8(c, w, state)
            return (c + (1e-6 * dx).astype(c.dtype),
                    w + (1e-6 * dw).astype(w.dtype), state), None
        carry, _ = jax.lax.scan(body, (x, w, state), None, length=ITERS)
        return carry[0]

    def bf16_loss(x, w):
        y = (x @ w).astype(jnp.float32)
        return jnp.sum(y * y)

    gb = jax.grad(bf16_loss, argnums=(0, 1))

    @jax.jit
    def run_bf16(x, w):
        def body(carry, _):
            c, w = carry
            dx, dw = gb(c, w)
            return (c + (1e-6 * dx).astype(c.dtype),
                    w + (1e-6 * dw).astype(w.dtype)), None
        carry, _ = jax.lax.scan(body, (x, w), None, length=ITERS)
        return carry[0]

    def _one(run, *args):
        t0 = time.perf_counter()
        out = run(*args)
        np.asarray(jax.tree.leaves(out)[0]).ravel()[0]
        return (time.perf_counter() - t0) / ITERS

    # warmup both, then INTERLEAVE the timing windows (A,B,A,B...): slow
    # tunnel drift hits both configs equally, so the best-of ratio is
    # pinned far tighter than two separate best-of-3 blocks
    _one(run_fp8, x, w, state)
    _one(run_bf16, x, w)
    t8 = tb = float("inf")
    for _ in range(4):
        t8 = min(t8, _one(run_fp8, x, w, state))
        tb = min(tb, _one(run_bf16, x, w))
    flops = 3 * 2 * M * K * N            # fwd + dx + dw matmuls
    print(json.dumps({
        "metric": ("fp8_dense_native_fwd_bwd_tflops" if native
                   else "fp8_dense_qdq_fwd_bwd_tflops"),
        "value": round(flops / t8 / 1e12, 1), "unit": "TFLOP/s",
        "vs_baseline": round(tb / t8, 3),
        "config": {"shape": [M, K, N], "iters": ITERS,
                   "native_fp8_dot_supported": native,
                   "baseline": "same GEMM chain in bf16 (interleaved "
                               "windows)",
                   "note": "v5e MXU executes fp8 operands without fp8 "
                           "units; vs_baseline < 1 means fp8 costs time "
                           "on this generation"}}))


if __name__ == "__main__":
    main()
