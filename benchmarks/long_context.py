"""Long-context benchmark: GPT training at 32k/64k tokens on one chip.

The reference's attention kernels hard-cap at 16k
(``/root/reference/csrc/megatron/scaled_masked_softmax.h:460``); these
configs run full GPT-2-size training steps at 2x and 4x that length through
the Pallas flash kernel (O(seq) memory): 32k full-causal, 32k
sliding-window, and 64k sliding-window. Context-parallel ring/Ulysses
extend the same kernels across chips (``tests/test_context_parallel.py``
pins parity and per-rank memory; a 128k ring phase runs in
``__graft_entry__.dryrun_multichip``).

Tuning (measured on v5e, PERF.md round 3): long-seq flash blocks
(1024, 1024) auto-selected by the kernel; no activation recompute — flash's
O(seq) residuals fit, and skipping the backward's attention re-run is worth
1.27x at 32k; unrolled layer scan; donated buffers.

Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/long_context.py``
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from functools import partial

import jax
import jax.numpy as jnp

from benchmarks._harness import run, transformer_train_flops
from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.optimizers import FusedAdam

LAYERS, HIDDEN, HEADS = 12, 768, 12


def main(seq=32768, window=None):
    # recompute-free fits through 32k (flash O(seq) residuals); at 64k the
    # saved activations + vocab logits exceed 16 GB, and with a sliding
    # window the re-run attention is cheap anyway
    cfg = TransformerConfig(
        num_layers=LAYERS, hidden_size=HIDDEN, num_attention_heads=HEADS,
        vocab_size=50304, max_position_embeddings=seq,
        position_embedding_type="rope",
        hidden_dropout=0.0, attention_dropout=0.0,
        sliding_window=window,
        recompute=(seq > 32768),
        # unrolled layers win at 32k; at 64k the unrolled graph lets every
        # layer's recompute buffers coexist and blows the 16 GB budget
        scan_unroll=(LAYERS if seq <= 32768 else 1),
        loss_seq_chunks=max(seq // 16384, 1),
        compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, seq), 0, 50304)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: model.apply(p, tokens, tokens))(params)
        params, opt_state = opt.step(grads, params, opt_state)
        return params, opt_state, loss

    n_params = sum(x.size for x in jax.tree.leaves(params))
    # attention term reflects the true window span when sliding
    eff_span = min(window, seq) if window else seq
    kt = f"{seq // 1024}k"
    name = (f"gpt2_124m_seq{kt}_window{window}" if window
            else f"gpt2_124m_seq{kt}")
    # full causal attention averages s/2 keys per query; a sliding window
    # averages ~window keys (no halving)
    return run(f"{name}_train_tokens_per_sec_per_chip", "tokens/sec",
               step, params, opt_state, work_per_step=seq, steps=5,
               consume_state=True,
               model_flops_per_step=transformer_train_flops(
                   n_params, seq, LAYERS, HIDDEN, eff_span,
                   causal=(window is None)))


if __name__ == "__main__":
    main()
    main(window=1024)
    main(seq=65536, window=1024)
