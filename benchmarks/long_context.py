"""Long-context benchmark: GPT training at 32k tokens on one chip.

The reference's attention kernels hard-cap at 16k
(``/root/reference/csrc/megatron/scaled_masked_softmax.h:460``); this config
runs a full GPT-2-size training step at 2x that length through the Pallas
flash kernel (O(seq) memory), plus a sliding-window variant
(O(seq * window) compute). Context-parallel ring/Ulysses extend the same
kernels across chips (``tests/test_context_parallel.py`` pins parity and
per-rank memory; multi-chip speed needs a real mesh).
Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/long_context.py``
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from benchmarks._harness import run, transformer_train_flops
from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.optimizers import FusedAdam


def main(seq=32768, window=None):
    cfg = TransformerConfig(
        num_layers=12, hidden_size=768, num_attention_heads=12,
        vocab_size=50304, max_position_embeddings=seq,
        position_embedding_type="rope",
        hidden_dropout=0.0, attention_dropout=0.0,
        sliding_window=window,
        recompute=True, compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, seq), 0, 50304)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: model.apply(p, tokens, tokens))(params)
        params, opt_state = opt.step(grads, params, opt_state)
        return params, opt_state, loss

    n_params = sum(x.size for x in jax.tree.leaves(params))
    # attention term reflects the true window span when sliding
    eff_span = min(window, seq) if window else seq
    name = (f"gpt2_124m_seq32k_window{window}" if window
            else "gpt2_124m_seq32k")
    # full causal attention averages s/2 keys per query; a sliding window
    # averages ~window keys (no halving)
    return run(f"{name}_train_tokens_per_sec_per_chip", "tokens/sec",
               step, params, opt_state, work_per_step=seq, steps=5,
               model_flops_per_step=transformer_train_flops(
                   n_params, seq, 12, 768, eff_span,
                   causal=(window is None)))


if __name__ == "__main__":
    main()
    main(window=1024)
