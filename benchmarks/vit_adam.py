"""BASELINE config 5: ViT-L/16 + FusedAdam train step; imgs/sec/chip.

Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/vit_adam.py``
"""

import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from benchmarks._harness import run, transformer_train_flops
from apex_tpu.models import vit_l16
from apex_tpu.optimizers import FusedAdam


def main(batch=32, image=224):
    model = vit_l16(image_size=image, num_classes=1000,
                    # r3 tuning: no recompute + unrolled scan + donation
                    recompute=False, scan_unroll=24,
                    compute_dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=3e-4, weight_decay=0.05)
    opt_state = opt.init(params)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, image, image, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)

    from functools import partial

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state):
        def loss_fn(p):
            logits = model.apply(p, x)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(logp[jnp.arange(batch), y])
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.step(grads, params, opt_state)
        return params, opt_state, loss

    n_params = sum(x.size for x in jax.tree.leaves(params))
    tokens = batch * ((image // 16) ** 2 + 1)
    return run("vit_l16_adam_train_imgs_per_sec_per_chip", "imgs/sec",
               step, params, opt_state, work_per_step=batch,
               consume_state=True,
               model_flops_per_step=transformer_train_flops(
                   n_params, tokens, 24, 1024, (image // 16) ** 2 + 1,
                   causal=False))


if __name__ == "__main__":
    main()
