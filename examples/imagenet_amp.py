"""ResNet-50 "ImageNet" training — the reference's flagship example.

Capability parity with ``/root/reference/examples/imagenet/main_amp.py``:
amp O2 (bf16 compute, fp32 master weights in the optimizer, dynamic loss
scaling for fp16), data parallelism (the apex-DDP role is one ``shard_map``
over the ``data`` mesh axis with SyncBN statistics ``psum``-merged), fused
SGD with momentum, and the per-interval ``Speed`` (imgs/sec) printout of
``main_amp.py:386-400``.

Runs on whatever devices exist: the real TPU chip (DP=1) or a virtual CPU
mesh (``XLA_FLAGS=--xla_force_host_platform_device_count=8`` + env
``JAX_PLATFORMS=cpu``).

Data flows through the real input pipeline
(:mod:`apex_tpu.data.pipeline`): ``--data-dir`` points at an on-disk
uint8-shard dataset (materialized synthetically on first run when absent —
swap in real ImageNet by replacing the shard reader), worker threads
augment/normalize, the C++ token queue stages batches, and ``device_put``
runs one batch ahead — the DALI/DataLoader prefetch role of the reference
example.

Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python examples/imagenet_amp.py
[--iters N] [--batch B] [--image-size S] [--data-dir DIR]``
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from apex_tpu import amp
from apex_tpu.models import ResNet, ResNetConfig
from apex_tpu.optimizers import FusedSGD


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=40)
    ap.add_argument("--batch", type=int, default=64, help="global batch")
    ap.add_argument("--image-size", type=int, default=128)
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--print-freq", type=int, default=10)
    ap.add_argument("--data-dir", type=str, default="/tmp/apex_tpu_imagenet",
                    help="on-disk dataset root (synthesized when absent)")
    ap.add_argument("--num-workers", type=int, default=2)
    ap.add_argument("--prefetch", type=int, default=2)
    args = ap.parse_args()

    devices = jax.devices()
    ndev = len(devices)
    assert args.batch % ndev == 0, \
        "global batch must be a multiple of the device count"
    mesh = Mesh(np.array(devices), ("data",))
    print(f"devices: {ndev} x {devices[0].device_kind} | "
          f"global batch {args.batch}")

    amp_state = amp.initialize("O2")  # bf16 compute, fp32 master, no scaling
    model = ResNet(ResNetConfig(
        depth=50, num_classes=args.num_classes,
        axis_name="data" if ndev > 1 else None,
        compute_dtype=jnp.bfloat16))
    params, bn_state = model.init(jax.random.PRNGKey(0))
    opt = FusedSGD(lr=args.lr, momentum=0.9, weight_decay=1e-4,
                   master_weights=True)
    opt_state = opt.init(params)

    def per_rank_step(params, bn_state, opt_state, images, labels):
        def loss_fn(p):
            logits, new_bn = model.apply(p, bn_state, images, train=True)
            logp = jax.nn.log_softmax(logits)
            n = labels.shape[0]
            loss = -jnp.mean(logp[jnp.arange(n), labels])
            return loss, (new_bn, logits)

        (loss, (new_bn, logits)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        if ndev > 1:
            grads = jax.lax.pmean(grads, "data")
            loss = jax.lax.pmean(loss, "data")
        params, opt_state = opt.step(grads, params, opt_state)
        n = labels.shape[0]
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        if ndev > 1:
            acc = jax.lax.pmean(acc, "data")
        return params, new_bn, opt_state, loss, acc

    if ndev > 1:
        step = jax.jit(shard_map(
            per_rank_step, mesh=mesh,
            in_specs=(P(), P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P(), P(), P())),
            donate_argnums=(0, 1, 2))
    else:
        step = jax.jit(per_rank_step, donate_argnums=(0, 1, 2))

    # real input pipeline: on-disk shards -> worker-thread augment -> C++
    # token queue -> device_put one batch ahead (apex_tpu.data.pipeline)
    from apex_tpu.data import make_input_pipeline, write_synthetic_imagenet

    stored = max(args.image_size, int(args.image_size * 1.15))
    per_shard = max(args.batch, 256)
    # key the scratch dataset dir by its shape config so flag changes
    # regenerate instead of tripping the meta-mismatch guard
    data_dir = (f"{args.data_dir}-{stored}px-{per_shard}x4"
                f"-c{args.num_classes}")
    write_synthetic_imagenet(
        data_dir, num_shards=4, per_shard=per_shard, image_size=stored,
        num_classes=args.num_classes)
    loader = make_input_pipeline(
        data_dir, args.batch, mesh=mesh if ndev > 1 else None,
        crop=args.image_size, prefetch=args.prefetch,
        num_workers=args.num_workers)
    batches = iter(loader)

    # warmup/compile
    images, labels = next(batches)
    params, bn_state, opt_state, loss, acc = step(
        params, bn_state, opt_state, images, labels)
    jax.block_until_ready(loss)
    print(f"compiled; initial loss {float(loss):.4f}")

    t0 = time.perf_counter()
    tlast, seen = t0, 0
    for it in range(1, args.iters + 1):
        images, labels = next(batches)
        params, bn_state, opt_state, loss, acc = step(
            params, bn_state, opt_state, images, labels)
        seen += args.batch
        if it % args.print_freq == 0:
            jax.block_until_ready(loss)
            now = time.perf_counter()
            speed = seen / (now - tlast)
            print(f"iter {it:4d}  loss {float(loss):7.4f}  "
                  f"prec@1 {float(acc) * 100:5.2f}  "
                  f"Speed {speed:9.1f} imgs/sec "
                  f"({speed / ndev:.1f}/chip)")
            tlast, seen = now, 0
    jax.block_until_ready(loss)
    total = args.iters * args.batch / (time.perf_counter() - t0)
    print(f"mean throughput: {total:.1f} imgs/sec over {args.iters} iters")


if __name__ == "__main__":
    main()
