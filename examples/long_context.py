"""Long-context training demo: 32k tokens on one chip, ring attention on a
mesh.

The reference's attention kernels cap at 16k tokens
(``csrc/megatron/scaled_masked_softmax.h:460``); this example trains
GPT-2-size models beyond that, two ways:

- single device: the Pallas flash kernel's O(seq) memory at ``--seq 32768``
  (optionally ``--window`` for Mistral-style local attention — banded-grid
  kernels make it O(seq x window));
- multi device (``--cp N``): ring-attention context parallelism — the
  sequence is sharded over the ``context`` mesh axis, K/V chunks rotate
  over ICI, and the loss/grads match the unsharded model exactly.

Usage:
  PYTHONPATH=/root/repo:/root/.axon_site python examples/long_context.py \
      [--seq 32768] [--window 1024] [--iters 5]
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/long_context.py --cp 4 --seq 2048 --force-cpu
"""

import argparse
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=32768)
    ap.add_argument("--window", type=int, default=None)
    ap.add_argument("--cp", type=int, default=1,
                    help="context-parallel size (ring attention)")
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--hidden", type=int, default=768)
    ap.add_argument("--force-cpu", action="store_true")
    args = ap.parse_args()

    if args.force_cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models import GPTModel, TransformerConfig
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step
    from apex_tpu.transformer import parallel_state

    cp = args.cp
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(context_parallel_size=cp)
    cfg = TransformerConfig(
        num_layers=args.layers, hidden_size=args.hidden,
        num_attention_heads=args.hidden // 64, vocab_size=50304,
        max_position_embeddings=args.seq,
        position_embedding_type="rope",
        hidden_dropout=0.0, attention_dropout=0.0,
        sliding_window=args.window,
        context_parallel_method="ring" if cp > 1 else None,
        recompute=True, compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    layout = f"cp {cp} (ring)" if cp > 1 else "single device"
    print(f"{n_params/1e6:.0f}M params | seq {args.seq} | "
          f"window {args.window} | {layout}")

    tokens = jax.random.randint(jax.random.PRNGKey(1), (cp, args.seq),
                                0, 50304)
    # next-token objective: position t predicts token t+1 (lm_head_loss
    # does not shift internally); the final position has no target
    labels = jnp.roll(tokens, -1, axis=1)
    loss_mask = jnp.ones(tokens.shape, jnp.float32).at[:, -1].set(0.0)
    opt = FusedAdam(lr=1e-4)
    opt_state = opt.init(params)
    # sequence positions shard over the context axis; each rank computes
    # its local loss, averaged over data+context by the train step
    step = make_train_step(
        lambda p, b, rng: model.apply(p, b["tokens"], b["labels"],
                                      loss_mask=b["loss_mask"]),
        opt, mesh, model.spec(),
        {"tokens": P(None, "context"), "labels": P(None, "context"),
         "loss_mask": P(None, "context")},
        opt_state_spec=opt.state_spec(params, model.spec()),
        data_axes=("data", "context"))
    batch = {"tokens": tokens, "labels": labels, "loss_mask": loss_mask}

    params, opt_state, loss = step(params, opt_state, batch, None)
    print(f"compiled; initial loss {float(loss):.4f}")
    t0 = time.perf_counter()
    for it in range(args.iters):
        params, opt_state, loss = step(params, opt_state, batch, None)
    loss = float(loss)
    dt = (time.perf_counter() - t0) / args.iters
    tput = tokens.size / dt
    print(f"loss {loss:.4f} | {dt*1e3:.0f} ms/step | "
          f"{tput:,.0f} tokens/sec total")


if __name__ == "__main__":
    main()
