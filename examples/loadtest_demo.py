"""Load-test harness demo — scenarios, SLO verdicts, the gate.

Act 1 runs the committed tier-1 smoke scenario
(``benchmarks/scenarios/smoke.json``, docs/loadtest.md): seeded
open-loop Poisson traffic through the engine-under-supervisor, one
JSONL log, and an SLO verdict scored from that log — then renders the
``python -m apex_tpu.monitor`` report whose SLO section reconciles with
the run.

Act 2 is the measurement the resilience claims have been waiting for:
a scenario whose fault schedule crashes the engine mid-run
(``decode_raise_calls``), so the scored ``recovery_s`` — worst gap from
the ``engine_restart`` incident to the first post-recovery completion —
is a *measured, finite* number, not an anecdote.

Act 3 shows the regression gate failing red: the same run checked
against a deliberately tightened baseline exits 2 (regression), the way
CI catches a serving change that moved a latency the wrong way.

Run (from the repo root): PYTHONPATH=. python examples/loadtest_demo.py
"""

import json
import os
import tempfile

from apex_tpu.loadtest import Scenario, build_model, run_scenario
from apex_tpu.loadtest.__main__ import main as loadtest_cli
from apex_tpu.observability import build_report, render_report

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SMOKE = os.path.join(ROOT, "benchmarks", "scenarios", "smoke.json")


def act1_smoke(workdir: str):
    print("=== act 1: smoke scenario -> SLO verdict ===")
    scenario = Scenario.load(SMOKE)
    log = os.path.join(workdir, "smoke.jsonl")
    run = run_scenario(scenario, log_path=log)
    assert not run.aborted
    print(f"served {len(run.results)} requests in {run.wall_s:.2f}s "
          f"({run.ticks} ticks, {run.engine_restarts} restarts)")
    assert run.slo is not None and run.ok, "smoke SLOs must pass"
    for obj in run.slo.objectives:
        print(f"  {obj.name:<16} measured={obj.measured:.4g} "
              f"{'<=' if obj.direction == 'max' else '>='} "
              f"{obj.threshold:g}  -> {'ok' if obj.ok else 'VIOLATED'}")
    print()
    print(render_report(build_report(log)))
    return log


def act2_crash_recovery(workdir: str):
    print("\n=== act 2: scheduled crash, measured recovery ===")
    scenario = Scenario.from_dict({
        "name": "demo-crash", "seed": 5,
        "engine": {"max_slots": 4, "max_len": 32, "max_queue": 16},
        "supervisor": {"max_restarts_per_request": 4},
        "phases": [{"name": "steady", "n_requests": 12,
                    "rate_rps": 100.0, "prompt_lens": {"4": 2, "8": 1},
                    "max_new_tokens": {"4": 1, "6": 1}}],
        "faults": {"decode_raise_calls": [6]},
        "slo": {"goodput": 0.99, "error_budget": 0.0,
                "recovery_s": 60.0}})
    model, params = build_model(scenario.model)
    log = os.path.join(workdir, "crash.jsonl")
    run = run_scenario(scenario, model=model, params=params, log_path=log)
    assert run.engine_restarts >= 1, "the scheduled crash must fire"
    recovery = run.metrics_by_name["recovery_s"]
    assert recovery is not None and recovery < float("inf")
    print(f"engine restarts: {run.engine_restarts}  "
          f"recovered requests: {run.counters['requests_recovered']}")
    print(f"measured recovery time: {recovery:.3f}s "
          f"(SLO <= 60s -> {'ok' if run.ok else 'VIOLATED'})")
    assert run.ok, run.slo.as_dict()
    return log


def act3_gate_red(workdir: str, smoke_log: str):
    print("\n=== act 3: the gate fails red on a tightened baseline ===")
    baseline = os.path.join(workdir, "tight_baseline.json")
    with open(baseline, "w", encoding="utf-8") as f:
        # a bar no CPU run can meet: any real latency is a "regression"
        json.dump({"smoke": {"ttft_p99_s": 1e-4}}, f)
    rc = loadtest_cli([SMOKE, "--from-log", smoke_log, "--check",
                       "--baseline", baseline])
    print(f"gate exit code: {rc}")
    assert rc == 2, "tightened baseline must trip the regression gate"


def main():
    with tempfile.TemporaryDirectory() as workdir:
        smoke_log = act1_smoke(workdir)
        act2_crash_recovery(workdir)
        act3_gate_red(workdir, smoke_log)
    print("\nloadtest demo: all acts passed")


if __name__ == "__main__":
    main()
