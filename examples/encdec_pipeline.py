"""User-style demo: T5-style encoder-decoder trained through the
two-section pipeline (``ModelType.encoder_and_decoder``).

A 4-stage pipeline split at rank 2 (2 encoder + 2 decoder stages) times
data parallelism on the 8-device virtual CPU mesh, driven by the 1F1B
schedule with the ``(enc_stream, dec_stream)`` lock-step carry. The task
is sequence reversal — the decoder must cross-attend the encoder output
to solve it, so a falling loss demonstrates the full enc-dec dataflow
through the pipeline.

Run: ``python examples/encdec_pipeline.py``
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax

# this demo always uses the 8-device VIRTUAL CPU mesh — it needs 8
# devices for the pp=4 x dp=2 layout; on a real multi-chip TPU slice,
# drop this line and the XLA_FLAGS override above
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.models import PipelinedEncoderDecoder, TransformerConfig
from apex_tpu.optimizers import FusedAdam
from apex_tpu.training import make_train_step
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel.utils import (
    split_batch_into_microbatches,
)

PP, SPLIT, M = 4, 2, 2
VOCAB, SEQ = 64, 16

mesh = parallel_state.initialize_model_parallel(
    pipeline_model_parallel_size=PP,
    pipeline_model_parallel_split_rank=SPLIT)
dp = mesh.shape["data"]
print(f"mesh: pp={PP} (split at {SPLIT}: {SPLIT} enc + {PP - SPLIT} dec "
      f"stages) x dp={dp}")

cfg = TransformerConfig(
    num_layers=2, hidden_size=64, num_attention_heads=4, vocab_size=VOCAB,
    max_position_embeddings=SEQ * 2, hidden_dropout=0.0,
    attention_dropout=0.0)
model = PipelinedEncoderDecoder(cfg, pipeline_size=PP, num_microbatches=M,
                                num_encoder_layers=2)
params = model.init(jax.random.PRNGKey(0))

# sequence reversal: decoder input is the shifted reversed sequence
bs = 4 * dp * M
enc = jax.random.randint(jax.random.PRNGKey(1), (bs, SEQ), 2, VOCAB)
labels = enc[:, ::-1]
dec = jnp.concatenate([jnp.ones((bs, 1), enc.dtype), labels[:, :-1]], 1)
batch = split_batch_into_microbatches(
    {"enc_tokens": enc, "dec_tokens": dec, "labels": labels}, M)
bspec = {k: P(None, "data") for k in batch}

opt = FusedAdam(lr=2e-3)
step = make_train_step(model.make_loss_fn(), opt, mesh, model.spec(), bspec,
                       opt_state_spec=opt.state_spec(params, model.spec()))
opt_state = opt.init(params)

losses = []
for i in range(60):
    params, opt_state, loss = step(params, opt_state, batch,
                                   jax.random.PRNGKey(i))
    losses.append(float(loss))
    if i % 10 == 0 or i == 59:
        print(f"iter {i:3d} loss {losses[-1]:.4f}", flush=True)

assert np.isfinite(losses).all()
assert losses[-1] < losses[0] * 0.5, f"no convergence: {losses[0]:.3f} -> {losses[-1]:.3f}"
print("CONVERGED OK (decoder learned to read the encoder through the "
      "pipelined cross-attention)")
parallel_state.destroy_model_parallel()
