"""Text generation with KV caches — greedy and sampled decoding.

The reference ships no inference utilities; this demonstrates the
exceeds-parity generation stack (``apex_tpu.models.generation``): one
batched prefill, then a jitted ``lax.scan`` decode loop, with a GQA model
(grouped K/V heads -> grouped caches) to show the memory win.

Run (from the repo root): PYTHONPATH=. python examples/generate.py
"""

import time

import jax
import jax.numpy as jnp

from apex_tpu.models import GPTModel, TransformerConfig, generate


def main():
    cfg = TransformerConfig(
        num_layers=4, hidden_size=256, num_attention_heads=8,
        num_query_groups=2,               # GQA: caches hold 2 heads, not 8
        vocab_size=512, max_position_embeddings=256,
        hidden_dropout=0.0, attention_dropout=0.0,
        compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))

    prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 512)

    t0 = time.perf_counter()
    greedy = generate(model, params, prompt, max_new_tokens=48)
    greedy.block_until_ready()
    t1 = time.perf_counter()
    sampled = generate(model, params, prompt, max_new_tokens=48,
                       temperature=0.8, top_k=40,
                       rng=jax.random.PRNGKey(7))
    sampled.block_until_ready()
    t2 = time.perf_counter()

    n_new = 4 * 48
    print(f"greedy : {greedy.shape} in {t1-t0:.2f}s "
          f"({n_new/(t1-t0):,.0f} tok/s incl. compile)")
    print(f"sampled: {sampled.shape} in {t2-t1:.2f}s "
          f"({n_new/(t2-t1):,.0f} tok/s incl. compile — the sampling "
          f"branch retraces)")
    same = bool(jnp.all(greedy == sampled))
    print(f"greedy == sampled: {same} (expected False for temperature>0)")
    kv_heads = cfg.kv_heads
    print(f"KV cache heads per layer: {kv_heads} "
          f"(vs {cfg.num_attention_heads} query heads — "
          f"{cfg.num_attention_heads // kv_heads}x smaller cache)")
    print("GENERATE OK")


if __name__ == "__main__":
    main()
