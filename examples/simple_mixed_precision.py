"""User-style demo: amp O2 mixed precision + FusedAdam + fused LayerNorm
training a small MLP regression on the real TPU."""
import jax, jax.numpy as jnp
import apex_tpu
from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam
from apex_tpu.ops import fused_layer_norm_affine, scaled_softmax

print("devices:", jax.devices(), "| apex_tpu", apex_tpu.__version__)

amp_state = amp.initialize("O2")
policy = amp_state.policy
scaler, sstate = amp_state.scaler, amp_state.scaler_states[0]

key = jax.random.PRNGKey(0)
k1, k2, k3 = jax.random.split(key, 3)
H = 256
params = {
    "w1": jax.random.normal(k1, (64, H)) * 0.1,
    "ln_w": jnp.ones((H,)), "ln_b": jnp.zeros((H,)),
    "w2": jax.random.normal(k2, (H, 1)) * 0.1,
}
params = policy.cast_to_param(params)          # bf16 model params (O2)
opt = FusedAdam(lr=1e-2, master_weights=True)  # fp32 master in opt state
opt_state = opt.init(params)

x = jax.random.normal(k3, (512, 64))
y_true = jnp.sin(x.sum(axis=1, keepdims=True))

def model(p, x):
    h = x.astype(jnp.bfloat16) @ p["w1"]
    h = fused_layer_norm_affine(h, p["ln_w"], p["ln_b"], H)  # Pallas kernel
    h = jax.nn.relu(h)
    return (h.astype(jnp.bfloat16) @ p["w2"]).astype(jnp.float32)

@jax.jit
def step(params, opt_state, sstate, x, y):
    def loss_fn(p):
        pred = model(p, x)
        loss = jnp.mean((pred - y) ** 2)
        return amp.scale_loss(loss, sstate), loss
    grads, loss = jax.grad(loss_fn, has_aux=True)(params)
    grads, found_inf = scaler.unscale(grads, sstate)
    new_params, new_opt = opt.step(grads, params, opt_state, found_inf=found_inf)
    return new_params, new_opt, scaler.update(sstate, found_inf), loss

for i in range(30):
    params, opt_state, sstate, loss = step(params, opt_state, sstate, x, y_true)
    if i % 10 == 0 or i == 29:
        print(f"iter {i:3d} loss {float(loss):.5f} scale {float(sstate.loss_scale):.0f} dtype {params['w1'].dtype}")

# sanity: softmax kernel on TPU inside the same program
probs = scaled_softmax(jax.random.normal(key, (4, 8, 128)), 0.125)
print("softmax rows sum to", float(probs.sum(-1).mean()))
print("final loss:", float(loss))
assert float(loss) < 0.1, "did not converge"
print("CONVERGED OK")
