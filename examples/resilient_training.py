"""User-style demo: the resilience layer end to end — dynamic loss scaling
+ FusedAdam under `run_training`, with a scripted NaN-gradient burst that
trips the watchdog, rolls training back to the last good checkpoint at a
decayed loss scale, and still converges. Ctrl-free: faults come from the
deterministic injector, so the run behaves identically everywhere.

The run also measures itself: a MetricsRegistry with a JSONL sink rides
along (ResilienceConfig.metrics), and at the end the same report that
`python -m apex_tpu.monitor <run.jsonl>` prints — counters reconciling
with TrainingResult.telemetry, step-time p50/p95, throughput/MFU,
incident timeline — is rendered from the log."""
import os
import tempfile

import jax
import jax.numpy as jnp

import apex_tpu
from apex_tpu.amp.scaler import LossScaler
from apex_tpu.observability import (JsonlSink, MetricsRegistry,
                                    build_report, render_report)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import (ResilienceConfig, make_train_state,
                                 make_resilient_train_step, run_training)
from apex_tpu.testing_faults import FaultInjector
from apex_tpu.utils.flops import peak_flops_per_chip

print("devices:", jax.devices(), "| apex_tpu", apex_tpu.__version__)

H = 128
key = jax.random.PRNGKey(0)
k1, k2 = jax.random.split(key)
params = {
    "w1": jax.random.normal(k1, (64, H)) * 0.1,
    "w2": jax.random.normal(k2, (H, 1)) * 0.1,
}
opt = FusedAdam(lr=1e-2, master_weights=True)
scaler = LossScaler("dynamic", init_scale=2.0 ** 12, scale_window=500)


def loss_fn(p, batch, rng):
    pred = batch["x"] @ p["w1"] @ p["w2"]
    return jnp.mean((pred - batch["y"]) ** 2)


TEACHER = jax.random.normal(jax.random.PRNGKey(7), (64, 1)) * 0.3


def batch_fn(step):  # pure function of step -> replayable after rollback
    k = jax.random.PRNGKey(step)
    x = jax.random.normal(k, (256, 64))
    return {"x": x, "y": x @ TEACHER}


step_fn = make_resilient_train_step(loss_fn, opt, scaler)
state = make_train_state(params, opt.init(params), scaler.init())

# a transient fault: train-step calls 30..35 produce NaN gradients
injector = FaultInjector(nan_grad_calls=range(30, 36))

with tempfile.TemporaryDirectory() as tmp:
    run_log = os.path.join(tmp, "run.jsonl")
    registry = MetricsRegistry([JsonlSink(run_log)])
    cfg = ResilienceConfig(
        save_interval_steps=20,       # checkpoint cadence (sharded, atomic commit)
        poll_interval_steps=5,        # watchdog device->host sync cadence
        max_consecutive_skips=4,      # divergence = 4 skipped steps in a row
        max_rollbacks=2,              # retry budget before TrainingDiverged
        rollback_scale_decay=4.0,     # retry at loss_scale/4
        save_backoff_base=0.2,        # checkpoint-save retry backoff
        metrics=registry,             # step metrics + incident events
        tokens_per_step=256,          # enables tokens/s
        model_flops_per_step=6.0 * (64 * 128 + 128),  # 6N for the 2-layer MLP
        peak_flops=peak_flops_per_chip() or 1e12,     # CPU: nominal peak
    )
    result = run_training(
        step_fn, state, batch_fn, num_steps=300,
        rng=jax.random.PRNGKey(42),
        checkpoint_dir=os.path.join(tmp, "ckpts"),
        config=cfg, fault_injector=injector)
    registry.close()
    # same output as `python -m apex_tpu.monitor <run.jsonl>`
    report = build_report(run_log)
    print(render_report(report))
    assert report["counters"] == result.telemetry  # two ledgers, one truth
    assert report["step_time_s"]["p50"] > 0 and report["mfu"]["p50"] > 0

print(f"status={result.status} steps={result.steps_completed} "
      f"rollbacks={result.rollbacks}")
print("telemetry:", result.telemetry)
final = [h for h in result.history if not h["skipped"]][-1]
print(f"final loss {final['loss']:.5f} at step {final['step']}, "
      f"loss_scale {float(result.state['scaler'].loss_scale):.0f}")
assert result.status == "completed"
assert result.rollbacks == 1          # the NaN burst cost one rollback
assert final["loss"] < 0.08, "did not converge"
print("RECOVERED + CONVERGED OK")
