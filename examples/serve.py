"""Continuous-batching serving demo — mixed traffic, faults, recovery.

Act 1 drives :class:`apex_tpu.serving.InferenceEngine` (docs/serving.md)
with requests of very different shapes — short greedy, long sampled, a
deadline-bounded request, and a fault-injected mid-flight cancellation —
while a JSONL metrics registry records one ``kind="request"`` row per
terminal request, and verifies the engine's two structural invariants:
token-exact greedy agreement with per-request ``generate()`` and a
decode step that never retraced.

Act 2 is the robustness demo (docs/serving.md#robustness): an injected
decode exception CRASHES the engine mid-flight; the
:class:`~apex_tpu.serving.EngineSupervisor` rebuilds it and re-prefills
every in-flight request from prompt + tokens already generated — the
final outputs are token-exact as if nothing had happened, and the run
report's incident timeline shows the restart/recovery events
reconciling with the registry counters.

Act 3 is the fleet demo (docs/serving.md#fleet): a 2-replica
:class:`~apex_tpu.serving.ReplicaFleet` serves the same traffic while
replica 0's engine crashes (supervised in-place recovery) AND replica 1
takes a mid-run DRAINING restart (its in-flight work migrates
token-exact to replica 0, it rebuilds, health-probes, rejoins) — zero
dropped requests, every output token-exact, and the run report's fleet
section reconciles key-for-key with the counters.

Run (from the repo root): PYTHONPATH=. python examples/serve.py
"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models import GPTModel, TransformerConfig, generate
from apex_tpu.observability import JsonlSink, MetricsRegistry
from apex_tpu.observability.report import (
    FLEET_INCIDENT_COUNTERS,
    SERVING_INCIDENT_COUNTERS,
    build_report,
    render_report,
)
from apex_tpu.serving import (
    EngineConfig,
    EngineSupervisor,
    FleetConfig,
    InferenceEngine,
    ReplicaFleet,
    Request,
    SamplingParams,
    SchedulerConfig,
)
from apex_tpu.testing_faults import ServingFaultInjector


def main():
    cfg = TransformerConfig(
        num_layers=4, hidden_size=256, num_attention_heads=8,
        vocab_size=512, max_position_embeddings=256,
        hidden_dropout=0.0, attention_dropout=0.0)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 512, size=n).tolist()
               for n in (6, 24, 11, 40, 3, 17)]
    requests = [
        Request(prompt=prompts[0], max_new_tokens=24),
        Request(prompt=prompts[1], max_new_tokens=48,
                sampling=SamplingParams(temperature=0.8, top_k=40, seed=1)),
        Request(prompt=prompts[2], max_new_tokens=12),
        Request(prompt=prompts[3], max_new_tokens=64),   # cancelled below
        Request(prompt=prompts[4], max_new_tokens=8, deadline_s=120.0),
        Request(prompt=prompts[5], max_new_tokens=32),
    ]
    victim = requests[3].request_id

    log_path = os.path.join(tempfile.mkdtemp(prefix="apex_tpu_serve_"),
                            "serving.jsonl")
    registry = MetricsRegistry([JsonlSink(log_path)])
    engine = InferenceEngine(
        model, params,
        EngineConfig(max_slots=4, max_len=128,
                     scheduler=SchedulerConfig(max_queue=8)),
        metrics=registry)

    def inject_fault(eng, tick):
        # fault injection: a client disappears mid-generation — the slot
        # must come back and everyone else must be unaffected
        if tick == 4:
            assert eng.cancel(victim)
            print(f"[tick {tick}] injected cancel of request {victim}")

    results = engine.serve(requests, on_tick=inject_fault)
    engine.close()

    print(f"\n{'id':>3} {'reason':<10} {'prompt':>6} {'new':>4} "
          f"{'queue_s':>8} {'total_s':>8}")
    for r in results:
        print(f"{r.request_id:>3} {r.finish_reason:<10} {r.prompt_len:>6} "
              f"{r.new_tokens:>4} {r.queue_s:>8.3f} {r.total_s:>8.3f}")

    # invariant 1: greedy results are token-exact vs per-request generate()
    for req, res in zip(requests, results):
        if req.sampling.temperature > 0 or res.finish_reason != "length":
            continue
        ref = generate(model, params, jnp.asarray([req.prompt], jnp.int32),
                       req.max_new_tokens, max_len=128)
        assert res.tokens == np.asarray(
            ref[0, req.prompt_len:]).tolist(), req.request_id
    # invariant 2: arrivals/retirements never retraced the decode step
    assert engine.decode_retraces == 0
    cancelled = next(r for r in results if r.request_id == victim)
    assert cancelled.finish_reason == "cancelled"
    print(f"\ngreedy outputs token-exact vs generate(); decode retraces: "
          f"{engine.decode_retraces}; prefill compiles: "
          f"{engine.prefill_compiles} (buckets: {engine.buckets})")

    # ---- act 2: engine crash + supervised recovery ----------------------
    print("\n=== act 2: injected engine crash, supervised recovery ===")
    crash_reqs = [Request(prompt=prompts[0], max_new_tokens=16),
                  Request(prompt=prompts[2], max_new_tokens=24)]
    # decode call 3 raises inside the jitted step — with a bare engine
    # this would kill every in-flight request and leak the slots
    injector = ServingFaultInjector(decode_raise_calls={3})
    supervisor = EngineSupervisor(
        model, params, EngineConfig(max_slots=4, max_len=128),
        metrics=registry, faults=injector)
    with supervisor:
        recovered = supervisor.serve(crash_reqs)
    assert supervisor.restarts == 1
    for req, res in zip(crash_reqs, recovered):
        ref = generate(model, params, jnp.asarray([req.prompt], jnp.int32),
                       req.max_new_tokens, max_len=128)
        assert res.tokens == np.asarray(
            ref[0, req.prompt_len:]).tolist(), req.request_id
        print(f"request {req.request_id}: {res.finish_reason}, "
              f"{res.new_tokens} tokens — token-exact across the restart")
    counters = registry.counters()
    print(f"engine_restarts={counters['engine_restarts']} "
          f"requests_recovered={counters['requests_recovered']} "
          f"tick_failures={counters['tick_failures']}")

    # ---- act 3: a replica fleet rides out a crash AND a drain ----------
    print("\n=== act 3: 2-replica fleet — replica crash + draining "
          "restart, zero dropped requests ===")
    fleet_reqs = [Request(prompt=prompts[i % len(prompts)],
                          max_new_tokens=12 + 2 * i) for i in range(5)]
    fleet = ReplicaFleet(
        model, params, EngineConfig(max_slots=4, max_len=128),
        fleet=FleetConfig(n_replicas=2), metrics=registry,
        # replica 0's decode crashes mid-run; its supervisor rebuilds the
        # engine and recovers in place — the fleet never notices
        faults={0: ServingFaultInjector(decode_raise_calls={4})})
    drained = []

    def drain_mid_run(fl, tick):
        # fleet-level fault injection: a planned rebuild of replica 1
        # while traffic is in flight — its work migrates to replica 0
        if tick == 3 and not drained and \
                fl.replica_states[1] == "active":
            fl.drain_restart(1)
            drained.append(tick)
            print(f"[tick {tick}] draining restart of replica 1 "
                  f"(states: {fl.replica_states})")

    with fleet:
        fleet_results = fleet.serve(fleet_reqs, on_tick=drain_mid_run)
    assert drained, "drain never fired"
    for req, res in zip(fleet_reqs, fleet_results):
        assert res.finish_reason == "length", (res.request_id,
                                               res.finish_reason)
        ref = generate(model, params, jnp.asarray([req.prompt], jnp.int32),
                       req.max_new_tokens, max_len=128)
        assert res.tokens == np.asarray(
            ref[0, req.prompt_len:]).tolist(), req.request_id
        print(f"request {req.request_id}: replica={res.replica_id} "
              f"{res.new_tokens} tokens — token-exact")
    counters = registry.counters()
    print(f"fleet_dispatches={counters['fleet_dispatches']} "
          f"requests_migrated={counters['requests_migrated']} "
          f"replica_rebuilds={counters['replica_rebuilds']} — "
          f"zero dropped requests")

    print(f"\n=== run report ({log_path}) ===")
    report = build_report(log_path)
    print(render_report(report))
    # incident counts reconcile key-for-key with the registry counters
    inc = report["serving_incidents"]
    for event, counter in SERVING_INCIDENT_COUNTERS.items():
        assert inc["counts"].get(event, 0) == report["counters"][counter]
    # ... and so does the fleet section
    fl = report["fleet"]
    for event, counter in FLEET_INCIDENT_COUNTERS.items():
        assert fl["counts"].get(event, 0) == report["counters"][counter]
    assert sum(v for k, v in fl["dispatches"].items()
               if k != "fleet_dispatches") == \
        report["counters"]["fleet_dispatches"]


if __name__ == "__main__":
    main()
