"""DCGAN mixed-precision training — parity with the reference's second
example (``/root/reference/examples/dcgan/main_amp.py``).

The apex capability exercised there is *multiple models/optimizers/losses
under one amp context* (``amp.initialize(num_losses=3)``, one ``scale_loss``
per loss with its own scaler). Here: two functional nets, two FusedAdam
optimizers, three dynamic loss-scaler states (errD_real, errD_fake, errG)
from one ``amp.initialize(num_losses=3)`` call, trained on synthetic images.

Usage: ``PYTHONPATH=/root/repo:/root/.axon_site python examples/dcgan_amp.py``
"""

import argparse
import time

import jax
import jax.numpy as jnp

from apex_tpu import amp
from apex_tpu.models import DCGANConfig, Discriminator, Generator
from apex_tpu.optimizers import FusedAdam


def bce_with_logits(logit, target):
    return jnp.mean(jnp.maximum(logit, 0) - logit * target
                    + jnp.log1p(jnp.exp(-jnp.abs(logit))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--nz", type=int, default=100)
    ap.add_argument("--lr", type=float, default=2e-4)
    args = ap.parse_args()

    # fp16-style dynamic scaling exercised on all three losses (the bf16
    # default wouldn't need it; the flow is the capability under test)
    amp_state = amp.initialize("O2", num_losses=3)
    scaler = amp_state.scaler
    sstates = amp_state.scaler_states
    cfg = DCGANConfig(latent_dim=args.nz, compute_dtype=jnp.bfloat16)
    gen, disc = Generator(cfg), Discriminator(cfg)
    gp, gs = gen.init(jax.random.PRNGKey(0))
    dp_, ds = disc.init(jax.random.PRNGKey(1))
    g_opt = FusedAdam(lr=args.lr, betas=(0.5, 0.999), master_weights=True)
    d_opt = FusedAdam(lr=args.lr, betas=(0.5, 0.999), master_weights=True)
    g_os, d_os = g_opt.init(gp), d_opt.init(dp_)

    @jax.jit
    def train_step(gp, gs, dp_, ds, g_os, d_os, sstates, rng):
        k_z, k_data, k_z2 = jax.random.split(rng, 3)
        real = jnp.tanh(jax.random.normal(
            k_data, (args.batch, 64, 64, 3)))          # synthetic "images"
        z = jax.random.normal(k_z, (args.batch, args.nz))
        s_real, s_fake, s_g = sstates

        # --- D step: two separately-scaled losses (reference lines
        # `with amp.scale_loss(errD_real, optimizerD, loss_id=0)` etc.)
        def d_loss_real(dp_):
            logit, _ = disc.apply(dp_, ds, real, train=True)
            return bce_with_logits(logit, jnp.ones(args.batch))

        def d_loss_fake(dp_):
            fake, _ = gen.apply(gp, gs, z, train=True)
            logit, new_ds = disc.apply(dp_, ds, fake, train=True)
            return bce_with_logits(logit, jnp.zeros(args.batch)), new_ds

        lr_scaled, g_real = jax.value_and_grad(
            lambda p: scaler.scale(d_loss_real(p), s_real))(dp_)
        lr_raw = lr_scaled / s_real.loss_scale

        def d_fake_scaled(p):
            loss, new_ds = d_loss_fake(p)
            return scaler.scale(loss, s_fake), new_ds

        (lf_scaled, new_ds), g_fake = jax.value_and_grad(
            d_fake_scaled, has_aux=True)(dp_)
        lf_raw = lf_scaled / s_fake.loss_scale

        g_real, inf_real = scaler.unscale(g_real, s_real)
        g_fake, inf_fake = scaler.unscale(g_fake, s_fake)
        d_grads = jax.tree.map(lambda a, b: a + b, g_real, g_fake)
        d_inf = jnp.logical_or(inf_real, inf_fake)
        new_dp, new_d_os = d_opt.step(d_grads, dp_, d_os, found_inf=d_inf)
        s_real = scaler.update(s_real, inf_real)
        s_fake = scaler.update(s_fake, inf_fake)

        # --- G step (loss_id=2)
        def g_loss(gp):
            fake, new_gs = gen.apply(gp, gs, z, train=True)
            logit, _ = disc.apply(new_dp, ds, fake, train=True)
            return bce_with_logits(logit, jnp.ones(args.batch)), new_gs

        def g_loss_scaled(p):
            loss, new_gs = g_loss(p)
            return scaler.scale(loss, s_g), new_gs

        (lg_scaled, new_gs), g_g = jax.value_and_grad(
            g_loss_scaled, has_aux=True)(gp)
        lg_raw = lg_scaled / s_g.loss_scale
        g_g, inf_g = scaler.unscale(g_g, s_g)
        new_gp, new_g_os = g_opt.step(g_g, gp, g_os, found_inf=inf_g)
        s_g = scaler.update(s_g, inf_g)

        errD = lr_raw + lf_raw
        return (new_gp, new_gs, new_dp, new_ds, new_g_os, new_d_os,
                [s_real, s_fake, s_g], errD, lg_raw)

    rng = jax.random.PRNGKey(42)
    t0 = time.perf_counter()
    for it in range(args.iters):
        rng, sub = jax.random.split(rng)
        (gp, gs, dp_, ds, g_os, d_os, sstates, errD, errG) = train_step(
            gp, gs, dp_, ds, g_os, d_os, sstates, sub)
        if it % 5 == 0:
            print(f"[{it:3d}/{args.iters}] Loss_D {float(errD):7.4f} "
                  f"Loss_G {float(errG):7.4f} "
                  f"scales {[int(s.loss_scale) for s in sstates]}")
    dt = time.perf_counter() - t0
    print(f"done: {args.iters * args.batch / dt:.1f} imgs/sec; "
          f"finite: D={bool(jnp.isfinite(errD))} G={bool(jnp.isfinite(errG))}")


if __name__ == "__main__":
    main()
