"""Minimal distributed data-parallel training.

Parity with the reference's ``examples/simple/distributed/
distributed_data_parallel.py`` (a linear model trained under apex DDP,
launched with one process per GPU): here one process drives all devices —
a ``data``-axis mesh, per-rank autodiff under ``shard_map``, and a gradient
``pmean`` standing in for DDP's bucketed allreduce.

Run on real chips, or on a virtual mesh:
``XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \\
  PYTHONPATH=/root/repo python examples/simple_distributed.py``
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.optimizers import FusedSGD

devices = jax.devices()
mesh = Mesh(np.array(devices), ("data",))
ndev = len(devices)
print(f"world size: {ndev}")

key = jax.random.PRNGKey(0)
params = {"w": jax.random.normal(key, (8, 1)) * 0.1, "b": jnp.zeros((1,))}
opt = FusedSGD(lr=0.1)
opt_state = opt.init(params)

x = jax.random.normal(jax.random.PRNGKey(1), (32 * ndev, 8))
y = x @ jnp.arange(1.0, 9.0).reshape(8, 1) + 0.5


def per_rank(params, opt_state, x, y):
    def loss_fn(p):
        return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    grads = jax.lax.pmean(grads, "data")     # DDP allreduce
    loss = jax.lax.pmean(loss, "data")
    params, opt_state = opt.step(grads, params, opt_state)
    return params, opt_state, loss


step = jax.jit(jax.shard_map(
    per_rank, mesh=mesh,
    in_specs=(P(), P(), P("data"), P("data")),
    out_specs=(P(), P(), P()), check_vma=False))

for it in range(50):
    params, opt_state, loss = step(params, opt_state, x, y)
    if it % 10 == 0:
        print(f"iter {it:3d} loss {float(loss):.6f}")
print("final loss:", float(loss))
assert float(loss) < 1e-3, "did not converge"
print("CONVERGED OK")
